"""Host/device telemetry.

TPU-native replacement for the reference's GPU memory manager
(``clear_memory``/``get_memory_usage`` — compare_instruct_models.py:66-101,
run_base_vs_instruct_100q.py:245-262): JAX arrays are freed by dropping
references (no ``empty_cache`` dance), so the useful pieces are RAM/disk
telemetry, per-device HBM stats from ``device.memory_stats()``, and explicit
buffer donation in the jitted steps (handled in runtime/).
"""

from __future__ import annotations

import gc
import math
import shutil
import threading
import time
from typing import Dict, List, Optional, Tuple

# ---------------------------------------------------------------------------
# Fault-event log (runtime/faults.py)
#
# Every recovery the fault-tolerance layer performs — an engine batch
# stepped down after OOM, a transient error retried, a preemption flush —
# degrades or perturbs the operating point the run reports, so it must stay
# auditable: a sweep that silently completed at batch 160 instead of 320 is
# a different measurement.  Events accumulate here (bounded ring) and are
# readable/drainable by benchmarks, tests, and reports.
# ---------------------------------------------------------------------------

_FAULT_EVENTS: List[Dict] = []
_FAULT_EVENTS_CAP = 1000
_FAULT_LISTENERS: List = []   # called with each event as it is recorded —
                              # the obs/ flight recorder's trigger path
# Faults are recorded from the scheduler loop, supervisor workers,
# watchdogs, AND API callers at once (graftlint G09 fingerprints
# 'G09/utils/telemetry.py/_FAULT_EVENTS.append(event)' and the
# listener check-then-append): the append+trim pair and the listener
# list need one lock.  Listeners are invoked OUTSIDE it — a listener
# that blocks (flight-recorder dump) must never stall every other
# fault-recording thread, and holding a telemetry lock into listener
# code would mint a telemetry->flight lock-order edge (G10).
_FAULTS_LOCK = threading.Lock()

#: The CLOSED registry of fault-event kinds.  Every ``record_fault``
#: literal in the codebase must name a member (graftlint G06 enforces
#: this statically), and fault LISTENERS — the flight recorder's
#: TRIGGER_KINDS, dashboards keyed on kind strings — can enumerate it
#: instead of grepping: a typo'd kind would otherwise fork a new event
#: stream no listener ever matches.  Grouped by the layer that records.
FAULT_KINDS = frozenset({
    # runtime/faults.py + runtime/engine.py (PR 1 fault layer)
    "engine_oom_backoff", "sweep_oom_skip", "sweep_oom_backoff",
    "transient_retry", "transient_exhausted", "preempted",
    # runtime/strict.py + scoring (measurement-integrity events)
    "blocked_transfer", "nan_logits", "packed_error_rows",
    # serve/ scheduler + obs/flight.py watchdog
    "serve_oom_split", "watchdog_stall",
    # serve/supervisor.py fleet self-healing (ISSUE 16)
    "pool_replica_crash", "pool_replica_wedged",
    "pool_replica_quarantined", "pool_poison_request", "breaker_open",
})


def add_fault_listener(fn) -> None:
    """Register ``fn(event_dict)`` to run on every :func:`record_fault`
    (idempotent per callable).  Listeners must be fast and must not
    raise; a raising listener is swallowed so the fault path — which is
    already handling an error — can never be broken by its observer."""
    with _FAULTS_LOCK:
        # check-then-append must be one atomic step, or two threads
        # registering the same listener double-deliver every event
        if fn not in _FAULT_LISTENERS:
            _FAULT_LISTENERS.append(fn)


def remove_fault_listener(fn) -> None:
    with _FAULTS_LOCK:
        try:
            _FAULT_LISTENERS.remove(fn)
        except ValueError:
            pass


def record_fault(kind: str, **info) -> Dict:
    """Append one fault-recovery event ({kind, time, **info}); returns it."""
    event = {"kind": str(kind), "time": time.time(), **info}
    with _FAULTS_LOCK:
        _FAULT_EVENTS.append(event)
        if len(_FAULT_EVENTS) > _FAULT_EVENTS_CAP:
            del _FAULT_EVENTS[: len(_FAULT_EVENTS) - _FAULT_EVENTS_CAP]
        listeners = list(_FAULT_LISTENERS)
    for fn in listeners:    # outside the lock: see _FAULTS_LOCK comment
        try:
            fn(event)
        except Exception:  # a listener can never break the fault path
            pass
    return event


def fault_events(kind: Optional[str] = None) -> List[Dict]:
    """Recorded fault events, newest last (optionally filtered by kind)."""
    with _FAULTS_LOCK:
        if kind is None:
            return list(_FAULT_EVENTS)
        return [e for e in _FAULT_EVENTS if e["kind"] == kind]


def clear_fault_events() -> None:
    with _FAULTS_LOCK:
        _FAULT_EVENTS.clear()


# ---------------------------------------------------------------------------
# Performance counters (runtime/engine.py prefix-KV reuse, compile-cache
# warmup, host pipeline)
#
# Monotonic named counters for the hot-path reuse machinery: how many
# suffix legs rode an already-prefilled prefix cache (``prefix_hit``) vs
# paid a fresh prefix prefill (``prefix_miss``), how many warmup programs
# came out of the persistent XLA compilation cache (``compile_cache_hit`` /
# ``compile_cache_miss``), and how long the device-feed loop sat idle
# waiting for background host tokenization (``host_overlap_idle_ms`` /
# ``host_overlap_chunks``).  Benchmarks and the perf smoke test read these
# to prove the reuse paths actually engaged; a sweep that silently fell
# back to unfused scoring is a different measurement.
#
# Strict mode (runtime/strict.py, LLM_INTERP_STRICT=1) adds two more:
# ``recompile_events`` — one per XLA compilation seen by the log_compiles
# sentry (a warm repeat must hold this flat; growth means a shape or
# plan-key leak) — and ``blocked_transfers`` — one per implicit transfer
# the armed jax.transfer_guard rejected inside a scoring pipeline (a clean
# operating point is provable as blocked_transfers == 0).  bench.py
# --strict reports both in its JSON record.
#
# The KV-cache quantization / chunked-prefill layer (runtime/engine
# ._prefill) adds: ``kv_cache_bytes_saved`` — HBM an int8-quantized KV
# cache does NOT pin vs its bf16 layout, accumulated per prefill from
# static shapes (a sweep that silently fell back to bf16 shows 0) — and
# ``prefill_chunks`` — chunked-prefill programs launched (chunk 0's
# ordinary prefill plus each suffix-extension replay).
#
# The serve/ scheduler (continuous-batching request coalescing) adds:
# ``serve_enqueued`` — requests admitted to the queue; ``serve_completed``
# — result rows delivered to futures; ``serve_rejected_full`` — typed
# QueueFull backpressure rejections; ``serve_rejected_deadline`` —
# deadline-expired requests rejected with a typed error (never silently
# dropped); ``serve_batches`` / ``serve_batch_rows`` — micro-batches
# launched and the rows they coalesced (rows/batches = achieved batching
# factor); ``serve_oom_splits`` — micro-batches split down the PR-1
# ladder and re-queued after a device OOM; ``serve_failed`` — requests
# failed with a non-recoverable error.  Queue-depth and latency
# DISTRIBUTIONS go through the bounded sample rings below
# (``serve_queue_depth``, ``serve_queue_wait_ms``, ``serve_latency_ms``)
# so percentiles are reportable without unbounded growth.
#
# Counters answer "how many / how much"; WHERE THE TIME GOES is the obs/
# span tracer's job (phase-tagged spans: host_tokenize, host_prep,
# dispatch, prefill, extend_prefill, decode, pooled_decode, d2h_fetch,
# host_rows, host_write, serve_* — README "Span / phase names").
# ---------------------------------------------------------------------------

_COUNTERS: Dict[str, float] = {}
_COUNTERS_LOCK = threading.Lock()  # the host prefetcher records from its
                                   # worker thread


def record_counter(name: str, value: float = 1) -> None:
    """Add ``value`` to the named monotonic counter (creates it at 0)."""
    with _COUNTERS_LOCK:
        _COUNTERS[name] = _COUNTERS.get(name, 0) + value


def counter(name: str) -> float:
    """Current value of one counter (0 when never recorded)."""
    with _COUNTERS_LOCK:
        return _COUNTERS.get(name, 0)


def counters() -> Dict[str, float]:
    """Snapshot of all counters."""
    with _COUNTERS_LOCK:
        return dict(_COUNTERS)


def clear_counters() -> None:
    with _COUNTERS_LOCK:
        _COUNTERS.clear()


def counters_since(snapshot: Dict[str, float]) -> Dict[str, float]:
    """Per-counter delta vs an earlier :func:`counters` snapshot.

    The counters are process-global monotones; callers measuring one
    phase (a bench repeat, a strict-mode sweep, a test) snapshot before,
    run, and diff — ``clear_counters`` would destroy concurrent readers'
    baselines.  Counters absent from ``snapshot`` count from 0; counters
    that only exist in ``snapshot`` are omitted (monotones cannot have
    shrunk).

    Robust to a :func:`clear_counters` between snapshot and read: a
    counter whose current value sits BELOW its snapshot was necessarily
    cleared and restarted, and reports its current value — never a
    negative number a report would subtract throughput with.  A clear
    the values cannot reveal (the counter re-accumulated PAST its
    snapshot) still reports the ordinary difference, so after a
    mid-window clear every delta is a LOWER bound on what was actually
    recorded; callers that clear mid-measurement get honest-but-
    conservative numbers, not corrupt ones."""
    now = counters()
    return {name: (value - snapshot.get(name, 0)
                   if value >= snapshot.get(name, 0) else value)
            for name, value in now.items()
            if value != snapshot.get(name, 0)}


# ---------------------------------------------------------------------------
# Bounded sample rings (serve/ queue-depth and latency percentiles)
#
# Counters are monotones; distributions (how long did a request WAIT, how
# deep was the queue WHEN it launched) need samples.  Each named ring keeps
# the most recent ``cap`` values (default _SAMPLES_CAP_DEFAULT,
# configurable per ring via :func:`set_sample_cap`) — enough for stable
# p50/p90/p99 over a serving window, bounded so a week-long server never
# grows host memory.
#
# TRUNCATION SEMANTICS (the silent-window footgun, fixed): a ring holds
# only its most recent ``cap`` samples, so a percentile over a run longer
# than the cap reflects ONLY THE TAIL — p99 of the last 4096 requests,
# not of the whole sweep.  Reports must therefore carry ``sample_total``
# (ever recorded) next to ``sample_count`` (retained); when total >
# retained the window was truncated and the percentile is a tail
# statistic.  :func:`sample_ring_report` packages exactly that, and the
# serve replay / strict reports embed it.  Callers that need whole-run
# percentiles raise the cap up front (``set_sample_cap``).
# ---------------------------------------------------------------------------

_SAMPLES: Dict[str, List[float]] = {}
_SAMPLE_TOTALS: Dict[str, int] = {}   # ever-recorded count per ring, so a
                                      # phase can be measured as "the last
                                      # (total_now - total_then) samples"
_SAMPLES_CAP_DEFAULT = 4096
_SAMPLE_CAPS: Dict[str, int] = {}     # per-ring overrides (set_sample_cap)
_SAMPLES_CAP = _SAMPLES_CAP_DEFAULT   # back-compat alias (default cap)


def set_sample_cap(cap: int, name: Optional[str] = None) -> None:
    """Configure ring capacity — for ``name`` only, or the default for
    every ring without an override (``name=None``).  Raising a cap takes
    effect on the next record; lowering one trims the ring immediately.
    A long benchmark that wants whole-run percentiles sets this before
    recording; the bound exists so a week-long server cannot grow host
    memory, not to hide history from reports."""
    global _SAMPLES_CAP
    cap = max(1, int(cap))
    with _COUNTERS_LOCK:
        if name is None:
            _SAMPLES_CAP = cap
        else:
            _SAMPLE_CAPS[name] = cap
            ring = _SAMPLES.get(name)
            if ring is not None and len(ring) > cap:
                del ring[: len(ring) - cap]


def sample_cap(name: str) -> int:
    """Effective capacity of the named ring."""
    with _COUNTERS_LOCK:
        return _SAMPLE_CAPS.get(name, _SAMPLES_CAP)


def record_sample(name: str, value: float) -> None:
    """Append one observation to the named bounded sample ring (capacity
    semantics: module docstring above / :func:`set_sample_cap`)."""
    with _COUNTERS_LOCK:
        ring = _SAMPLES.setdefault(name, [])
        ring.append(float(value))
        _SAMPLE_TOTALS[name] = _SAMPLE_TOTALS.get(name, 0) + 1
        cap = _SAMPLE_CAPS.get(name, _SAMPLES_CAP)
        if len(ring) > cap:
            del ring[: len(ring) - cap]


def sample_count(name: str) -> int:
    """Samples currently IN the ring (bounded by the cap)."""
    with _COUNTERS_LOCK:
        return len(_SAMPLES.get(name, ()))


def sample_total(name: str) -> int:
    """Monotonic count of samples EVER recorded to the ring — snapshot it
    before a phase and pass the delta as ``sample_percentiles``'s
    ``last`` to scope percentiles to that phase (clearing the ring would
    destroy concurrent readers' windows, like ``clear_counters`` would)."""
    with _COUNTERS_LOCK:
        return _SAMPLE_TOTALS.get(name, 0)


def sample_percentiles(name: str, pcts: tuple = (50.0, 90.0, 99.0),
                       last: Optional[int] = None) -> Dict[str, float]:
    """``{"p50": ..., "p90": ..., "p99": ...}`` (nearest-rank) over the
    named ring — the whole current window, or only the most recent
    ``last`` samples (one measured phase).  ``{}`` when nothing was
    recorded (or ``last == 0``)."""
    with _COUNTERS_LOCK:
        ring = _SAMPLES.get(name, ())
        if last is not None:
            ring = ring[len(ring) - min(len(ring), max(0, last)):]
        values = sorted(ring)
    if not values:
        return {}
    out = {}
    for p in pcts:
        rank = max(0, min(len(values) - 1,
                          int(round(p / 100.0 * (len(values) - 1)))))
        out[f"p{p:g}"] = values[rank]
    return out


def sample_ring_report(names: Optional[List[str]] = None) -> Dict[str, Dict]:
    """Truncation-visibility report: ``{ring: {total, retained, cap}}``.

    ``total`` is every sample EVER recorded; ``retained`` is what the
    bounded ring still holds (what percentiles are computed over).  When
    ``total > retained`` the window was truncated and any percentile is
    a TAIL statistic — reports embed this block so a p99 can never
    silently masquerade as a whole-run number.  ``names=None`` reports
    every ring that has recorded at least one sample."""
    with _COUNTERS_LOCK:
        keys = list(_SAMPLE_TOTALS) if names is None else list(names)
        return {
            name: {
                "total": _SAMPLE_TOTALS.get(name, 0),
                "retained": len(_SAMPLES.get(name, ())),
                "cap": _SAMPLE_CAPS.get(name, _SAMPLES_CAP),
            }
            for name in keys if _SAMPLE_TOTALS.get(name, 0)
        }


def clear_samples() -> None:
    with _COUNTERS_LOCK:
        _SAMPLES.clear()
        _SAMPLE_TOTALS.clear()


# ---------------------------------------------------------------------------
# Log-bucketed streaming histograms (serve/ load harness latency anatomy)
#
# The sample rings above keep the most recent ``cap`` VALUES, so over a
# long window their percentiles are tail statistics — acceptable for a
# dashboard, fatal for tail-latency measurement, where the one-in-a-
# thousand slow request is exactly what the bounded ring is most likely
# to have evicted.  A histogram inverts the trade: VALUES are quantized
# onto geometric bucket boundaries (each bucket ``HIST_GROWTH``× the
# previous, so any reported quantile overstates the true sample by at
# most ~9%), but COUNTS are exact and nothing is ever evicted — a p99.9
# over a million requests costs the same few hundred ints as over a
# hundred.  This is the structure behind Prometheus ``histogram`` series
# (obs/metrics.py exports these as ``_bucket``/``_sum``/``_count``).
#
# Scoping follows the counter discipline: histograms are process-global
# monotones; callers measuring one phase take :func:`hist_snapshot`
# before, run, and compute percentiles from :func:`hist_since`'s
# bucket-count deltas — never ``clear_hists`` mid-run.
# ---------------------------------------------------------------------------

#: smallest distinguishable value; everything at or below lands in
#: bucket 0 with upper bound HIST_MIN_VALUE (1 microsecond at ms scale).
HIST_MIN_VALUE = 1e-3
#: geometric bucket growth: 2**(1/8) ≈ 1.0905 — any quantile read from
#: bucket upper bounds overstates the true sample value by < 9.05%.
HIST_GROWTH = 2.0 ** 0.125
_LOG_GROWTH = math.log(HIST_GROWTH)

_HIST_COUNTS: Dict[str, Dict[int, int]] = {}
_HIST_META: Dict[str, List[float]] = {}   # [count, sum, min, max]


def hist_bucket_index(value: float) -> int:
    """Bucket index for ``value``: 0 holds everything <= HIST_MIN_VALUE,
    bucket i holds (le(i-1), le(i)] with le(i) = HIST_MIN_VALUE *
    HIST_GROWTH**i."""
    if value <= HIST_MIN_VALUE:
        return 0
    # epsilon guards the exact-boundary case against float log jitter
    return max(0, int(math.ceil(
        math.log(value / HIST_MIN_VALUE) / _LOG_GROWTH - 1e-9)))


def hist_bucket_le(index: int) -> float:
    """Upper (inclusive) bound of bucket ``index``."""
    return HIST_MIN_VALUE * HIST_GROWTH ** index


def record_hist(name: str, value: float) -> None:
    """Add one observation to the named streaming histogram.  Exact
    counts, no eviction — the no-truncation sibling of
    :func:`record_sample`."""
    value = float(value)
    idx = hist_bucket_index(value)
    with _COUNTERS_LOCK:
        counts = _HIST_COUNTS.setdefault(name, {})
        counts[idx] = counts.get(idx, 0) + 1
        meta = _HIST_META.get(name)
        if meta is None:
            _HIST_META[name] = [1, value, value, value]
        else:
            meta[0] += 1
            meta[1] += value
            meta[2] = min(meta[2], value)
            meta[3] = max(meta[3], value)


def hist_count(name: str) -> int:
    """Observations ever recorded to the named histogram (0 if none)."""
    with _COUNTERS_LOCK:
        meta = _HIST_META.get(name)
        return int(meta[0]) if meta else 0


def hist_counts(name: str) -> Dict[int, int]:
    """Copy of the named histogram's {bucket index: exact count}."""
    with _COUNTERS_LOCK:
        return dict(_HIST_COUNTS.get(name, ()))


def hist_snapshot(names: Optional[List[str]] = None) -> Dict[str, Dict]:
    """Snapshot for phase scoping: ``{name: {"counts", "count", "sum"}}``.
    Diff with :func:`hist_since` — the ``counters_since`` discipline."""
    with _COUNTERS_LOCK:
        keys = list(_HIST_COUNTS) if names is None else list(names)
        return {
            name: {
                "counts": dict(_HIST_COUNTS.get(name, ())),
                "count": int(_HIST_META[name][0]) if name in _HIST_META else 0,
                "sum": float(_HIST_META[name][1]) if name in _HIST_META else 0.0,
            }
            for name in keys
        }


def hist_since(snapshot: Dict[str, Dict]) -> Dict[str, Dict]:
    """Per-histogram bucket-count delta vs a :func:`hist_snapshot` —
    ``{name: {"counts", "count", "sum"}}`` covering only observations
    recorded after the snapshot.  Histograms absent from the snapshot
    count from zero; a bucket whose count sits below its snapshot (a
    mid-window :func:`clear_hists`) reports its current count, never a
    negative."""
    now = hist_snapshot()
    out: Dict[str, Dict] = {}
    for name, cur in now.items():
        prev = snapshot.get(name, {"counts": {}, "count": 0, "sum": 0.0})
        counts = {}
        for idx, n in cur["counts"].items():
            base = prev["counts"].get(idx, 0)
            delta = n - base if n >= base else n
            if delta:
                counts[idx] = delta
        count = (cur["count"] - prev["count"]
                 if cur["count"] >= prev["count"] else cur["count"])
        total = (cur["sum"] - prev["sum"]
                 if cur["count"] >= prev["count"] else cur["sum"])
        if count:
            out[name] = {"counts": counts, "count": count, "sum": total}
    return out


def hist_percentiles_from(counts: Dict[int, int],
                          pcts: Tuple = (50.0, 90.0, 99.0, 99.9)
                          ) -> Dict[str, float]:
    """Percentiles over a bucket-count dict (current state or a
    :func:`hist_since` delta): nearest-rank over the exact counts, each
    reported as its bucket's UPPER bound — so a reported quantile is
    >= the true sample value and overstates it by < HIST_GROWTH.
    ``{}`` when the counts are empty."""
    total = sum(counts.values())
    if not total:
        return {}
    ordered = sorted(counts.items())
    out: Dict[str, float] = {}
    for p in pcts:
        rank = min(total, max(1, int(math.ceil(p / 100.0 * total))))
        seen = 0
        for idx, n in ordered:
            seen += n
            if seen >= rank:
                out[f"p{p:g}"] = hist_bucket_le(idx)
                break
    return out


def hist_percentiles(name: str,
                     pcts: Tuple = (50.0, 90.0, 99.0, 99.9)
                     ) -> Dict[str, float]:
    """Percentiles over the named histogram's WHOLE (never-truncated)
    history."""
    return hist_percentiles_from(hist_counts(name), pcts)


def hist_report(names: Optional[List[str]] = None) -> Dict[str, Dict]:
    """Exposition-shaped report for every histogram with at least one
    observation: ``{name: {count, sum, min, max, buckets: [(le, n)]}}``
    with per-bucket (non-cumulative) exact counts sorted by bound."""
    with _COUNTERS_LOCK:
        keys = list(_HIST_COUNTS) if names is None else list(names)
        out = {}
        for name in keys:
            meta = _HIST_META.get(name)
            if not meta or not meta[0]:
                continue
            out[name] = {
                "count": int(meta[0]),
                "sum": float(meta[1]),
                "min": float(meta[2]),
                "max": float(meta[3]),
                "buckets": [(hist_bucket_le(i), n) for i, n in
                            sorted(_HIST_COUNTS.get(name, {}).items())],
            }
        return out


def clear_hists() -> None:
    with _COUNTERS_LOCK:
        _HIST_COUNTS.clear()
        _HIST_META.clear()


def get_memory_usage() -> str:
    """Human-readable host RAM / disk / device HBM summary string."""
    parts = []
    try:
        import psutil

        vm = psutil.virtual_memory()
        parts.append(f"RAM: {vm.used / 1e9:.1f}/{vm.total / 1e9:.1f} GB ({vm.percent}%)")
    except Exception:
        pass
    try:
        du = shutil.disk_usage("/")
        parts.append(f"Disk: {du.used / 1e9:.1f}/{du.total / 1e9:.1f} GB")
    except Exception:
        pass
    parts.append(device_memory_summary() or "HBM: n/a")
    return " | ".join(parts)


def device_memory_summary() -> Optional[str]:
    try:
        import jax

        stats = []
        for d in jax.local_devices():
            ms = d.memory_stats() or {}
            used = ms.get("bytes_in_use")
            limit = ms.get("bytes_limit")
            if used is not None:
                lim = f"/{limit / 1e9:.1f}" if limit else ""
                stats.append(f"{d.platform}:{d.id} {used / 1e9:.2f}{lim} GB")
        return "HBM: " + ", ".join(stats) if stats else None
    except Exception:
        return None


def clear_host_memory() -> None:
    """Release python garbage; JAX device buffers free with their references."""
    for _ in range(3):
        gc.collect()
