"""Sweep checkpoint / resume primitives.

The reference persists resume state three ways (SURVEY.md §4):
  - JSON ``{completed_models, results}`` after each model
    (run_base_vs_instruct_100q.py:265-276),
  - pickled sets of processed ``(model, scenario, perturbation_id)`` triples
    (evaluate_irrelevant_perturbations.py:89-162),
  - skip-sets re-derived from the output workbook (perturb_prompts.py:161-188).

Here: one atomic-JSON ``CheckpointFile`` plus a ``ProcessedSet`` of idempotency
keys usable for all three patterns (keys are JSON-encoded tuples, so no pickle).
"""

from __future__ import annotations

import json
import os
from typing import Any, Iterable, Optional, Sequence


_TMP_SEQ = iter(range(1 << 62))


def _atomic_write_json(path: str, obj: Any) -> None:
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    # per-call unique tmp name: a PreemptionGuard handler saving the same
    # checkpoint can interrupt an in-progress save IN THE SAME THREAD; with
    # a shared tmp path the handler's open("w") would truncate the inode
    # the interrupted writer still holds, whose buffered partial JSON then
    # flushes on unwind into the freshly-replaced FINAL file.  Unique names
    # keep the two writes on separate inodes (the interrupted tmp is
    # orphaned, harmlessly).
    tmp = f"{path}.{next(_TMP_SEQ)}.tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f, indent=2, default=str)
        # fsync before the rename: os.replace is atomic against concurrent
        # readers but not against power/instance loss — an unsynced tmp can
        # land as an empty/truncated checkpoint after a hard preemption.
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def append_jsonl(path: str, rows: Iterable[Any], fsync: bool = True) -> None:
    """Append one JSON object per row to a side-log, crash-consistently.

    The sweep shells' checkpoint flush is an O(new-rows) append to a
    ``.rows.jsonl`` side-log; with ``fsync`` (the default) the data is
    forced to disk before the call returns, so a SIGKILL/power loss right
    after a ``checkpoint_every`` flush can no longer lose the rows the
    flush claimed to checkpoint.  Numpy scalars serialize via ``.item()``
    like the sweep writers."""
    with open(path, "a") as f:
        for row in rows:
            f.write(json.dumps(
                row, default=lambda o: o.item()
                if hasattr(o, "item") else str(o)) + "\n")
        if fsync:
            f.flush()
            os.fsync(f.fileno())


class CheckpointFile:
    """Atomic JSON checkpoint with a default payload on first load."""

    def __init__(self, path: str, default: Optional[dict] = None):
        self.path = path
        self.default = default or {}

    def load(self) -> dict:
        if os.path.exists(self.path):
            with open(self.path) as f:
                return json.load(f)
        return json.loads(json.dumps(self.default))

    def save(self, state: dict) -> None:
        _atomic_write_json(self.path, state)

    def clear(self) -> None:
        if os.path.exists(self.path):
            os.remove(self.path)


class ProcessedSet:
    """Persistent set of idempotency keys (tuples of str/int)."""

    def __init__(self, path: Optional[str] = None):
        self.path = path
        self._keys = set()
        if path and os.path.exists(path):
            with open(path) as f:
                self._keys = {tuple(k) for k in json.load(f)}

    @staticmethod
    def _norm(key) -> tuple:
        # Scalar keys (e.g. a bare model name, the reference's
        # ``completed_models`` pattern) become 1-tuples; only real sequences
        # are treated as composite keys.
        if isinstance(key, (str, bytes, int, float)):
            return (key,)
        return tuple(key)

    def __contains__(self, key) -> bool:
        return self._norm(key) in self._keys

    def __len__(self) -> int:
        return len(self._keys)

    def add(self, key, flush: bool = True) -> None:
        self._keys.add(self._norm(key))
        if flush and self.path:
            self.flush()

    def update(self, keys, flush: bool = True) -> None:
        for k in keys:
            self._keys.add(self._norm(k))
        if flush and self.path:
            self.flush()

    def flush(self) -> None:
        if self.path:
            # Sort by JSON repr: stable output even when keys mix types at the
            # same tuple position (plain sorted() would raise TypeError).
            keys = sorted((list(k) for k in self._keys), key=lambda k: json.dumps(k, default=str))
            _atomic_write_json(self.path, keys)
