"""Minimal, dependency-free .xlsx reader/writer.

The reference writes its sweep results to Excel workbooks (e.g.
``results_30_multi_model.xlsx`` — /root/reference/analysis/perturb_prompts.py:964-1066)
and every analysis script reads them back with pandas.  This image has no
``openpyxl``, so we implement the OOXML subset we need directly: a workbook is a
zip of XML parts; we emit inline strings (no sharedStrings table) and parse both
inline and shared strings on read.

Public API:
    write_xlsx(df, path, sheet_name="Sheet1")
    write_xlsx_sheets({name: df, ...}, path)   -> multi-sheet workbook (the
                                reference's results_analysis.xlsx carries Raw
                                Results / Summary / Position Analysis sheets,
                                evaluate_irrelevant_perturbations.py:676-713)
    read_xlsx(path, sheet=0) -> pandas.DataFrame
    append_xlsx(df, path)    -> read existing + concat + rewrite (the reference's
                                incremental-append pattern, perturb_prompts_claude.py:250-253)
"""

from __future__ import annotations

import math
import re
import zipfile
from xml.etree import ElementTree as ET
from xml.sax.saxutils import escape

import numpy as np
import pandas as pd

_NS = "{http://schemas.openxmlformats.org/spreadsheetml/2006/main}"

_CONTENT_TYPES = """<?xml version="1.0" encoding="UTF-8" standalone="yes"?>
<Types xmlns="http://schemas.openxmlformats.org/package/2006/content-types">
<Default Extension="rels" ContentType="application/vnd.openxmlformats-package.relationships+xml"/>
<Default Extension="xml" ContentType="application/xml"/>
<Override PartName="/xl/workbook.xml" ContentType="application/vnd.openxmlformats-officedocument.spreadsheetml.sheet.main+xml"/>
{sheet_overrides}</Types>
"""

_SHEET_OVERRIDE = '<Override PartName="/xl/worksheets/sheet{i}.xml" ContentType="application/vnd.openxmlformats-officedocument.spreadsheetml.worksheet+xml"/>\n'

_RELS = """<?xml version="1.0" encoding="UTF-8" standalone="yes"?>
<Relationships xmlns="http://schemas.openxmlformats.org/package/2006/relationships">
<Relationship Id="rId1" Type="http://schemas.openxmlformats.org/officeDocument/2006/relationships/officeDocument" Target="xl/workbook.xml"/>
</Relationships>
"""

_WORKBOOK = """<?xml version="1.0" encoding="UTF-8" standalone="yes"?>
<workbook xmlns="http://schemas.openxmlformats.org/spreadsheetml/2006/main" xmlns:r="http://schemas.openxmlformats.org/officeDocument/2006/relationships">
<sheets>{sheets}</sheets>
</workbook>
"""

_WORKBOOK_RELS = """<?xml version="1.0" encoding="UTF-8" standalone="yes"?>
<Relationships xmlns="http://schemas.openxmlformats.org/package/2006/relationships">
{rels}</Relationships>
"""

# Characters illegal in XML 1.0 (except tab/newline/CR) — strip on write.
_ILLEGAL_XML = re.compile("[\x00-\x08\x0b\x0c\x0e-\x1f]")


def _col_letter(idx: int) -> str:
    """0-based column index -> A1-style letters."""
    out = ""
    idx += 1
    while idx:
        idx, rem = divmod(idx - 1, 26)
        out = chr(ord("A") + rem) + out
    return out


def _cell_xml(ref: str, value) -> str:
    if value is None:
        return ""
    if isinstance(value, float) and math.isnan(value):
        return ""
    if isinstance(value, (bool, np.bool_)):
        return f'<c r="{ref}" t="b"><v>{int(value)}</v></c>'
    if isinstance(value, (int, np.integer)):
        return f'<c r="{ref}"><v>{int(value)}</v></c>'
    if isinstance(value, (float, np.floating)):
        if math.isinf(value):
            # Excel has no inf literal; store as string like pandas/openpyxl repr
            text = "inf" if value > 0 else "-inf"
            return f'<c r="{ref}" t="inlineStr"><is><t>{text}</t></is></c>'
        return f'<c r="{ref}"><v>{float(value)!r}</v></c>'
    text = escape(_ILLEGAL_XML.sub("", str(value)))
    return f'<c r="{ref}" t="inlineStr"><is><t xml:space="preserve">{text}</t></is></c>'


def _sheet_xml(df: pd.DataFrame) -> str:
    rows_xml = []
    header_cells = "".join(
        _cell_xml(f"{_col_letter(c)}1", col) for c, col in enumerate(df.columns)
    )
    rows_xml.append(f'<row r="1">{header_cells}</row>')
    for r, (_, row) in enumerate(df.iterrows(), start=2):
        cells = "".join(
            _cell_xml(f"{_col_letter(c)}{r}", v) for c, v in enumerate(row.tolist())
        )
        rows_xml.append(f'<row r="{r}">{cells}</row>')
    return (
        '<?xml version="1.0" encoding="UTF-8" standalone="yes"?>'
        '<worksheet xmlns="http://schemas.openxmlformats.org/spreadsheetml/2006/main">'
        f'<sheetData>{"".join(rows_xml)}</sheetData></worksheet>'
    )


def write_xlsx_sheets(sheets: "dict[str, pd.DataFrame]", path) -> None:
    """Write a workbook with one worksheet per (name, frame) entry, in order.

    ``read_xlsx(path, sheet=i)`` reads them back positionally."""
    if not sheets:
        raise ValueError("write_xlsx_sheets needs at least one sheet")
    names = [escape(str(n)[:31]) for n in sheets]
    sheet_tags = "".join(
        f'<sheet name="{n}" sheetId="{i}" r:id="rId{i}"/>'
        for i, n in enumerate(names, start=1)
    )
    rels = "".join(
        f'<Relationship Id="rId{i}" Type="http://schemas.openxmlformats.org/'
        f'officeDocument/2006/relationships/worksheet" Target="worksheets/sheet{i}.xml"/>\n'
        for i in range(1, len(names) + 1)
    )
    overrides = "".join(_SHEET_OVERRIDE.format(i=i) for i in range(1, len(names) + 1))
    # atomic: write to a sibling temp file then os.replace, so a crash mid-
    # write can never truncate an existing workbook (the sweeps checkpoint by
    # rewriting in place — a corrupt file would break their resume)
    import os
    import tempfile

    path = str(path)
    fd, tmp = tempfile.mkstemp(
        dir=os.path.dirname(os.path.abspath(path)) or ".", suffix=".xlsx.tmp"
    )
    os.close(fd)
    # mkstemp creates 0600; restore umask-default permissions (or keep the
    # destination's existing mode) so shared results dirs stay readable
    if os.path.exists(path):
        os.chmod(tmp, os.stat(path).st_mode & 0o777)
    else:
        umask = os.umask(0)
        os.umask(umask)
        os.chmod(tmp, 0o666 & ~umask)
    try:
        with zipfile.ZipFile(tmp, "w", zipfile.ZIP_DEFLATED) as zf:
            zf.writestr("[Content_Types].xml",
                        _CONTENT_TYPES.format(sheet_overrides=overrides))
            zf.writestr("_rels/.rels", _RELS)
            zf.writestr("xl/workbook.xml", _WORKBOOK.format(sheets=sheet_tags))
            zf.writestr("xl/_rels/workbook.xml.rels",
                        _WORKBOOK_RELS.format(rels=rels))
            for i, df in enumerate(sheets.values(), start=1):
                zf.writestr(f"xl/worksheets/sheet{i}.xml", _sheet_xml(df))
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.remove(tmp)
        raise


def write_xlsx(df: pd.DataFrame, path, sheet_name: str = "Sheet1") -> None:
    write_xlsx_sheets({sheet_name: df}, path)


def _parse_shared_strings(zf: zipfile.ZipFile):
    try:
        data = zf.read("xl/sharedStrings.xml")
    except KeyError:
        return []
    root = ET.fromstring(data)
    strings = []
    for si in root.findall(f"{_NS}si"):
        strings.append("".join(t.text or "" for t in si.iter(f"{_NS}t")))
    return strings


def _cell_ref_to_col(ref: str) -> int:
    col = 0
    for ch in ref:
        if ch.isalpha():
            col = col * 26 + (ord(ch.upper()) - ord("A") + 1)
        else:
            break
    return col - 1


def _coerce_number(text: str):
    try:
        f = float(text)
    except ValueError:
        return text
    if f.is_integer() and "." not in text and "e" not in text.lower():
        return int(f)
    return f


def read_xlsx(path, sheet: int = 0) -> pd.DataFrame:
    with zipfile.ZipFile(path) as zf:
        shared = _parse_shared_strings(zf)
        sheet_names = sorted(
            (n for n in zf.namelist() if re.match(r"xl/worksheets/sheet\d+\.xml$", n)),
            key=lambda n: int(re.search(r"(\d+)\.xml$", n).group(1)),
        )
        if not sheet_names:
            raise ValueError(f"no worksheets in {path}")
        root = ET.fromstring(zf.read(sheet_names[sheet]))
    raw_rows = []
    max_col = 0
    for row in root.iter(f"{_NS}row"):
        cells = {}
        for c in row.findall(f"{_NS}c"):
            ref = c.get("r", "")
            col = _cell_ref_to_col(ref) if ref else len(cells)
            ctype = c.get("t", "n")
            value = None
            if ctype == "inlineStr":
                is_el = c.find(f"{_NS}is")
                if is_el is not None:
                    value = "".join(t.text or "" for t in is_el.iter(f"{_NS}t"))
            else:
                v = c.find(f"{_NS}v")
                if v is not None and v.text is not None:
                    if ctype == "s":
                        value = shared[int(v.text)]
                    elif ctype == "b":
                        value = bool(int(v.text))
                    elif ctype == "str":
                        value = v.text
                    else:
                        value = _coerce_number(v.text)
            cells[col] = value
            max_col = max(max_col, col + 1)
        raw_rows.append(cells)
    if not raw_rows:
        return pd.DataFrame()
    header = [raw_rows[0].get(i) for i in range(max_col)]
    header = [h if h is not None else f"Unnamed: {i}" for i, h in enumerate(header)]
    data = [[r.get(i) for i in range(max_col)] for r in raw_rows[1:]]
    df = pd.DataFrame(data, columns=header)
    # Mirror pandas.read_excel dtype behavior: numeric columns become float when
    # they contain missing values.
    return df.infer_objects()


def append_xlsx(df: pd.DataFrame, path) -> pd.DataFrame:
    """Concatenate ``df`` onto an existing workbook (if any) and rewrite it."""
    import os

    if os.path.exists(path):
        existing = read_xlsx(path)
        combined = pd.concat([existing, df], ignore_index=True) if len(existing) else df
    else:
        combined = df
    write_xlsx(combined, path)
    return combined
