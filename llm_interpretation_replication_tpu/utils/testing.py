"""In-process fixtures for tests and driver dryruns.

The image is zero-egress (no HF hub), so anything that needs a real tokenizer
builds a tiny byte-level BPE in process.  Shared by ``tests/helpers.py`` and
``__graft_entry__.dryrun_multichip``'s scoring leg so the dryrun exercises the
exact ScoringEngine path (tokenize → bucket → decode → scan) the sweeps use.
"""

from __future__ import annotations


def build_inprocess_tokenizer(vocab_size: int = 300):
    """Byte-level BPE tokenizer trained in-process.  Distinguishes " Yes" from
    "Yes" like real GPT-style vocabs (the leading-space convention of
    run_base_vs_instruct_100q.py:332-335)."""
    from tokenizers import ByteLevelBPETokenizer
    from transformers import PreTrainedTokenizerFast

    tok = ByteLevelBPETokenizer()
    corpus = [
        "Yes No Answer: Yes.",
        "Answer: No.",
        "Is a tweet a publication? Yes",
        "Is soup a beverage? No",
        "confidence 0 1 2 3 4 5 6 7 8 9 10 42 85 90 100",
        "The quick brown fox jumps over the lazy dog.",
    ] * 50
    tok.train_from_iterator(corpus, vocab_size=vocab_size, min_frequency=1)
    inner = tok._tokenizer if hasattr(tok, "_tokenizer") else tok
    fast = PreTrainedTokenizerFast(tokenizer_object=inner)
    fast.pad_token = fast.decode([0])
    fast.pad_token_id = 0
    return fast
