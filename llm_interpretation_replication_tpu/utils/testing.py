"""In-process fixtures for tests and driver dryruns.

The image is zero-egress (no HF hub), so anything that needs a real tokenizer
builds a tiny byte-level BPE in process.  Shared by ``tests/helpers.py`` and
``__graft_entry__.dryrun_multichip``'s scoring leg so the dryrun exercises the
exact ScoringEngine path (tokenize → bucket → decode → scan) the sweeps use.

Also home of the FAULT-INJECTION HARNESS (:class:`FaultyEngine`): a wrapper
that injects device OOM, SIGTERM preemption, transient RPC errors, and NaN
logits on a schedule, at either the sweep-call or the device-batch
granularity, so the pytest fault matrix (tests/test_faults.py, ``-m
faults``) pins every recovery path in runtime/faults.py against a tiny CPU
model.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import signal
import threading
import time
from typing import List, Optional, Sequence


def build_inprocess_tokenizer(vocab_size: int = 300):
    """Byte-level BPE tokenizer trained in-process.  Distinguishes " Yes" from
    "Yes" like real GPT-style vocabs (the leading-space convention of
    run_base_vs_instruct_100q.py:332-335)."""
    from tokenizers import ByteLevelBPETokenizer
    from transformers import PreTrainedTokenizerFast

    tok = ByteLevelBPETokenizer()
    corpus = [
        "Yes No Answer: Yes.",
        "Answer: No.",
        "Is a tweet a publication? Yes",
        "Is soup a beverage? No",
        "confidence 0 1 2 3 4 5 6 7 8 9 10 42 85 90 100",
        "The quick brown fox jumps over the lazy dog.",
    ] * 50
    tok.train_from_iterator(corpus, vocab_size=vocab_size, min_frequency=1)
    inner = tok._tokenizer if hasattr(tok, "_tokenizer") else tok
    fast = PreTrainedTokenizerFast(tokenizer_object=inner)
    fast.pad_token = fast.decode([0])
    fast.pad_token_id = 0
    return fast


# ---------------------------------------------------------------------------
# Fault-injection harness
# ---------------------------------------------------------------------------

def injected_oom_error() -> RuntimeError:
    """The RESOURCE_EXHAUSTED spelling the real stack produces, so the
    injected fault exercises the same ``faults.is_oom`` classification."""
    return RuntimeError(
        "RESOURCE_EXHAUSTED: Out of memory allocating device buffer "
        "(injected by FaultyEngine)")


@dataclasses.dataclass
class Fault:
    """One scheduled fault.

    ``kind``:
      - ``"oom"``       raise a fake RESOURCE_EXHAUSTED
      - ``"transient"`` raise a :class:`~..runtime.faults.TransientError`
      - ``"preempt"``   deliver SIGTERM to this process (so installed
                        :class:`~..runtime.faults.PreemptionGuard` handlers
                        flush); raises ``Preempted`` directly when no
                        handler is installed (never kills the test runner)
      - ``"nan"``       delegate the call, then overwrite every probability
                        field with NaN — the observable effect of NaN logits

    Exactly one trigger: ``at_call`` (1-based index over the engine's
    score_prompts / first_token_relative_prob calls — sweep-chunk
    granularity) or ``at_batch`` (1-based device-batch launch inside the
    engine — the granularity the engine's OOM back-off operates at).
    ``times`` repeats the fault on consecutive matching triggers."""

    kind: str
    at_call: int = 0
    at_batch: int = 0
    times: int = 1
    fired: int = 0

    def __post_init__(self):
        if self.kind not in ("oom", "transient", "preempt", "nan"):
            raise ValueError(f"unknown fault kind {self.kind!r}")
        if (self.at_call > 0) == (self.at_batch > 0):
            raise ValueError("specify exactly one of at_call / at_batch")
        if self.kind == "nan" and self.at_batch:
            raise ValueError("nan faults operate at call granularity")


class FaultyEngine:
    """Duck-typed engine wrapper injecting faults on a schedule.

    Wraps any engine the sweep shells accept (a real ScoringEngine or a
    test fake) and delegates everything, counting ``calls`` (score_prompts
    and first_token_relative_prob invocations, shared counter — the same
    numbering bench's regression tests use) and ``batches`` (device-batch
    launches, by hooking the engine's ``_run_pipelined`` chokepoint when it
    has one — installed only for the duration of THIS wrapper's calls, so
    discarding the wrapper leaves the engine clean and no stale unfired
    ``at_batch`` fault can ambush a later direct use of the engine).
    Faults fire per their schedule; everything injected is recorded on
    ``self.injected`` for assertions."""

    def __init__(self, engine, faults: Sequence[Fault] = ()):
        self.engine = engine
        self.faults = list(faults)
        self.calls = 0
        self.batches = 0
        self.injected: List[dict] = []
        self._hook_batches = any(f.at_batch for f in self.faults)
        # expose score_prefixed ONLY when the wrapped engine has it, so
        # hasattr probes (the sweeps' fused-path capability check) see the
        # same surface as the bare engine — a FakeEngine without the fused
        # path keeps routing sweeps through the legacy string path
        if hasattr(engine, "score_prefixed"):
            self.score_prefixed = self._score_prefixed

    @contextlib.contextmanager
    def _batch_hook(self):
        """Shadow the engine's ``_run_pipelined`` with the batch-counting
        hook for one delegated call, restoring the original on exit."""
        if not self._hook_batches or not hasattr(self.engine,
                                                 "_run_pipelined"):
            yield
            return
        real_run = self.engine._run_pipelined

        def run(batches, launch, consume, rebatch=None):
            def counting_launch(batch):
                self.batches += 1
                self._maybe_fire(at_batch=self.batches)
                return launch(batch)
            return real_run(batches, counting_launch, consume,
                            rebatch=rebatch)

        self.engine._run_pipelined = run
        try:
            yield
        finally:
            self.engine.__dict__.pop("_run_pipelined", None)

    # -- delegation ------------------------------------------------------

    def __getattr__(self, name):
        return getattr(self.engine, name)

    def score_prompts(self, prompts, targets=("Yes", "No"),
                      with_confidence=False, max_new_tokens=None, **kw):
        self.calls += 1
        nan = self._take(at_call=self.calls, kinds=("nan",))
        self._maybe_fire(at_call=self.calls)
        kwargs = dict(targets=targets, with_confidence=with_confidence, **kw)
        if max_new_tokens is not None:  # old-signature engines keep working
            kwargs["max_new_tokens"] = max_new_tokens
        with self._batch_hook():
            rows = self.engine.score_prompts(prompts, **kwargs)
        if nan is not None:
            self._record(nan, at_call=self.calls)
            for row in rows:
                for key in ("yes_prob", "no_prob", "relative_prob",
                            "odds_ratio", "first_token_yes_prob",
                            "first_token_no_prob",
                            "first_token_relative_prob"):
                    if key in row:
                        row[key] = float("nan")
        return rows

    def _score_prefixed(self, pairs, targets=("Yes", "No"), legs=None, **kw):
        """Fused-path injection point (installed as ``score_prefixed`` when
        the wrapped engine has one): shares the call counter and fault
        schedule with score_prompts — a sweep chunk is one call either
        way — and hooks device-batch launches identically."""
        self.calls += 1
        nan = self._take(at_call=self.calls, kinds=("nan",))
        self._maybe_fire(at_call=self.calls)
        with self._batch_hook():
            outs = self.engine.score_prefixed(pairs, targets=targets,
                                              legs=legs, **kw)
        if nan is not None:
            self._record(nan, at_call=self.calls)
            for rows in outs:
                for row in rows:
                    for key in ("yes_prob", "no_prob", "relative_prob",
                                "odds_ratio", "first_token_yes_prob",
                                "first_token_no_prob",
                                "first_token_relative_prob"):
                        if key in row:
                            row[key] = float("nan")
        return outs

    def serve_scheduler(self, config=None):
        """Serve-path injection point: a continuous-batching scheduler
        (serve/.Scheduler) built over THIS wrapper, so scheduler-driven
        micro-batches launch through the counting/injecting
        ``score_prompts`` / ``score_prefixed`` above — ``at_call`` and
        ``at_batch`` faults fire inside serve launches exactly as they do
        inside sweep calls, and the fault matrix covers the scheduler's
        own recovery paths (OOM → split + queue re-entry, transient →
        in-place retry) with the same schedules."""
        from ..serve import Scheduler

        return Scheduler(self, config)

    def first_token_relative_prob(self, prompts, targets=("Yes", "No"),
                                  top_filter: int = 0):
        self.calls += 1
        nan = self._take(at_call=self.calls, kinds=("nan",))
        self._maybe_fire(at_call=self.calls)
        with self._batch_hook():
            out = self.engine.first_token_relative_prob(
                prompts, targets=targets, top_filter=top_filter)
        if nan is not None:
            self._record(nan, at_call=self.calls)
            out = out * float("nan")
        return out

    # -- scheduling ------------------------------------------------------

    def _take(self, at_call: int = 0, at_batch: int = 0,
              kinds: Sequence[str] = ("oom", "transient", "preempt")
              ) -> Optional[Fault]:
        for f in self.faults:
            if f.fired >= f.times or f.kind not in kinds:
                continue
            if at_call and f.at_call == at_call:
                f.fired += 1
                return f
            if at_batch and f.at_batch == at_batch:
                f.fired += 1
                return f
        return None

    def _record(self, fault: Fault, **where):
        self.injected.append({"kind": fault.kind, **where})

    def _maybe_fire(self, at_call: int = 0, at_batch: int = 0) -> None:
        fault = self._take(at_call=at_call, at_batch=at_batch)
        if fault is None:
            return
        self._record(fault, at_call=at_call, at_batch=at_batch)
        if fault.kind == "oom":
            raise injected_oom_error()
        if fault.kind == "transient":
            from ..runtime.faults import TransientError

            raise TransientError("injected transient fault (FaultyEngine)")
        if fault.kind == "preempt":
            from ..runtime.faults import Preempted

            handler = signal.getsignal(signal.SIGTERM)
            if callable(handler):
                # a real handler is installed (e.g. PreemptionGuard): deliver
                # the actual signal so its flush path runs; the handler's
                # raise surfaces out of the sleep below
                os.kill(os.getpid(), signal.SIGTERM)
                deadline = time.monotonic() + 2.0
                while time.monotonic() < deadline:
                    time.sleep(0.01)  # handler raises from in here
            # SIG_DFL/SIG_IGN would kill (or ignore in) the test runner;
            # simulate the preemption exit instead
            raise Preempted(signal.SIGTERM)


# ---------------------------------------------------------------------------
# Pool-level fault harness (fleet self-healing, serve/supervisor.py)
# ---------------------------------------------------------------------------

class BreakableEngine:
    """Duck-typed engine wrapper a test can KILL or WEDGE at will.

    :class:`FaultyEngine` injects faults the ENGINE-level ladder recovers
    from (OOM back-off, transient retry); this wrapper injects the faults
    that kill a whole POOL REPLICA so the supervisor's quarantine /
    rebuild / failover paths can be pinned:

    - :meth:`kill` — every subsequent scoring call raises a non-request
      ``RuntimeError`` (the supervisor classifies it as a replica CRASH);
    - :meth:`wedge` — every subsequent scoring call BLOCKS (a hung
      device: no beats while busy → the supervisor's wedge watchdog);
    - :meth:`heal` — back to delegation; unblocks a wedged call so a
      quarantined replica's bounded teardown can complete in tests;
    - ``poison_marker`` — any batch whose prompt contains this substring
      crashes the call wherever it lands: the SAME request killing
      replica after replica, which is exactly the poison-row ceiling's
      trigger (``SupervisorConfig.poison_kill_limit``).

    ``crashes`` counts injected kills for assertions.  Factories built
    from this wrapper (one per replica) drive the strict failover matrix
    in tests/test_pool.py and the ``bench --serve-load-replicas`` fault
    schedule."""

    def __init__(self, engine, poison_marker: Optional[str] = None):
        self.engine = engine
        self.poison_marker = poison_marker
        self.mode = "ok"               # ok | dead | wedged
        self.crashes = 0
        self._unwedge = threading.Event()
        self._unwedge.set()
        if hasattr(engine, "score_prefixed"):
            self.score_prefixed = self._score_prefixed
        # real engines expose the slot-admission entry the serve
        # scheduler PREFERS over score_prompts; a plain __getattr__
        # delegation would bypass the fault gate entirely
        if hasattr(engine, "score_prompts_slotted"):
            self.score_prompts_slotted = self._score_prompts_slotted

    # -- fault controls --------------------------------------------------

    def kill(self) -> None:
        self.mode = "dead"

    def wedge(self) -> None:
        self._unwedge.clear()
        self.mode = "wedged"

    def heal(self) -> None:
        self.mode = "ok"
        self._unwedge.set()

    # -- injection gate --------------------------------------------------

    def _crash(self, why: str) -> "RuntimeError":
        self.crashes += 1
        return RuntimeError(
            f"replica engine crashed: {why} (injected by BreakableEngine)")

    def _text(self, prompt) -> str:
        # the pool coalescer pre-tokenizes on the submit thread whenever
        # the engine has a tokenizer, so by the time a real engine is
        # called the "prompt" is a token-id list — decode it back or the
        # poison marker is invisible on exactly the engines that matter
        if isinstance(prompt, str):
            return prompt
        if isinstance(prompt, (tuple, list)) and all(
                isinstance(x, str) for x in prompt):
            return "".join(prompt)       # un-encoded suffix tuple
        tok = getattr(self.engine, "tokenizer", None)
        if tok is None:
            return ""
        try:
            return tok.decode(list(prompt))
        except Exception:
            return ""

    def _gate(self, prompts: Sequence) -> None:
        if self.mode == "dead":
            raise self._crash("killed")
        if self.mode == "wedged":
            # block like a hung device until heal(); the scheduler's
            # coalescer thread sits here, so the replica makes no
            # progress beats while busy — the wedge watchdog's signature
            self._unwedge.wait()
            if self.mode == "dead":
                raise self._crash("killed while wedged")
        if self.poison_marker and any(
                self.poison_marker in self._text(p) for p in prompts):
            raise self._crash(f"poison marker {self.poison_marker!r}")

    # -- delegation ------------------------------------------------------

    def __getattr__(self, name):
        return getattr(self.engine, name)

    def score_prompts(self, prompts, **kw):
        self._gate(prompts)
        return self.engine.score_prompts(prompts, **kw)

    def _score_prefixed(self, pairs, **kw):
        self._gate([f"{self._text(p)}{self._text(s)}" for p, s in pairs])
        return self.engine.score_prefixed(pairs, **kw)

    def _score_prompts_slotted(self, prompts, **kw):
        self._gate(prompts)
        return self.engine.score_prompts_slotted(prompts, **kw)

    def first_token_relative_prob(self, prompts, **kw):
        self._gate(prompts)
        return self.engine.first_token_relative_prob(prompts, **kw)


class FlakyVendor:
    """A togglable-outage ``evaluate`` callable for
    :class:`~..serve.pool.RemoteBackend` — the vendor-side twin of
    :class:`BreakableEngine` that drives the circuit-breaker tests.

    Usable directly as ``RemoteBackend("vendor-model", FlakyVendor())``.
    Set ``down = True`` for a hard outage (every call raises a transport
    ``RuntimeError``) or ``fail_next = N`` for a bounded burst; calls and
    failures are counted for breaker-threshold assertions."""

    def __init__(self, yes_prob: float = 0.9, no_prob: float = 0.1,
                 latency_s: float = 0.0):
        self.yes_prob = yes_prob
        self.no_prob = no_prob
        self.latency_s = latency_s
        self.down = False
        self.fail_next = 0
        self.calls = 0
        self.failures = 0

    def __call__(self, prompt, targets, with_confidence, max_new_tokens):
        self.calls += 1
        if self.down or self.fail_next > 0:
            if self.fail_next > 0:
                self.fail_next -= 1
            self.failures += 1
            raise RuntimeError(
                "vendor unavailable: injected 503 (FlakyVendor)")
        if self.latency_s:
            time.sleep(self.latency_s)
        return {"yes_prob": self.yes_prob, "no_prob": self.no_prob,
                "response": "Yes" if self.yes_prob >= self.no_prob
                else "No"}
