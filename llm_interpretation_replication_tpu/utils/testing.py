"""In-process fixtures for tests and driver dryruns.

The image is zero-egress (no HF hub), so anything that needs a real tokenizer
builds a tiny byte-level BPE in process.  Shared by ``tests/helpers.py`` and
``__graft_entry__.dryrun_multichip``'s scoring leg so the dryrun exercises the
exact ScoringEngine path (tokenize → bucket → decode → scan) the sweeps use.

Also home of the FAULT-INJECTION HARNESS (:class:`FaultyEngine`): a wrapper
that injects device OOM, SIGTERM preemption, transient RPC errors, and NaN
logits on a schedule, at either the sweep-call or the device-batch
granularity, so the pytest fault matrix (tests/test_faults.py, ``-m
faults``) pins every recovery path in runtime/faults.py against a tiny CPU
model.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import signal
import time
from typing import List, Optional, Sequence


def build_inprocess_tokenizer(vocab_size: int = 300):
    """Byte-level BPE tokenizer trained in-process.  Distinguishes " Yes" from
    "Yes" like real GPT-style vocabs (the leading-space convention of
    run_base_vs_instruct_100q.py:332-335)."""
    from tokenizers import ByteLevelBPETokenizer
    from transformers import PreTrainedTokenizerFast

    tok = ByteLevelBPETokenizer()
    corpus = [
        "Yes No Answer: Yes.",
        "Answer: No.",
        "Is a tweet a publication? Yes",
        "Is soup a beverage? No",
        "confidence 0 1 2 3 4 5 6 7 8 9 10 42 85 90 100",
        "The quick brown fox jumps over the lazy dog.",
    ] * 50
    tok.train_from_iterator(corpus, vocab_size=vocab_size, min_frequency=1)
    inner = tok._tokenizer if hasattr(tok, "_tokenizer") else tok
    fast = PreTrainedTokenizerFast(tokenizer_object=inner)
    fast.pad_token = fast.decode([0])
    fast.pad_token_id = 0
    return fast


# ---------------------------------------------------------------------------
# Fault-injection harness
# ---------------------------------------------------------------------------

def injected_oom_error() -> RuntimeError:
    """The RESOURCE_EXHAUSTED spelling the real stack produces, so the
    injected fault exercises the same ``faults.is_oom`` classification."""
    return RuntimeError(
        "RESOURCE_EXHAUSTED: Out of memory allocating device buffer "
        "(injected by FaultyEngine)")


@dataclasses.dataclass
class Fault:
    """One scheduled fault.

    ``kind``:
      - ``"oom"``       raise a fake RESOURCE_EXHAUSTED
      - ``"transient"`` raise a :class:`~..runtime.faults.TransientError`
      - ``"preempt"``   deliver SIGTERM to this process (so installed
                        :class:`~..runtime.faults.PreemptionGuard` handlers
                        flush); raises ``Preempted`` directly when no
                        handler is installed (never kills the test runner)
      - ``"nan"``       delegate the call, then overwrite every probability
                        field with NaN — the observable effect of NaN logits

    Exactly one trigger: ``at_call`` (1-based index over the engine's
    score_prompts / first_token_relative_prob calls — sweep-chunk
    granularity) or ``at_batch`` (1-based device-batch launch inside the
    engine — the granularity the engine's OOM back-off operates at).
    ``times`` repeats the fault on consecutive matching triggers."""

    kind: str
    at_call: int = 0
    at_batch: int = 0
    times: int = 1
    fired: int = 0

    def __post_init__(self):
        if self.kind not in ("oom", "transient", "preempt", "nan"):
            raise ValueError(f"unknown fault kind {self.kind!r}")
        if (self.at_call > 0) == (self.at_batch > 0):
            raise ValueError("specify exactly one of at_call / at_batch")
        if self.kind == "nan" and self.at_batch:
            raise ValueError("nan faults operate at call granularity")


class FaultyEngine:
    """Duck-typed engine wrapper injecting faults on a schedule.

    Wraps any engine the sweep shells accept (a real ScoringEngine or a
    test fake) and delegates everything, counting ``calls`` (score_prompts
    and first_token_relative_prob invocations, shared counter — the same
    numbering bench's regression tests use) and ``batches`` (device-batch
    launches, by hooking the engine's ``_run_pipelined`` chokepoint when it
    has one — installed only for the duration of THIS wrapper's calls, so
    discarding the wrapper leaves the engine clean and no stale unfired
    ``at_batch`` fault can ambush a later direct use of the engine).
    Faults fire per their schedule; everything injected is recorded on
    ``self.injected`` for assertions."""

    def __init__(self, engine, faults: Sequence[Fault] = ()):
        self.engine = engine
        self.faults = list(faults)
        self.calls = 0
        self.batches = 0
        self.injected: List[dict] = []
        self._hook_batches = any(f.at_batch for f in self.faults)
        # expose score_prefixed ONLY when the wrapped engine has it, so
        # hasattr probes (the sweeps' fused-path capability check) see the
        # same surface as the bare engine — a FakeEngine without the fused
        # path keeps routing sweeps through the legacy string path
        if hasattr(engine, "score_prefixed"):
            self.score_prefixed = self._score_prefixed

    @contextlib.contextmanager
    def _batch_hook(self):
        """Shadow the engine's ``_run_pipelined`` with the batch-counting
        hook for one delegated call, restoring the original on exit."""
        if not self._hook_batches or not hasattr(self.engine,
                                                 "_run_pipelined"):
            yield
            return
        real_run = self.engine._run_pipelined

        def run(batches, launch, consume, rebatch=None):
            def counting_launch(batch):
                self.batches += 1
                self._maybe_fire(at_batch=self.batches)
                return launch(batch)
            return real_run(batches, counting_launch, consume,
                            rebatch=rebatch)

        self.engine._run_pipelined = run
        try:
            yield
        finally:
            self.engine.__dict__.pop("_run_pipelined", None)

    # -- delegation ------------------------------------------------------

    def __getattr__(self, name):
        return getattr(self.engine, name)

    def score_prompts(self, prompts, targets=("Yes", "No"),
                      with_confidence=False, max_new_tokens=None, **kw):
        self.calls += 1
        nan = self._take(at_call=self.calls, kinds=("nan",))
        self._maybe_fire(at_call=self.calls)
        kwargs = dict(targets=targets, with_confidence=with_confidence, **kw)
        if max_new_tokens is not None:  # old-signature engines keep working
            kwargs["max_new_tokens"] = max_new_tokens
        with self._batch_hook():
            rows = self.engine.score_prompts(prompts, **kwargs)
        if nan is not None:
            self._record(nan, at_call=self.calls)
            for row in rows:
                for key in ("yes_prob", "no_prob", "relative_prob",
                            "odds_ratio", "first_token_yes_prob",
                            "first_token_no_prob",
                            "first_token_relative_prob"):
                    if key in row:
                        row[key] = float("nan")
        return rows

    def _score_prefixed(self, pairs, targets=("Yes", "No"), legs=None, **kw):
        """Fused-path injection point (installed as ``score_prefixed`` when
        the wrapped engine has one): shares the call counter and fault
        schedule with score_prompts — a sweep chunk is one call either
        way — and hooks device-batch launches identically."""
        self.calls += 1
        nan = self._take(at_call=self.calls, kinds=("nan",))
        self._maybe_fire(at_call=self.calls)
        with self._batch_hook():
            outs = self.engine.score_prefixed(pairs, targets=targets,
                                              legs=legs, **kw)
        if nan is not None:
            self._record(nan, at_call=self.calls)
            for rows in outs:
                for row in rows:
                    for key in ("yes_prob", "no_prob", "relative_prob",
                                "odds_ratio", "first_token_yes_prob",
                                "first_token_no_prob",
                                "first_token_relative_prob"):
                        if key in row:
                            row[key] = float("nan")
        return outs

    def serve_scheduler(self, config=None):
        """Serve-path injection point: a continuous-batching scheduler
        (serve/.Scheduler) built over THIS wrapper, so scheduler-driven
        micro-batches launch through the counting/injecting
        ``score_prompts`` / ``score_prefixed`` above — ``at_call`` and
        ``at_batch`` faults fire inside serve launches exactly as they do
        inside sweep calls, and the fault matrix covers the scheduler's
        own recovery paths (OOM → split + queue re-entry, transient →
        in-place retry) with the same schedules."""
        from ..serve import Scheduler

        return Scheduler(self, config)

    def first_token_relative_prob(self, prompts, targets=("Yes", "No"),
                                  top_filter: int = 0):
        self.calls += 1
        nan = self._take(at_call=self.calls, kinds=("nan",))
        self._maybe_fire(at_call=self.calls)
        with self._batch_hook():
            out = self.engine.first_token_relative_prob(
                prompts, targets=targets, top_filter=top_filter)
        if nan is not None:
            self._record(nan, at_call=self.calls)
            out = out * float("nan")
        return out

    # -- scheduling ------------------------------------------------------

    def _take(self, at_call: int = 0, at_batch: int = 0,
              kinds: Sequence[str] = ("oom", "transient", "preempt")
              ) -> Optional[Fault]:
        for f in self.faults:
            if f.fired >= f.times or f.kind not in kinds:
                continue
            if at_call and f.at_call == at_call:
                f.fired += 1
                return f
            if at_batch and f.at_batch == at_batch:
                f.fired += 1
                return f
        return None

    def _record(self, fault: Fault, **where):
        self.injected.append({"kind": fault.kind, **where})

    def _maybe_fire(self, at_call: int = 0, at_batch: int = 0) -> None:
        fault = self._take(at_call=at_call, at_batch=at_batch)
        if fault is None:
            return
        self._record(fault, at_call=at_call, at_batch=at_batch)
        if fault.kind == "oom":
            raise injected_oom_error()
        if fault.kind == "transient":
            from ..runtime.faults import TransientError

            raise TransientError("injected transient fault (FaultyEngine)")
        if fault.kind == "preempt":
            from ..runtime.faults import Preempted

            handler = signal.getsignal(signal.SIGTERM)
            if callable(handler):
                # a real handler is installed (e.g. PreemptionGuard): deliver
                # the actual signal so its flush path runs; the handler's
                # raise surfaces out of the sleep below
                os.kill(os.getpid(), signal.SIGTERM)
                deadline = time.monotonic() + 2.0
                while time.monotonic() < deadline:
                    time.sleep(0.01)  # handler raises from in here
            # SIG_DFL/SIG_IGN would kill (or ignore in) the test runner;
            # simulate the preemption exit instead
            raise Preempted(signal.SIGTERM)
