"""Retry / backoff primitives.

Behavioral spec from the reference's ``retry_with_exponential_backoff``
(/root/reference/analysis/perturb_prompts.py:72-106): up to 10 retries, initial
delay 60 s doubling to a 300 s cap, multiplicative jitter in [0.8, 1.2], retry
on rate-limit/transient errors, re-raise after exhaustion.  Here it is a
decorator factory with injectable sleep/rng so tests run instantly.
"""

from __future__ import annotations

import functools
import random
import time
from dataclasses import dataclass, field
from typing import Callable, Optional, Tuple, Type


@dataclass
class RetryPolicy:
    max_retries: int = 10
    initial_delay: float = 60.0
    max_delay: float = 300.0
    exponential_base: float = 2.0
    jitter: Tuple[float, float] = (0.8, 1.2)
    retry_on: Tuple[Type[BaseException], ...] = (Exception,)
    # Optional value-level filter consulted AFTER the class check: the error
    # retries only when isinstance(err, retry_on) AND retry_predicate(err).
    # Lets one policy retry e.g. only transient errors (runtime/faults.py
    # is_transient) without enumerating wrapper exception classes.
    retry_predicate: Optional[Callable[[BaseException], bool]] = None
    sleep: Callable[[float], None] = field(default=time.sleep)
    rng: random.Random = field(default_factory=random.Random)
    # Full jitter (AWS-style): the delay is uniform in [0, clamped base]
    # instead of base * uniform(jitter).  Decorrelates N callers that
    # failed at the same instant — the failing-over-fleet case where
    # multiplicative jitter still produces a thundering herd on the
    # rebuilt replica (all N delays land within +-20% of each other).
    full_jitter: bool = False

    def delay_for_attempt(self, attempt: int) -> float:
        base = min(self.initial_delay * self.exponential_base**attempt, self.max_delay)
        if self.full_jitter:
            return self.rng.uniform(0.0, base)
        lo, hi = self.jitter
        # clamp AFTER jitter: with hi > 1 the old order let the delay
        # exceed max_delay on every capped attempt
        return min(base * self.rng.uniform(lo, hi), self.max_delay)


def retry_with_exponential_backoff(policy: RetryPolicy | None = None, **overrides):
    """Decorator: retry the wrapped callable per ``policy``.

    ``retry_with_exponential_backoff()`` with no args reproduces the reference
    defaults.  Keyword overrides build a fresh policy.
    """
    if callable(policy) and not isinstance(policy, RetryPolicy):
        # Bare-decorator form: @retry_with_exponential_backoff with no call.
        fn, policy = policy, RetryPolicy()
        return retry_with_exponential_backoff(policy)(fn)
    if policy is None:
        policy = RetryPolicy(**overrides)
    elif overrides:
        raise ValueError("pass either a policy or overrides, not both")

    def decorator(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            last_err = None
            for attempt in range(policy.max_retries + 1):
                try:
                    return fn(*args, **kwargs)
                except policy.retry_on as err:  # noqa: PERF203
                    last_err = err
                    if attempt == policy.max_retries:
                        break
                    # consulted only when a retry would actually happen, so
                    # a recording predicate (faults.retry_transient) never
                    # logs a retry for the final, propagating failure
                    if (policy.retry_predicate is not None
                            and not policy.retry_predicate(err)):
                        raise
                    policy.sleep(policy.delay_for_attempt(attempt))
            raise last_err

        return wrapper

    return decorator


class RateLimiter:
    """Token-bucket rate limiter (reference: ``RateLimitTracker``
    perturb_prompts_gemini.py:43-78 and ``rate_limit_wait``
    perturb_prompts_gemini_parallel.py:30-64).  Thread-safe."""

    def __init__(self, requests_per_second: float, clock=time.monotonic, sleep=time.sleep):
        import threading

        self._interval = 1.0 / requests_per_second
        self._clock = clock
        self._sleep = sleep
        self._lock = threading.Lock()
        self._next_slot = clock()

    def acquire(self) -> float:
        """Block until a request slot is available; return the wait incurred."""
        with self._lock:
            now = self._clock()
            wait = max(0.0, self._next_slot - now)
            self._next_slot = max(now, self._next_slot) + self._interval
        if wait:
            self._sleep(wait)
        return wait
