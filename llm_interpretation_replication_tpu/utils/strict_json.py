"""Strict-JSON dumping: non-finite floats become null, not bare NaN tokens.

``json.dump`` emits literal ``NaN``/``Infinity`` for non-finite floats
(allow_nan default), which jq / JavaScript ``JSON.parse`` / strict parsers
reject.  Analysis artifacts routinely contain NaN statistics (all-error
groups, empty subsets), so every artifact writer sanitizes through here.
"""

from __future__ import annotations

import json
import math
import os


def nan_to_null(obj):
    """Recursively replace non-finite floats (incl. numpy scalars) with None."""
    if isinstance(obj, dict):
        return {k: nan_to_null(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [nan_to_null(v) for v in obj]
    if isinstance(obj, float):
        return obj if math.isfinite(obj) else None
    if hasattr(obj, "dtype") and getattr(obj, "ndim", None) == 0:
        val = float(obj)
        return val if math.isfinite(val) else None
    return obj


def dump_strict(obj, path: str, indent: int = 2) -> str:
    """Write ``obj`` as strict JSON (parent dirs created, utf-8, NaN→null)."""
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(nan_to_null(obj), f, indent=indent, default=float)
    return path
