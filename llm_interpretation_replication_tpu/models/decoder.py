"""Decoder-only transformer forward pass — pure-functional JAX.

TPU-first re-design of the reference's HF/CUDA inference path
(run_base_vs_instruct_100q.py:279-392, compare_instruct_models.py:171-293):
instead of per-prompt ``model.generate`` crossing the Python↔device boundary
every token, the whole model is one jit-compiled function over a padded batch.

Design notes (see SURVEY.md §7):
- Layer parameters are **stacked along a leading L axis** and the block loop is
  a ``lax.scan`` — one compiled block body regardless of depth, fast XLA
  compiles, and clean GSPMD sharding (the L axis is never sharded).
- Multi/grouped-query attention is native (Falcon MQA num_kv=1, Mistral GQA 8).
- Rotary (NeoX partial-dim and LLaMA full-dim rotate-half, GPT-J/ChatGLM2
  interleaved, GLM-4 hybrid — see ``apply_rotary``), ALiBi (BLOOM, MPT,
  Baichuan-13B), and learned positions (OPT, +2 offset) are all supported.
- Attention softmax and the final logits run in fp32 regardless of the compute
  dtype; matmuls run in the params' dtype (bf16 on TPU) to stay on the MXU.
- Greedy decode keeps a static-shaped KV cache and runs under ``lax.scan`` so
  the 50-token generation of the reference is one device program.

Param pytree layout (converters in models/convert.py produce exactly this):
    embed/tokens            [V, H]
    embed/pos               [P, H]            (learned positions only)
    embed/ln/{scale,bias}   [H]               (BLOOM embedding layernorm)
    layers/ln1/{scale,bias} [L, H]
    layers/ln2/{scale,bias} [L, H]            (absent when shared_layernorm)
    layers/attn/{wq,wk,wv}  [L, H, N*D]/[L, H, Nkv*D]  (+ bq,bk,bv)
    layers/attn/wo          [L, N*D, H]       (+ bo)
    layers/mlp/wi           [L, H, F]  (+bi)  ("mlp") | wg/wi/wo ("gated")
    layers/mlp/wo           [L, F, H]  (+bo)
    final_ln/{scale,bias}   [H]
    lm_head                 [H, V]            (absent when tie_word_embeddings)
    lm_head_bias            [V]               (GPT-J only)
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from .config import DecoderConfig
from ..ops import quant

NEG_INF = -1e9  # mask value; large but finite so fp32 softmax stays NaN-free


# ---------------------------------------------------------------------------
# Primitives
# ---------------------------------------------------------------------------

def layer_norm(x, scale, bias, eps):
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mu), axis=-1, keepdims=True)
    out = (x32 - mu) * lax.rsqrt(var + eps) * scale.astype(jnp.float32)
    if bias is not None:
        out = out + bias.astype(jnp.float32)
    return out.astype(x.dtype)


def rms_norm(x, scale, eps):
    x32 = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    return (x32 * lax.rsqrt(var + eps) * scale.astype(jnp.float32)).astype(x.dtype)


def _norm(cfg: DecoderConfig, x, p):
    if cfg.norm_type == "rmsnorm":
        return rms_norm(x, p["scale"], cfg.norm_eps)
    return layer_norm(x, p["scale"], p.get("bias"), cfg.norm_eps)


def activation(name: str, x):
    if name == "gelu":
        return jax.nn.gelu(x, approximate=False)
    if name == "gelu_new":
        return jax.nn.gelu(x, approximate=True)
    if name == "silu":
        return jax.nn.silu(x)
    if name == "relu":
        return jax.nn.relu(x)
    raise ValueError(name)


def rotary_embedding(positions, dim: int, theta: float, dtype=jnp.float32):
    """Return (sin, cos) of shape [..., dim/2] for the given positions."""
    inv_freq = 1.0 / (theta ** (jnp.arange(0, dim, 2, dtype=jnp.float32) / dim))
    angles = positions[..., None].astype(jnp.float32) * inv_freq  # [..., dim/2]
    return jnp.sin(angles).astype(dtype), jnp.cos(angles).astype(dtype)


def apply_rotary(x, sin, cos, rotary_dim: int, style: str = "half"):
    """RoPE on the first ``rotary_dim`` dims of the head axis.

    x: [B, S, N, D]; sin/cos: [B, S, rotary_dim/2] (broadcast over heads).
    ``style`` picks the pairing convention (DecoderConfig.rotary_style):
    "half" pairs (i, i+rd/2) — LLaMA/NeoX; "interleaved" pairs (2i, 2i+1) —
    GPT-J and ChatGLM2 (HF rotate_every_two); "glm" is HF GLM-4's hybrid:
    rotate-half pairing but frequencies repeat_interleave'd across dims
    (modeling_glm.apply_rotary_pos_emb)."""
    rot, pass_ = x[..., :rotary_dim], x[..., rotary_dim:]
    half = rotary_dim // 2
    sin = sin[:, :, None, :]
    cos = cos[:, :, None, :]
    if style == "half":
        x1, x2 = rot[..., :half], rot[..., half:]
        rotated = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    elif style == "interleaved":
        x1, x2 = rot[..., 0::2], rot[..., 1::2]
        rotated = jnp.stack(
            [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
        ).reshape(rot.shape)
    elif style == "glm":
        cs = jnp.repeat(cos, 2, axis=-1)                     # [.., rd]
        sn = jnp.repeat(sin, 2, axis=-1)
        x1, x2 = rot[..., :half], rot[..., half:]
        rotate_half = jnp.concatenate([-x2, x1], axis=-1)
        rotated = rot * cs + rotate_half * sn
    else:
        raise ValueError(f"unknown rotary style {style!r}")
    return jnp.concatenate([rotated.astype(x.dtype), pass_], axis=-1)


def alibi_slopes(num_heads: int) -> jnp.ndarray:
    """ALiBi per-head slopes (Press et al.; matches HF BLOOM/Falcon)."""
    import math

    def pow2_slopes(n):
        start = 2.0 ** (-(2.0 ** -(math.log2(n) - 3)))
        return [start * (start**i) for i in range(n)]

    if math.log2(num_heads).is_integer():
        slopes = pow2_slopes(num_heads)
    else:
        closest = 2 ** math.floor(math.log2(num_heads))
        slopes = pow2_slopes(closest)
        extra = pow2_slopes(2 * closest)[0::2][: num_heads - closest]
        slopes += extra
    return jnp.asarray(slopes, dtype=jnp.float32)


# ---------------------------------------------------------------------------
# Attention
# ---------------------------------------------------------------------------

def _repeat_kv(x, groups: int):
    """[B, T, Nkv, D] -> [B, T, Nkv*groups, D] for GQA/MQA."""
    if groups == 1:
        return x
    b, t, nkv, d = x.shape
    return jnp.broadcast_to(x[:, :, :, None, :], (b, t, nkv, groups, d)).reshape(
        b, t, nkv * groups, d
    )


def dot_product_attention(q, k, v, bias):
    """q: [B,S,N,D], k/v: [B,T,N,D], bias: broadcastable to [B,N,S,T].

    fp32 softmax; matmuls in input dtype (MXU-friendly bf16 on TPU).
    """
    d = q.shape[-1]
    scores = jnp.einsum("bsnd,btnd->bnst", q, k) / jnp.sqrt(d).astype(q.dtype)
    scores = scores.astype(jnp.float32) + bias
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bnst,btnd->bsnd", probs, v)


def _grouped_scores(q, k):
    """q [B,S,N,D] × unrepeated k [B,T,G,D] → scores [B,G,N/G,S,T]."""
    b, s, n, d = q.shape
    g = k.shape[2]
    qg = q.reshape(b, s, g, n // g, d)
    return jnp.einsum("bsghd,btgd->bghst", qg, k) / jnp.sqrt(d).astype(q.dtype)


def _bias_grouped(bias, b, n, g, s, t):
    bias = jnp.broadcast_to(bias, (b, bias.shape[1], s, t))
    if bias.shape[1] == n:
        return bias.reshape(b, g, n // g, s, t)
    return bias[:, :, None]                        # head-agnostic [B,1,1,S,T]


def grouped_attention_two_block(q, kp, vp, bias_p, kt, vt, bias_t):
    """Attention over TWO K/V blocks with one joint softmax: a large
    read-only block (the prompt KV cache) and a small mutable tail (this
    chunk's generated tokens).  Splitting the softmax flash-attention-style
    (shared running max + denominator) means decode never concatenates new
    K/V onto the cached block, so the big block stays loop-invariant across
    the decode scan — no per-step cache copy, scatter, or relayout."""
    b, s, n, d = q.shape
    g = kp.shape[2]
    sp = _grouped_scores(q, kp).astype(jnp.float32) + _bias_grouped(
        bias_p, b, n, g, s, kp.shape[1]
    )
    st = _grouped_scores(q, kt).astype(jnp.float32) + _bias_grouped(
        bias_t, b, n, g, s, kt.shape[1]
    )
    m = jnp.maximum(sp.max(-1, keepdims=True), st.max(-1, keepdims=True))
    ep = jnp.exp(sp - m)
    et = jnp.exp(st - m)
    denom = ep.sum(-1, keepdims=True) + et.sum(-1, keepdims=True)
    op = jnp.einsum("bghst,btgd->bsghd", (ep / denom).astype(q.dtype), vp)
    ot = jnp.einsum("bghst,btgd->bsghd", (et / denom).astype(q.dtype), vt)
    return (op + ot).reshape(b, s, n, d)


def make_attention_bias(
    cfg: DecoderConfig,
    q_positions,      # [B, S] absolute position of each query token
    kv_positions,     # [B, T] absolute position of each key slot
    kv_valid,         # [B, T] bool: key slot holds a real token
):
    """Additive fp32 bias [B, N_or_1, S, T]: causal + padding + sliding window
    (+ ALiBi when configured)."""
    causal = q_positions[:, :, None] >= kv_positions[:, None, :]          # [B,S,T]
    mask = causal & kv_valid[:, None, :]
    if cfg.sliding_window is not None:
        mask &= q_positions[:, :, None] - kv_positions[:, None, :] < cfg.sliding_window
    bias = jnp.where(mask[:, None, :, :], 0.0, NEG_INF).astype(jnp.float32)
    if cfg.position_embedding == "alibi":
        slopes = alibi_slopes(cfg.num_heads)  # [N]
        # HF BLOOM computes the ALiBi distance from the *key* position relative
        # to the final query so rows differ only via the causal mask; the
        # equivalent per-(i,j) form is slope * -(i - j) for j <= i.
        dist = (q_positions[:, :, None] - kv_positions[:, None, :]).astype(jnp.float32)
        bias = bias - slopes[None, :, None, None] * dist[:, None, :, :]
    return bias


class KVCache(NamedTuple):
    """Read-only K/V block for decode.

    ``positions``/``valid`` make the slot→position mapping explicit so the
    cache can hold ragged content: prompt slots (slot index == position for
    right-padded rows) and, after a decode chunk, per-row generated slots at
    ragged positions.  Decode NEVER writes into these arrays — new K/V
    accumulate in a small per-chunk tail and are concatenated once per chunk
    (decode_steps) — so XLA keeps one loop-invariant buffer instead of
    round-tripping a ~700 MB cache through every step (the scatter-based
    cache cost a full-cache relayout loop, ~150-310 ms/batch, on v5e).

    With ``DecoderConfig.kv_cache_dtype == "int8"`` the k/v blocks store
    int8 codes and ``k_scale``/``v_scale`` carry the per-head symmetric
    fp32 scales (ops/quant.quantize_kv: one scale per (layer, row, slot,
    head) — absmax over head_dim).  Quantization happens ON APPEND — the
    prefill scan body, extend_prefill's suffix block, and decode_steps'
    end-of-chunk tail fold — so every slot is quantized exactly once and
    the full-precision cache never materializes.  Readers dequantize at
    the attention op (ops/attention.cache_extend_attention, the decode
    two-block path).  ``None`` scales mean the bf16 bit-parity layout."""
    k: jnp.ndarray          # [L, B, T, Nkv, D] (compute dtype, or int8)
    v: jnp.ndarray          # [L, B, T, Nkv, D]
    positions: jnp.ndarray  # [B, T] int32 absolute position of each slot
    valid: jnp.ndarray      # [B, T] bool: slot holds a real token
    length: jnp.ndarray     # [] int32 — slots filled so far
    k_scale: Optional[jnp.ndarray] = None  # [L, B, T, Nkv] fp32 (int8 only)
    v_scale: Optional[jnp.ndarray] = None  # [L, B, T, Nkv] fp32 (int8 only)


def cache_kv_map(cache: KVCache, f, **replace) -> KVCache:
    """Apply ``f`` to the cache's k/v blocks AND (when quantized) their
    scale arrays, returning a cache with any extra ``replace`` fields set.

    ``f`` must act only on the leading ``[L, B, T, ...]`` axes the two
    layouts share (gather rows on axis 1, pad/concat slots on axis 2) —
    the one spelling every cache-reshaping call site (engine row gather,
    pool padding, slice selection) uses so none can forget the scales."""
    return cache._replace(
        k=f(cache.k), v=f(cache.v),
        k_scale=None if cache.k_scale is None else f(cache.k_scale),
        v_scale=None if cache.v_scale is None else f(cache.v_scale),
        **replace)


def _deq(x, scale, dtype):
    """Cache block -> compute dtype: dequantize when per-head scales are
    present (int8 cache), plain cast otherwise."""
    if scale is None:
        return x.astype(dtype)
    return quant.dequantize_kv(x, scale, dtype)


def _quantize_append(cfg: DecoderConfig, k, v):
    """Quantize-on-append hook: (k, v, k_scale|None, v_scale|None) in the
    cache's storage layout for a freshly-computed K/V block."""
    if cfg.kv_cache_dtype != "int8":
        return k, v, None, None
    kq, ks = quant.quantize_kv(k)
    vq, vs = quant.quantize_kv(v)
    return kq, vq, ks, vs




# ---------------------------------------------------------------------------
# Block + full forward
# ---------------------------------------------------------------------------

def _attn(cfg: DecoderConfig, lp, x, sin_cos, bias, cache_len=None,
          flash_lengths=None):
    """One attention sub-block.  When ``cache_len`` is given, the prompt K/V
    are zero-padded out to that many slots and returned as this layer's KV
    cache (a pad, NOT a dynamic-update-slice into a zeros buffer — the DUS
    form made XLA pick a T-minor cache layout that cost a full-cache relayout
    loop, ~309 ms at sweep shapes, before every decode).  When
    ``flash_lengths`` is given (no-cache path only), the Pallas flash kernel
    replaces the dense bias-based attention."""
    b, s, h = x.shape
    n, nkv, d = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    ap = lp["attn"]
    q = quant.linear(ap, "wq", x)
    k = quant.linear(ap, "wk", x)
    v = quant.linear(ap, "wv", x)
    if "bq" in ap:
        q, k, v = q + ap["bq"], k + ap["bk"], v + ap["bv"]
    q = q.reshape(b, s, n, d)
    k = k.reshape(b, s, nkv, d)
    v = v.reshape(b, s, nkv, d)
    if sin_cos is not None:
        sin, cos = sin_cos
        rd = int(cfg.rotary_pct * d) // 2 * 2
        q = apply_rotary(q, sin, cos, rd, cfg.rotary_style)
        k = apply_rotary(k, sin, cos, rd, cfg.rotary_style)
    if cache_len is not None:
        pad = ((0, 0), (0, cache_len - s), (0, 0), (0, 0))
        new_cache = (jnp.pad(k, pad), jnp.pad(v, pad))
        if flash_lengths is None:
            # dense path attends over the whole (zero-padded) cache; the
            # flash path below attends over the prompt K/V directly —
            # equivalent, since unwritten cache slots are masked anyway
            k, v = new_cache
    else:
        new_cache = None
    if flash_lengths is not None:
        from ..ops.attention import attention_bsnd

        # layout-native dispatcher: the causal block-skipping Pallas kernel
        # consumes the projection layout ([B, S, N, D] queries, UNREPEATED
        # [B, S, G, D] K/V) directly — no head-major transpose of the big
        # q/out tensors, K/V read once from VMEM per group.  Works for the
        # cached prompt forward too (greedy_decode's first phase), which
        # would otherwise materialize both the S×T bias and S×T scores.
        out = attention_bsnd(q, k, v, flash_lengths, causal=True)
    else:
        k = _repeat_kv(k, n // nkv)
        v = _repeat_kv(v, n // nkv)
        out = dot_product_attention(q, k, v, bias)
    out = quant.linear(ap, "wo", out.reshape(b, s, n * d))
    if "bo" in ap:
        out = out + ap["bo"]
    return out, new_cache


def _mlp(cfg: DecoderConfig, lp, x):
    mp = lp["mlp"]
    if cfg.mlp_type == "gated":
        gate = quant.linear(mp, "wg", x)
        up = quant.linear(mp, "wi", x)
        if "bg" in mp:
            gate, up = gate + mp["bg"], up + mp["bi"]
        hidden = activation(cfg.activation, gate) * up
    else:
        hidden = quant.linear(mp, "wi", x)
        if "bi" in mp:
            hidden = hidden + mp["bi"]
        hidden = activation(cfg.activation, hidden)
    out = quant.linear(mp, "wo", hidden)
    if "bo" in mp:
        out = out + mp["bo"]
    return out


def _block(cfg: DecoderConfig, lp, x, sin_cos, bias, cache_len=None,
           flash_lengths=None):
    ln1_out = _norm(cfg, x, lp["ln1"])
    attn_out, new_cache = _attn(cfg, lp, ln1_out, sin_cos, bias, cache_len,
                                flash_lengths)
    if cfg.parallel_residual:
        # NeoX/Falcon: mlp reads the same (or its own) LN of the block input.
        mlp_in = ln1_out if cfg.shared_layernorm else _norm(cfg, x, lp["ln2"])
        x = x + attn_out + _mlp(cfg, lp, mlp_in)
    else:
        x = x + attn_out
        x = x + _mlp(cfg, lp, _norm(cfg, x, lp["ln2"]))
    return x, new_cache


def _embed(cfg: DecoderConfig, params, token_ids, positions):
    x = jnp.take(params["embed"]["tokens"], token_ids, axis=0)
    if cfg.position_embedding == "learned":
        x = x + jnp.take(
            params["embed"]["pos"], positions + cfg.learned_pos_offset, axis=0
        )
    if cfg.embedding_layernorm:
        ln = params["embed"]["ln"]
        x = layer_norm(x, ln["scale"], ln["bias"], cfg.norm_eps)
    return x


def _unembed_hidden(cfg: DecoderConfig, params, x):
    """(final-normed hidden, fp32 logits) — the two halves of
    :func:`_unembed`.  The K-token verify path (``k_verify_block``) needs
    the hidden its K-head proposals project from AND the logits, computed
    by exactly the ops every other path runs, so the split lives here and
    ``_unembed`` stays a thin wrapper (bit-identical by construction)."""
    if cfg.final_norm:
        x = _norm(cfg, x, params["final_ln"])
    table = params.get("lm_head")
    if table is None:
        table = params["embed"]["tokens"].T
    # fp32 ACCUMULATION without fp32 INPUT upcasts: upcasting a bf16 table
    # materializes a 1.2 GB fp32 copy (65k-vocab 7B) on every decode step,
    # and fp32×fp32 MXU matmuls are multi-pass; bf16 products accumulated in
    # fp32 are bit-identical to the products of the upcast values, so
    # preferred_element_type gives the same logits modulo summation order.
    logits = lax.dot_general(
        x, table, (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * cfg.logit_scale
    bias = params.get("lm_head_bias")          # GPT-J ships an lm_head bias
    if bias is not None:
        logits = logits + bias.astype(jnp.float32)
    return x, logits


def _unembed(cfg: DecoderConfig, params, x):
    return _unembed_hidden(cfg, params, x)[1]


def run_layers(cfg: DecoderConfig, layers, x, positions, attention_mask):
    """Rotary setup + flash/dense attention dispatch + scan over stacked
    ``layers``.  The one shared per-layer driver for the full-trunk path and
    the pipeline-parallel stage (parallel/pipeline.py) — change attention
    dispatch here and both paths move together."""
    mask = attention_mask.astype(bool)
    sin_cos = None
    if cfg.position_embedding == "rotary":
        rd = int(cfg.rotary_pct * cfg.head_dim) // 2 * 2
        sin_cos = rotary_embedding(positions, rd, cfg.rope_theta, x.dtype)
    use_flash = cfg.use_flash_attention(x.shape[1])
    bias = None if use_flash else make_attention_bias(cfg, positions, positions, mask)
    flash_lengths = jnp.sum(attention_mask, axis=-1).astype(jnp.int32) if use_flash else None

    def body(h, lp):
        h, _ = _block(cfg, lp, h, sin_cos, bias, None, flash_lengths)
        return h, None

    out, _ = lax.scan(body, x, layers)
    return out


def _trunk(params, cfg: DecoderConfig, token_ids, attention_mask,
           cache_len: Optional[int] = None):
    """Embed + blocks.  Returns (hidden [B,S,H], cache | None)."""
    b, s = token_ids.shape
    mask = attention_mask.astype(bool)
    positions = jnp.cumsum(attention_mask, axis=-1) - 1  # right-padded prompts
    positions = jnp.maximum(positions, 0)
    x = _embed(cfg, params, token_ids, positions)

    if cache_len is None:
        return run_layers(cfg, params["layers"], x, positions, attention_mask), None

    sin_cos = None
    if cfg.position_embedding == "rotary":
        rd = int(cfg.rotary_pct * cfg.head_dim) // 2 * 2
        sin_cos = rotary_embedding(positions, rd, cfg.rope_theta, params["embed"]["tokens"].dtype)

    t = cache_len
    # Slot index == position for right-padded rows: ONE definition of the
    # cache's slot→position mapping, used by both the dense prompt bias and
    # the returned KVCache (decode rebuilds biases from these fields).
    kv_positions = jnp.broadcast_to(jnp.arange(t)[None, :], (b, t))
    kv_valid = jnp.pad(mask, ((0, 0), (0, t - s)))
    # The prompt forward honors flash/auto here too — the dense cached path
    # materializes BOTH an S×T bias and S×T scores, exactly the HBM blowup
    # 'auto' exists to avoid on long prompts.  Decode steps (S=1) stay dense.
    use_flash = cfg.use_flash_attention(s)
    flash_lengths = (jnp.sum(attention_mask, axis=-1).astype(jnp.int32)
                     if use_flash else None)
    bias = (None if use_flash
            else make_attention_bias(cfg, positions, kv_positions, kv_valid))

    def body(h, lp):
        h, (ck, cv) = _block(cfg, lp, h, sin_cos, bias, t, flash_lengths)
        # quantize-on-append INSIDE the scan body: the stacked cache the
        # scan emits is already int8 + scales, so the full-precision
        # [L, B, T, G, D] block never materializes (the attention above
        # still read this layer's exact bf16 K/V — quantization touches
        # storage only, prompt logits stay bit-identical)
        return h, _quantize_append(cfg, ck, cv)

    x, (ks, vs, kss, vss) = lax.scan(body, x, params["layers"])
    lengths = jnp.sum(attention_mask, axis=-1)  # [B] per-row prompt length
    cache = KVCache(
        k=ks, v=vs, positions=kv_positions, valid=kv_valid,
        length=jnp.max(lengths).astype(jnp.int32),
        k_scale=kss, v_scale=vss,
    )
    return x, cache


@functools.partial(jax.jit, static_argnames=("cfg",))
def forward(
    params,
    cfg: DecoderConfig,
    token_ids,                 # [B, S] int32, right-padded
    attention_mask,            # [B, S] 1 for real tokens
):
    """Full-sequence forward: fp32 logits [B, S, V].  (Decode flows start
    from :func:`prefill`, which returns last-position logits + KV cache.)"""
    x, _ = _trunk(params, cfg, token_ids, attention_mask, None)
    return _unembed(cfg, params, x)


@functools.partial(jax.jit, static_argnames=("cfg",))
def forward_last_logits(params, cfg: DecoderConfig, token_ids, attention_mask):
    """fp32 logits at each row's LAST real position only — [B, V].

    The sweep's hot op: avoids materializing the [B, S, V] fp32 logit tensor
    (1 GB at B=16, S=256, V=65k) that full-sequence unembedding would cost.
    """
    x, _ = _trunk(params, cfg, token_ids, attention_mask, None)
    lengths = jnp.sum(attention_mask, axis=-1)
    last = jnp.take_along_axis(x, (lengths - 1)[:, None, None], axis=1)  # [B,1,H]
    return _unembed(cfg, params, last)[:, 0, :]


@functools.partial(jax.jit, static_argnames=("cfg",))
def forward_anchor_logits(params, cfg: DecoderConfig, token_ids,
                          attention_mask, anchors):
    """fp32 logits at K anchor positions per row — [B, K, V].

    The packed-batch-prompting hot op (scoring/packed.py): one packed row
    carries Q questions, each ending at an anchor token whose next-token
    logits score its answer.  Gathering the hidden states at the anchors
    and unembedding ONLY those K positions keeps the logit transient at
    [B, K, V] — the [B, S, V] full-sequence unembed would be ~1 GB at
    sweep shapes, and :func:`forward_last_logits` can only read one
    position per row.  ``anchors``: [B, K] int32 token indices (within
    each row's real length; padded anchor slots may duplicate a real
    anchor — callers mask them host-side)."""
    x, _ = _trunk(params, cfg, token_ids, attention_mask, None)
    h = jnp.take_along_axis(x, anchors[:, :, None], axis=1)   # [B, K, H]
    return _unembed(cfg, params, h)


def _prefill_impl(params, cfg: DecoderConfig, token_ids, attention_mask, cache_len):
    """Prompt forward with KV cache; logits at each row's last real token."""
    x, cache = _trunk(params, cfg, token_ids, attention_mask, cache_len)
    lengths = jnp.sum(attention_mask, axis=-1)  # [B]
    # Hidden state at the last real prompt token predicts the first generated
    # token; unembed only there (full [B,S,V] fp32 logits would be ~1 GB for
    # 7B-vocab models at sweep batch sizes).
    last_h = jnp.take_along_axis(x, (lengths - 1)[:, None, None], axis=1)
    last = _unembed(cfg, params, last_h)[:, 0, :]
    return last, cache


def _attn_extend(cfg: DecoderConfig, lp, x, sin_cos, bias, kp_l, vp_l,
                 ks_l=None, vs_l=None):
    """Attention sub-block for a suffix-extension prefill: queries are the
    whole suffix (S > 1, known tokens — no sequential dependency), keys are
    the read-only prefix cache slice plus the suffix's own K/V, softmaxed
    jointly (ops/attention.cache_extend_attention — which also owns the
    dequant when the prefix block is int8: ``ks_l``/``vs_l`` are this
    layer's per-head scales).  Returns the suffix's K/V so the caller can
    concatenate them onto the cache for decode."""
    from ..ops.attention import cache_extend_attention

    b, s, h = x.shape
    n, nkv, d = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    ap = lp["attn"]
    q = quant.linear(ap, "wq", x)
    k = quant.linear(ap, "wk", x)
    v = quant.linear(ap, "wv", x)
    if "bq" in ap:
        q, k, v = q + ap["bq"], k + ap["bk"], v + ap["bv"]
    q = q.reshape(b, s, n, d)
    k = k.reshape(b, s, nkv, d)
    v = v.reshape(b, s, nkv, d)
    if sin_cos is not None:
        sin, cos = sin_cos
        rd = int(cfg.rotary_pct * d) // 2 * 2
        q = apply_rotary(q, sin, cos, rd, cfg.rotary_style)
        k = apply_rotary(k, sin, cos, rd, cfg.rotary_style)
    # dequant-or-cast of the prefix block happens inside the attention op
    # (ONE spelling of the rule, shared with every reader)
    out = cache_extend_attention(q, kp_l, vp_l, k, v, bias,
                                 kp_scale=ks_l, vp_scale=vs_l)
    out = quant.linear(ap, "wo", out.reshape(b, s, n * d))
    if "bo" in ap:
        out = out + ap["bo"]
    return out, (k, v)


def _block_extend(cfg: DecoderConfig, lp, x, sin_cos, bias, kp_l, vp_l,
                  ks_l=None, vs_l=None):
    ln1_out = _norm(cfg, x, lp["ln1"])
    attn_out, new_kv = _attn_extend(cfg, lp, ln1_out, sin_cos, bias, kp_l,
                                    vp_l, ks_l, vs_l)
    if cfg.parallel_residual:
        mlp_in = ln1_out if cfg.shared_layernorm else _norm(cfg, x, lp["ln2"])
        x = x + attn_out + _mlp(cfg, lp, mlp_in)
    else:
        x = x + attn_out
        x = x + _mlp(cfg, lp, _norm(cfg, x, lp["ln2"]))
    return x, new_kv


@functools.partial(jax.jit, static_argnames=("cfg",))
def extend_prefill(params, cfg: DecoderConfig, cache: KVCache, token_ids,
                   attention_mask, prefix_lengths):
    """Suffix-extension prefill: run the trunk over ``token_ids`` ([B, S]
    right-padded suffix tokens) attending over a prefilled prefix
    :class:`KVCache` — the prefix-reuse half of the engine's fused two-leg
    scoring (runtime/engine.score_prefixed).  Each leg's short format
    suffix extends the SAME prefix cache instead of re-running the full
    prompt forward, cutting per-row prefill FLOPs nearly in half for the
    full-study row contract.

    Suffix token j of row b sits at absolute position
    ``prefix_lengths[b] + j``; the returned cache appends the suffix block's
    K/V and slot->position mapping onto the prefix cache, so
    :func:`decode_steps` continues from it exactly as from :func:`prefill`'s
    output.  The caller must not mutate the input ``cache`` — the returned
    cache shares its buffers (a concatenate, not a copy of the prefix).

    Returns (last_logits [B, V] fp32 at each row's last real suffix token,
    extended KVCache, total_lengths [B] = prefix + suffix real tokens).
    """
    b, s = token_ids.shape
    mask = attention_mask.astype(bool)
    rel = jnp.maximum(jnp.cumsum(attention_mask, axis=-1) - 1, 0)
    positions = prefix_lengths[:, None] + rel                       # [B, S]
    x = _embed(cfg, params, token_ids, positions)
    sin_cos = None
    if cfg.position_embedding == "rotary":
        rd = int(cfg.rotary_pct * cfg.head_dim) // 2 * 2
        sin_cos = rotary_embedding(positions, rd, cfg.rope_theta,
                                   params["embed"]["tokens"].dtype)
    # One bias over the CONCATENATED key axis (prefix slots then suffix
    # slots): make_attention_bias's position comparison yields causal
    # masking within the suffix and full visibility of the valid prefix —
    # the same mask the unfused full-prompt prefill builds, just laid out
    # over cache slots.
    kv_positions = jnp.concatenate([cache.positions, positions], axis=1)
    kv_valid = jnp.concatenate([cache.valid, mask], axis=1)
    bias = make_attention_bias(cfg, positions, kv_positions, kv_valid)
    # structure checks only (trace-time Python on pytree layout, never on
    # traced values): the scale fields are None or arrays, decided by how
    # the cache was built
    if (cache.k_scale is not None) != (cfg.kv_cache_dtype == "int8"):
        # a mismatch would concat int8 codes into a bf16 block (or vice
        # versa) and silently corrupt every later read — fail loudly
        raise ValueError(
            f"cache quantization "
            f"({'int8' if cache.k_scale is not None else 'bf16'}) does "
            f"not match cfg.kv_cache_dtype={cfg.kv_cache_dtype!r}")

    # quantize-on-append inside both bodies: the suffix block's K/V enter
    # the cache in the cache's own storage layout (attention reads the
    # exact values); the body variant is picked at trace time on the
    # cache's pytree STRUCTURE, never on traced values
    if cache.k_scale is None:
        def body(h, xs):
            lp, kp_l, vp_l = xs
            h, (k_s, v_s) = _block_extend(cfg, lp, h, sin_cos, bias,
                                          kp_l, vp_l)
            return h, _quantize_append(cfg, k_s, v_s)

        xs = (params["layers"], cache.k, cache.v)
    else:
        def body(h, xs):
            lp, kp_l, vp_l, ks_l, vs_l = xs
            h, (k_s, v_s) = _block_extend(cfg, lp, h, sin_cos, bias,
                                          kp_l, vp_l, ks_l, vs_l)
            return h, _quantize_append(cfg, k_s, v_s)

        xs = (params["layers"], cache.k, cache.v, cache.k_scale,
              cache.v_scale)
    with jax.named_scope("extend_prefill"):  # profiler attribution (obs/)
        x, (ks, vs, kss, vss) = lax.scan(body, x, xs)
    suffix_lengths = jnp.sum(attention_mask, axis=-1)
    last_h = jnp.take_along_axis(x, (suffix_lengths - 1)[:, None, None], axis=1)
    last = _unembed(cfg, params, last_h)[:, 0, :]
    new_cache = KVCache(
        k=jnp.concatenate([cache.k, ks.astype(cache.k.dtype)], axis=2),
        v=jnp.concatenate([cache.v, vs.astype(cache.v.dtype)], axis=2),
        positions=kv_positions, valid=kv_valid,
        length=cache.length + s,
        k_scale=(None if kss is None
                 else jnp.concatenate([cache.k_scale, kss], axis=2)),
        v_scale=(None if vss is None
                 else jnp.concatenate([cache.v_scale, vss], axis=2)),
    )
    return last, new_cache, prefix_lengths + suffix_lengths


@functools.partial(jax.jit, static_argnames=("cfg", "cache_len"))
def prefill(params, cfg: DecoderConfig, token_ids, attention_mask, cache_len: int):
    """Phase-1 of the two-phase sweep: one prompt forward that returns BOTH the
    position-0 logits (enough to settle every row whose top-k already contains
    a target — the reference reads position 0 for those rows,
    run_base_vs_instruct_100q.py:349-364) AND the KV cache, so rows that do
    need look-ahead continue via :func:`decode_steps` without re-running the
    prompt.

    Returns (last_logits [B, V] fp32, KVCache padded to ``cache_len``).
    """
    # named_scope carries into the HLO op metadata: a --profile capture
    # (obs/) attributes this program's ops to "prefill" on the device
    # timeline, where host-side spans cannot see
    with jax.named_scope("prefill"):
        return _prefill_impl(params, cfg, token_ids, attention_mask,
                             cache_len)


def chunked_prefill(params, cfg: DecoderConfig, token_ids, attention_mask,
                    chunk: int):
    """Prompt forward in fixed-size chunks: chunk 0 runs the ordinary
    :func:`prefill`, every later chunk replays through the suffix-extension
    prefill (:func:`extend_prefill`) against the cache built so far.

    The monolithic prompt forward materializes ``[B, S, S]``-shaped
    attention transients (fp32 bias + scores per layer step) — at the long
    buckets that transient, not FLOPs, is what throttles the sweep (430-
    token buckets measured 36.8 p/s vs 128.7 at 104 tokens).  Chunking
    bounds the query axis at ``chunk``: the widest attention transient
    becomes ``[B, chunk, S]`` and peak activations scale with ``chunk``
    instead of the bucket length (runtime/plan.py budgets exactly this —
    the ``prefill_chunk`` term).  Each chunk is its own device program; no
    host fetch happens between chunks, so the launch loop stays legal
    inside strict mode's transfer guard and the pipeline never drains.

    Equivalence: a chunk's queries attend over the concatenated (prefix
    cache + own K/V) key axis under ONE joint softmax with the same
    position/validity mask the monolithic forward builds, and masked slots
    contribute exact zeros — so at bf16 KV the chunked forward reproduces
    the monolithic one to reduction-order noise (pinned by the tier-1
    ``-m kvcache`` equivalence test).  With an int8 KV cache, later chunks
    read DEQUANTIZED prefix K/V, so chunking composes with quantization
    under the same documented tolerance (PARITY.md), which is why bf16
    stays the bit-parity default.

    Compile cost: one ``extend_prefill`` executable per (chunk index,
    bucket) pair — the same fan-out discipline as decode_steps' per-chunk
    cache growth, amortized by the persistent compilation cache.

    Returns (last_logits [B, V] fp32 at each row's LAST real token,
    KVCache over all ``S`` slots, n_chunks).
    """
    b, s = token_ids.shape
    c0 = min(int(chunk), s)
    last, cache = prefill(params, cfg, token_ids[:, :c0],
                          attention_mask[:, :c0], cache_len=c0)
    lengths = jnp.sum(attention_mask[:, :c0], axis=-1)
    offset, n_chunks = c0, 1
    while offset < s:
        c = min(int(chunk), s - offset)
        sub_mask = attention_mask[:, offset:offset + c]
        nlast, cache, lengths = extend_prefill(
            params, cfg, cache, token_ids[:, offset:offset + c], sub_mask,
            lengths)
        # rows right-padded out before this chunk have no real suffix token;
        # their answer logits came from the chunk holding their last token
        has = jnp.sum(sub_mask, axis=-1) > 0
        last = jnp.where(has[:, None], nlast, last)
        offset += c
        n_chunks += 1
    return last, cache, n_chunks


#: Candidates kept per step by the REDUCED score mode — the confidence leg's
#: 19-candidate contract (runtime.engine._confidence_topk k=19, itself the
#: API extractors' top-20-logprobs view minus the sampled token).  Any yes/no
#: scan with top_k <= this reads its threshold from the kept candidates.
REDUCED_TOPK = 19


class ReducedScores(NamedTuple):
    """Per-step score statistics that replace the stacked [B, P, V] fp32
    logits when the caller only ever reads (a) target-token probabilities,
    (b) top-k membership, and (c) top-19 candidates — i.e. everything
    scoring.yes_no and the confidence leg consume.  ~1600x smaller than the
    full score tensor (a measured ~580 MB per in-flight batch at the
    full-study sweep's shapes), which is what capped the sweep's batch size.
    """
    topk_vals: jnp.ndarray      # [B, P, REDUCED_TOPK] fp32 logits, descending
    topk_ids: jnp.ndarray       # [B, P, REDUCED_TOPK] int32 token ids
    logz: jnp.ndarray           # [B, P] fp32 logsumexp over the vocab
    target_logits: jnp.ndarray  # [B, P, 2] fp32 logits at (yes_id, no_id)


def _reduce_step_scores(logits, target_ids):
    """One step's [B, V] logits -> (vals, ids, logz, tgt) for ReducedScores."""
    sub = logits.astype(jnp.float32)
    logz = jax.scipy.special.logsumexp(sub, axis=-1)
    vals, ids = lax.top_k(sub, REDUCED_TOPK)
    tgt = jnp.take_along_axis(sub, target_ids, axis=-1)
    return vals, ids, logz, tgt


def _decode_steps_impl(params, cfg: DecoderConfig, cache, prev_logits, lengths,
                       offset, num_steps, eos_token_id, done, with_scores,
                       target_ids=None):
    b = prev_logits.shape[0]
    n = num_steps
    quantized = cache.k_scale is not None
    # the in-chunk tail always lives in the COMPUTE dtype (this chunk's
    # attention reads it exactly); an int8 cache quantizes the tail once,
    # at the end-of-chunk fold below
    cdt = params["embed"]["tokens"].dtype if quantized else cache.k.dtype
    tail_shape = (cfg.num_layers, b, n, cfg.num_kv_heads, cfg.head_dim)
    tail_k0 = jnp.zeros(tail_shape, cdt)
    tail_v0 = jnp.zeros(tail_shape, cdt)
    # Tail slot j (for every row) holds the step-j token, generated at
    # per-row position lengths + offset + j.
    tail_positions = lengths[:, None] + offset + jnp.arange(n)[None, :]  # [B,n]
    step_idx = jnp.arange(n)

    def step(carry, i):
        tail_k, tail_v, prev_logits, done = carry
        next_tok = jnp.argmax(prev_logits, axis=-1).astype(jnp.int32)  # [B]
        if eos_token_id is not None:
            next_tok = jnp.where(done, eos_token_id, next_tok)
        pos = lengths + offset + i                                      # [B]
        q_pos = pos[:, None]                                            # [B,1]
        bias_p = make_attention_bias(cfg, q_pos, cache.positions, cache.valid)
        tail_valid = jnp.broadcast_to(step_idx[None, :] <= i, (b, n))
        bias_t = make_attention_bias(cfg, q_pos, tail_positions, tail_valid)
        sin_cos = None
        if cfg.position_embedding == "rotary":
            rd = int(cfg.rotary_pct * cfg.head_dim) // 2 * 2
            sin_cos = rotary_embedding(q_pos, rd, cfg.rope_theta, cdt)
        x = _embed(cfg, params, next_tok[:, None], q_pos)

        def body(carry_h, xs):
            h = carry_h
            if quantized:
                lp, kp_l, vp_l, ks_l, vs_l, tk_l, tv_l = xs
            else:
                (lp, kp_l, vp_l, tk_l, tv_l), ks_l, vs_l = xs, None, None
            h, (tk_l, tv_l) = _block_decode(
                cfg, lp, h, sin_cos, bias_p, bias_t, kp_l, vp_l, tk_l, tv_l,
                i, ks_l, vs_l
            )
            return h, (tk_l, tv_l)

        layer_xs = (
            (params["layers"], cache.k, cache.v, cache.k_scale,
             cache.v_scale, tail_k, tail_v)
            if quantized
            else (params["layers"], cache.k, cache.v, tail_k, tail_v))
        x, (tail_k, tail_v) = lax.scan(body, x, layer_xs)
        step_logits = _unembed(cfg, params, x)[:, 0, :]                 # [B,V]
        if eos_token_id is not None:
            done = done | (next_tok == eos_token_id)
        if with_scores == "reduced":
            out = (next_tok, _reduce_step_scores(prev_logits, target_ids))
        elif with_scores:
            out = (next_tok, prev_logits)
        else:
            out = next_tok
        return (tail_k, tail_v, step_logits, done), out

    (tail_k, tail_v, last_logits, done), out = lax.scan(
        step, (tail_k0, tail_v0, prev_logits, done), jnp.arange(n)
    )
    # One concat per CHUNK (not per step) folds the tail into the read-only
    # block for the next chunk; callers that ignore the returned cache (the
    # scored look-ahead subset) get it DCE'd by XLA.  An int8 cache
    # quantizes the tail here — once per generated token, on append.
    if quantized:
        tail_k, tk_s = quant.quantize_kv(tail_k)
        tail_v, tv_s = quant.quantize_kv(tail_v)
    cache = KVCache(
        k=jnp.concatenate([cache.k, tail_k], axis=2),
        v=jnp.concatenate([cache.v, tail_v], axis=2),
        positions=jnp.concatenate([cache.positions, tail_positions], axis=1),
        valid=jnp.concatenate([cache.valid, jnp.ones((b, n), bool)], axis=1),
        length=cache.length + n,
        k_scale=(jnp.concatenate([cache.k_scale, tk_s], axis=2)
                 if quantized else None),
        v_scale=(jnp.concatenate([cache.v_scale, tv_s], axis=2)
                 if quantized else None),
    )
    if with_scores == "reduced":
        tokens, (s_vals, s_ids, s_logz, s_tgt) = out
        scores = ReducedScores(
            jnp.swapaxes(s_vals, 0, 1), jnp.swapaxes(s_ids, 0, 1),
            jnp.swapaxes(s_logz, 0, 1), jnp.swapaxes(s_tgt, 0, 1),
        )
    elif with_scores:
        tokens, step_scores = out
        scores = jnp.swapaxes(step_scores, 0, 1)
    else:
        tokens, scores = out, None
    return jnp.swapaxes(tokens, 0, 1), scores, cache, last_logits, done


@functools.partial(jax.jit, static_argnames=("cfg", "num_steps", "with_scores"))
def decode_steps(
    params,
    cfg: DecoderConfig,
    cache: KVCache,     # from :func:`prefill` or a previous decode_steps call
    prev_logits,        # [B, V] fp32 logits predicting the next token
    lengths,            # [B] prompt lengths (real tokens per row)
    offset,             # [] int32 — tokens already generated before this call
    num_steps: int,
    eos_token_id: Optional[int] = None,
    done=None,          # [B] bool — rows already finished (EOS seen)
    with_scores=True,   # True | False | "reduced"
    target_ids=None,    # [B, 2] int32 (yes, no) ids — required by "reduced"
):
    """Continue a batched greedy decode from an existing KV cache.

    Chunked driver behind both halves of the reference's ``generate``
    semantics: the scores chunk (MAX_LOOK_AHEAD=10 positions feeding the
    yes/no scan) and the score-free completion chunks up to
    ``max_new_tokens=50`` (run_base_vs_instruct_100q.py:337-346) — the host
    stops between chunks once every row has emitted EOS, the batched
    equivalent of HF generate's per-sequence EOS stop.  ``with_scores=False``
    skips stacking the [B, n, V] fp32 score buffer (~500 MB at sweep shapes),
    which completion chunks never need; ``with_scores="reduced"`` stacks only
    :class:`ReducedScores` per-step statistics (top-19 + logsumexp + the two
    ``target_ids`` logits — everything the yes/no scan and the confidence leg
    read), trading the ~500 MB buffer for ~300 KB so the full-study sweep's
    batch is no longer score-buffer-bound.

    Returns (tokens [B, n], scores [B, n, V] | ReducedScores | None, cache,
    last_logits, done); ``scores[:, 0]`` is exactly ``prev_logits`` (reduced:
    its statistics), so a chunk started from :func:`prefill`'s output
    reproduces the reference's position-0 read.
    """
    if done is None:
        done = jnp.zeros((prev_logits.shape[0],), bool)
    if with_scores == "reduced" and target_ids is None:
        raise ValueError("with_scores='reduced' needs target_ids [B, 2]")
    with jax.named_scope("decode_steps"):  # profiler attribution (obs/)
        return _decode_steps_impl(params, cfg, cache, prev_logits, lengths,
                                  offset, num_steps, eos_token_id, done,
                                  with_scores, target_ids)


@functools.partial(jax.jit, static_argnames=("cfg", "num_steps"))
def greedy_decode(
    params,
    cfg: DecoderConfig,
    token_ids,          # [B, S] right-padded prompts
    attention_mask,     # [B, S]
    num_steps: int,
    eos_token_id: Optional[int] = None,
):
    """Batched greedy decode, the reference's ``model.generate(max_new_tokens=N,
    output_scores=True)`` (run_base_vs_instruct_100q.py:337-346) as ONE device
    program: prompt forward + ``num_steps`` scanned single-token steps.

    Returns:
        tokens  [B, num_steps] int32 greedy continuations
        logits  [B, num_steps, V] fp32 scores at each generated position
    """
    b, s = token_ids.shape
    last, cache = _prefill_impl(params, cfg, token_ids, attention_mask, s)
    lengths = jnp.sum(attention_mask, axis=-1)  # [B]
    tokens, scores, _, _, _ = _decode_steps_impl(
        params, cfg, cache, last, lengths, jnp.int32(0), num_steps,
        eos_token_id, jnp.zeros((b,), bool), True,
    )
    return tokens, scores


# ---------------------------------------------------------------------------
# Joint next-K-token decode with verify-and-accept (K-Forcing, 2606.10820)
# ---------------------------------------------------------------------------
#
# Every decode in this system is a short, highly predictable continuation
# (confidence digits, EOS-terminated completions), so a lightweight K-head
# — per-offset logit projections off the LAST final-normed hidden state —
# proposes the next K tokens and ONE joint forward pass over the proposed
# block verifies them against the single-step argmax path.  The verify
# pass reuses the decode path's own machinery (`_block_decode`, the
# two-block split-softmax attention, the same per-chunk tail buffer and
# end-of-chunk fold), so a fully-accepted block reproduces the sequential
# `decode_steps` scan EXACTLY in tokens — and everything derived from
# them: completion text, first-int parses, EOS stops, retirement points —
# and reproduces its logits/scores to fp32 REDUCTION-ORDER NOISE, the
# chunked-prefill equivalence class (that function's docstring): the
# per-row math is identical, but a K-query pass may group summations
# differently from K single-query steps in the last ulp on some
# geometries/backends (measured on the CPU harness: single-query blocks
# are bit-identical, multi-query blocks drift <= 1 ulp — PARITY.md
# "K-decode").  Any proposal mismatch is a rejection: the caller discards
# the pass WHOLESALE and re-runs the chunk through the unchanged
# sequential loop (runtime/engine._k_decode_chunk), which is bit-
# identical by identity — so a bad K-head can only cost wasted passes,
# never a wrong row.  On weight-streaming-bound decode hardware the
# accepted pass streams the weights ONCE for K tokens instead of K times
# — the multiplier the bench's k_decode block measures.


class KVerifyOut(NamedTuple):
    """One joint verification pass over a proposed K-token block."""
    tokens: jnp.ndarray          # [B, kb] TRUE tokens (argmax/EOS chain)
    scores: Optional[object]     # ReducedScores | [B, kb, V] fp32 | None
    last_logits: jnp.ndarray     # [B, V] fp32 — predicts the next position
    last_hidden: jnp.ndarray     # [B, H] final-normed hidden at the last
    #                            # block position (the K-head's input for
    #                            # the next block's proposals)
    done: jnp.ndarray            # [B] EOS-done after the TRUE chain
    a_len: jnp.ndarray           # [B] int32 leading proposals that match
    accepted: jnp.ndarray        # [B] bool — the whole block matched
    tail_k: jnp.ndarray          # updated chunk tail buffers
    tail_v: jnp.ndarray
    cache: Optional[KVCache]     # folded cache when ``fold`` (else None)


def k_head_num_heads(k_head) -> int:
    """Look-ahead heads a K-head params tree carries (proposal block size
    = 1 + this: position 0 is always the free, exact argmax)."""
    if k_head is None:
        return 0
    return int(k_head["w"].shape[0])


def init_k_head(cfg: DecoderConfig, k: int, seed: int = 0, dtype=None):
    """Random K-head: ``k - 1`` per-offset logit projections [H, V] off
    the last hidden state.  Random proposals verify-and-REJECT almost
    everywhere — correctness never depends on head quality — so this is
    the forced-rejection test fixture and the cold-start shape;
    :func:`distill_k_head` is what makes proposals land."""
    import numpy as np

    heads = max(0, int(k) - 1)
    rng = np.random.default_rng(seed)
    w = rng.standard_normal(
        (heads, cfg.hidden_size, cfg.vocab_size)).astype(np.float32) * 0.02
    return {"w": jnp.asarray(w, dtype) if dtype else jnp.asarray(w)}


def distill_k_head(params, cfg: DecoderConfig, token_ids, attention_mask,
                   k: int, eos_token_id: Optional[int] = None,
                   gen_steps: Optional[int] = None, ridge: float = 1e-4):
    """Greedy self-distillation of the K-head on sample prompts.

    Teacher-force the model's OWN greedy continuations: generate
    ``gen_steps`` tokens per row, run one full forward over
    [prompt + continuation], and fit each offset's projection ``W_i`` as
    a ridge linear probe from the final-normed hidden at position ``t``
    to the one-hot greedy token at ``t + 1 + i`` — hidden states along
    the greedy path, i.e. the exact inputs the head sees at decode time.
    Closed-form normal equations on host (no optimizer dependency); the
    probe only has to beat the verify-and-accept floor, never be exact —
    a miss costs one rejected block, not a wrong row."""
    import numpy as np

    heads = max(0, int(k) - 1)
    if heads == 0:
        return {"w": jnp.zeros((0, cfg.hidden_size, cfg.vocab_size))}
    gen = int(gen_steps or (k + 4))
    ids = jnp.asarray(token_ids)
    mask = jnp.asarray(attention_mask)
    b = ids.shape[0]
    toks, _ = greedy_decode(params, cfg, ids, mask, num_steps=gen,
                            eos_token_id=eos_token_id)
    seq = jnp.concatenate([ids, toks], axis=1)
    full_mask = jnp.concatenate(
        [mask, jnp.ones((b, gen), mask.dtype)], axis=1)
    x, _ = _trunk(params, cfg, seq, full_mask, None)
    hidden, _ = _unembed_hidden(cfg, params, x)          # [B, S+gen, H]
    hid = np.asarray(hidden, np.float32)
    toks_np = np.asarray(toks)
    lens = np.asarray(jnp.sum(mask, axis=-1))
    s = ids.shape[1]
    h_dim, v = cfg.hidden_size, cfg.vocab_size
    ws = []
    for i in range(1, heads + 1):
        feats, targets = [], []
        for r in range(b):
            # ARRAY SLOTS vs POSITIONS: prompts are right-padded, so the
            # greedy region always sits at slots [s, s+gen) while its
            # positions continue the row's real run — the frontier hidden
            # (position len-1) lives at slot len-1, greedy token j at
            # slot s+j.  Hidden at position p trains head i on the greedy
            # token at position p + 1 + i.
            if i < gen:
                feats.append(hid[r, int(lens[r]) - 1])
                targets.append(int(toks_np[r, i]))
            for jj in range(0, gen - 1 - i):
                feats.append(hid[r, s + jj])
                targets.append(int(toks_np[r, jj + 1 + i]))
        if not feats:
            ws.append(np.zeros((h_dim, v), np.float32))
            continue
        hm = np.stack(feats)                              # [N, H]
        y = np.zeros((len(targets), v), np.float32)
        y[np.arange(len(targets)), targets] = 1.0
        a = hm.T @ hm + ridge * max(1, len(feats)) * np.eye(h_dim,
                                                           dtype=np.float32)
        ws.append(np.linalg.solve(a, hm.T @ y))           # [H, V]
    # store in the WEIGHTS dtype (bf16 on TPU): the head is a second
    # lm_head and plan.k_head_bytes prices it at the weights' width — a
    # resident fp32 copy would pin 2x the budgeted HBM
    return {"w": jnp.asarray(np.stack(ws),
                             params["embed"]["tokens"].dtype)}


@functools.partial(jax.jit, static_argnames=("k",))
def k_propose(k_head, hidden, prev_logits, k: int, done=None,
              eos_token_id: Optional[int] = None):
    """[B, k] proposed next tokens: position 0 is the free, exact
    ``argmax(prev_logits)``; positions 1..k-1 project ``hidden`` (the
    last final-normed hidden state) through the K-head's per-offset
    matrices.  Rows already EOS-done propose ``eos`` throughout — the
    frozen continuation the sequential path emits."""
    cols = [jnp.argmax(prev_logits, axis=-1).astype(jnp.int32)]
    for i in range(1, k):
        logits_i = lax.dot_general(
            hidden, k_head["w"][i - 1].astype(hidden.dtype),
            (((hidden.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        cols.append(jnp.argmax(logits_i, axis=-1).astype(jnp.int32))
    props = jnp.stack(cols, axis=1)
    if eos_token_id is not None and done is not None:
        props = jnp.where(done[:, None], eos_token_id, props)
    return props


def _k_verify_block_impl(params, cfg: DecoderConfig, cache: KVCache,
                         tail_k, tail_v, prev_logits, lengths, offset,
                         block_start, proposals, eos_token_id, done,
                         target_ids, with_scores, fold: bool):
    """Body of :func:`k_verify_block` (split like ``_decode_steps_impl``
    so the trace-time structure branches — quantized-vs-bf16 cache, the
    reduced-score mode — stay outside the jit decoration)."""
    b, kb = proposals.shape
    n = tail_k.shape[2]
    quantized = cache.k_scale is not None
    if done is None:
        done = jnp.zeros((b,), bool)
    if with_scores == "reduced" and target_ids is None:
        raise ValueError("with_scores='reduced' needs target_ids [B, 2]")
    cdt = params["embed"]["tokens"].dtype if quantized else cache.k.dtype
    q_pos = lengths[:, None] + offset + block_start + jnp.arange(kb)[None, :]
    tail_positions = lengths[:, None] + offset + jnp.arange(n)[None, :]
    # slots of earlier blocks stay visible; later slots are masked out —
    # causality WITHIN the block comes from the position comparison in
    # make_attention_bias, exactly like decode_steps' step mask
    tail_valid = jnp.broadcast_to(
        jnp.arange(n)[None, :] < block_start + kb, (b, n))
    bias_p = make_attention_bias(cfg, q_pos, cache.positions, cache.valid)
    bias_t = make_attention_bias(cfg, q_pos, tail_positions, tail_valid)
    sin_cos = None
    if cfg.position_embedding == "rotary":
        rd = int(cfg.rotary_pct * cfg.head_dim) // 2 * 2
        sin_cos = rotary_embedding(q_pos, rd, cfg.rope_theta, cdt)
    x = _embed(cfg, params, proposals, q_pos)

    def body(h, xs):
        if quantized:
            lp, kp_l, vp_l, ks_l, vs_l, tk_l, tv_l = xs
        else:
            (lp, kp_l, vp_l, tk_l, tv_l), ks_l, vs_l = xs, None, None
        h, (tk_l, tv_l) = _block_decode(
            cfg, lp, h, sin_cos, bias_p, bias_t, kp_l, vp_l, tk_l, tv_l,
            block_start, ks_l, vs_l
        )
        return h, (tk_l, tv_l)

    layer_xs = (
        (params["layers"], cache.k, cache.v, cache.k_scale,
         cache.v_scale, tail_k, tail_v)
        if quantized
        else (params["layers"], cache.k, cache.v, tail_k, tail_v))
    x, (tail_k, tail_v) = lax.scan(body, x, layer_xs)
    hidden, logits_blk = _unembed_hidden(cfg, params, x)  # [B,kb,H/V]
    # logits predicting block position i: prev_logits for i=0, the pass's
    # own logits at i-1 after — the sequential scan's score convention
    pred = jnp.concatenate([prev_logits[:, None], logits_blk[:, :-1]],
                           axis=1)
    reduced = with_scores == "reduced"

    def chain(done_b, pred_i):
        # per-position ops on [B, V] slices — the EXACT spellings
        # _decode_steps_impl's step body runs, so stats/argmaxes can
        # never drift from the sequential path's
        nt = jnp.argmax(pred_i, axis=-1).astype(jnp.int32)
        if eos_token_id is not None:
            nt = jnp.where(done_b, eos_token_id, nt)
            done_b = done_b | (nt == eos_token_id)
        out = (nt, _reduce_step_scores(pred_i, target_ids)) if reduced \
            else (nt,)
        return done_b, out

    done_out, outs = lax.scan(chain, done, jnp.swapaxes(pred, 0, 1))
    true_toks = jnp.swapaxes(outs[0], 0, 1)              # [B, kb]
    if reduced:
        s_vals, s_ids, s_logz, s_tgt = outs[1]
        scores = ReducedScores(
            jnp.swapaxes(s_vals, 0, 1), jnp.swapaxes(s_ids, 0, 1),
            jnp.swapaxes(s_logz, 0, 1), jnp.swapaxes(s_tgt, 0, 1))
    elif with_scores:
        scores = pred
    else:
        scores = None
    match = proposals == true_toks
    a_len = jnp.cumprod(match.astype(jnp.int32), axis=1).sum(axis=1)
    new_cache = None
    if fold:
        # end-of-chunk fold, byte-for-byte decode_steps' (int8 caches
        # quantize the whole tail here, once — same quantization points)
        fk, fv = tail_k, tail_v
        if quantized:
            fk, tk_s = quant.quantize_kv(fk)
            fv, tv_s = quant.quantize_kv(fv)
        new_cache = KVCache(
            k=jnp.concatenate([cache.k, fk], axis=2),
            v=jnp.concatenate([cache.v, fv], axis=2),
            positions=jnp.concatenate([cache.positions, tail_positions],
                                      axis=1),
            valid=jnp.concatenate([cache.valid, jnp.ones((b, n), bool)],
                                  axis=1),
            length=cache.length + n,
            k_scale=(jnp.concatenate([cache.k_scale, tk_s], axis=2)
                     if quantized else None),
            v_scale=(jnp.concatenate([cache.v_scale, tv_s], axis=2)
                     if quantized else None),
        )
    return KVerifyOut(true_toks, scores, logits_blk[:, -1],
                      hidden[:, -1], done_out, a_len, a_len == kb,
                      tail_k, tail_v, new_cache)


@functools.partial(jax.jit,
                   static_argnames=("cfg", "with_scores", "fold"))
def k_verify_block(params, cfg: DecoderConfig, cache: KVCache, tail_k,
                   tail_v, prev_logits, lengths, offset, block_start,
                   proposals, eos_token_id: Optional[int] = None,
                   done=None, target_ids=None, with_scores="reduced",
                   fold: bool = False):
    """ONE joint forward over a proposed token block + in-program
    verification against the single-step argmax path.

    The block's ``kb`` proposed tokens run as ``kb`` parallel queries
    through the SAME per-layer machinery the sequential scan uses
    (`_block_decode`: two-block split-softmax over the read-only cache
    plus the chunk's ``n``-slot tail, K/V landing in tail slots
    ``block_start..block_start+kb``), so when every proposal matches the
    argmax chain the pass reproduces ``decode_steps`` over the same
    chunk exactly in tokens and to fp32 reduction-order noise in
    logits/scores (single-query blocks bit-identically) — pinned by
    tests, the engine's verify-and-accept contract (PARITY.md
    "K-decode").

    In-program acceptance: the TRUE token at block position ``i`` is the
    EOS-frozen argmax of the logits predicting it (position 0:
    ``prev_logits``; later: the pass's own logits at ``i - 1``), exactly
    the sequential chain.  ``a_len`` counts leading proposal matches per
    row; a row's outputs past its first mismatch are garbage BY
    CONSTRUCTION (the wrong token's K/V contaminated its own row only),
    which is why the engine consumes a pass only when every real row
    accepted the whole block and otherwise falls back to the sequential
    loop.  ``fold=True`` (the chunk's last block) folds the tail into
    the cache with the exact end-of-chunk quantize+concat
    ``decode_steps`` performs, so chunk boundaries — and therefore the
    int8 quantization points — match the sequential path's."""
    with jax.named_scope("k_verify"):  # profiler attribution (obs/)
        return _k_verify_block_impl(
            params, cfg, cache, tail_k, tail_v, prev_logits, lengths,
            offset, block_start, proposals, eos_token_id, done,
            target_ids, with_scores, fold)


def _block_decode(cfg, lp, x, sin_cos, bias_p, bias_t, kp_l, vp_l, tk_l,
                  tv_l, i, ks_l=None, vs_l=None):
    """_block variant for decode: the layer's new K/V land in the small tail
    buffer; the prompt cache slice (kp_l/vp_l, with per-head scales
    ks_l/vs_l when int8) is read-only."""
    ln1_out = _norm(cfg, x, lp["ln1"])
    attn_out, new_tail = _attn_decode(
        cfg, lp, ln1_out, sin_cos, bias_p, bias_t, kp_l, vp_l, tk_l, tv_l,
        i, ks_l, vs_l
    )
    if cfg.parallel_residual:
        mlp_in = ln1_out if cfg.shared_layernorm else _norm(cfg, x, lp["ln2"])
        x = x + attn_out + _mlp(cfg, lp, mlp_in)
    else:
        x = x + attn_out
        x = x + _mlp(cfg, lp, _norm(cfg, x, lp["ln2"]))
    return x, new_tail


def _attn_decode(cfg, lp, x, sin_cos, bias_p, bias_t, kp_l, vp_l, tk_l,
                 tv_l, i, ks_l=None, vs_l=None):
    b, s, h = x.shape  # s == 1 during decode
    n, nkv, d = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    ap = lp["attn"]
    q = quant.linear(ap, "wq", x)
    k = quant.linear(ap, "wk", x)
    v = quant.linear(ap, "wv", x)
    if "bq" in ap:
        q, k, v = q + ap["bq"], k + ap["bk"], v + ap["bv"]
    q = q.reshape(b, s, n, d)
    k = k.reshape(b, s, nkv, d)
    v = v.reshape(b, s, nkv, d)
    if sin_cos is not None:
        sin, cos = sin_cos
        rd = int(cfg.rotary_pct * d) // 2 * 2
        q = apply_rotary(q, sin, cos, rd, cfg.rotary_style)
        k = apply_rotary(k, sin, cos, rd, cfg.rotary_style)
    # This step's K/V go into tail slot i — a [B, 1, G, D] dynamic-update-
    # slice into the ~5 MB tail, not a scatter into the ~700 MB prompt cache.
    tk_l = lax.dynamic_update_slice(tk_l, k.astype(tk_l.dtype), (0, i, 0, 0))
    tv_l = lax.dynamic_update_slice(tv_l, v.astype(tv_l.dtype), (0, i, 0, 0))
    out = grouped_attention_two_block(
        q, _deq(kp_l, ks_l, x.dtype), _deq(vp_l, vs_l, x.dtype), bias_p,
        tk_l.astype(x.dtype), tv_l.astype(x.dtype), bias_t,
    )
    out = quant.linear(ap, "wo", out.reshape(b, s, n * d))
    if "bo" in ap:
        out = out + ap["bo"]
    return out, (tk_l, tv_l)
