"""Model architecture configs.

One ``DecoderConfig`` parameterizes every decoder-only family the reference
sweeps (SURVEY.md §2.2 model rosters): GPT-NeoX (StableLM-alpha, RedPajama-
INCITE, Pythia, Dolly-v2, h2ogpt), Falcon, BLOOM(Z), Mistral, LLaMA-2, Qwen
(v1 fused-c_attn and v2), Baichuan(2) (fused W_pack, NormHead, 13B ALiBi), and
OPT (opt-iml) — plus the roster's commented-out alternates: GPT-J(T), MPT,
GLM/ChatGLM2, and XGen (LLaMA-arch behind remote code).  T5-style encoder-
decoders (T0, tk-instruct, Flan-T5) use ``T5Config``.

The reference loads these via HF ``AutoModelForCausalLM`` with
``device_map="auto"`` + bitsandbytes int8 (run_base_vs_instruct_100q.py:414-451);
here a config is a static, hashable pytree-free dataclass so jit caches one
executable per architecture.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple


@dataclasses.dataclass(frozen=True)
class DecoderConfig:
    vocab_size: int
    hidden_size: int
    num_layers: int
    num_heads: int
    # Grouped/multi-query attention: Falcon-7B uses 1 kv head (MQA), Mistral 8
    # (GQA), everyone else num_heads.
    num_kv_heads: Optional[int] = None
    head_dim: Optional[int] = None
    intermediate_size: Optional[int] = None

    # Position encoding: "rotary" | "alibi" | "learned"
    position_embedding: str = "rotary"
    rotary_pct: float = 1.0          # GPT-NeoX applies RoPE to a fraction of head_dim
    # RoPE pairing convention over the rotated dims:
    #   "half"        rotate-half, pair (i, i+rd/2) with freq i — LLaMA/NeoX/HF
    #   "interleaved" pair (2i, 2i+1) with freq i — GPT-J, ChatGLM2
    #   "glm"         rotate-half pairing with interleaved freq assignment
    #                 (cos/sin repeat_interleave'd) — HF GLM-4
    rotary_style: str = "half"
    rope_theta: float = 10000.0
    max_position_embeddings: int = 2048
    learned_pos_offset: int = 0      # OPT stores positions with a +2 offset

    # Block structure
    parallel_residual: bool = False  # GPT-NeoX/Falcon: attn and mlp both read x
    shared_layernorm: bool = False   # Falcon-7B: one LN feeds both attn and mlp
    norm_type: str = "layernorm"     # "layernorm" | "rmsnorm"
    norm_eps: float = 1e-5
    embedding_layernorm: bool = False  # BLOOM: LN right after the embedding

    # Projections
    qkv_bias: bool = True
    out_bias: bool = True
    mlp_bias: bool = True
    fused_qkv: bool = False           # informational: conversion handles layouts
    # MLP: "mlp" (fc->act->proj) | "gated" (SwiGLU-style gate*up->proj)
    mlp_type: str = "mlp"
    activation: str = "gelu"          # "gelu" | "gelu_new" | "silu" | "relu"

    sliding_window: Optional[int] = None  # Mistral local attention window
    # Baichuan2 NormHead: lm_head rows are L2-normalized at inference.  Weights
    # are static at inference, so conversion bakes the normalization into the
    # checkpoint (convert_baichuan) instead of normalizing per forward.
    norm_head: bool = False
    tie_word_embeddings: bool = False
    final_norm: bool = True
    logit_scale: float = 1.0
    # "xla"   — compiler-fused dense attention (fastest in situ at sweep
    #           lengths; the measured tradeoff lives in ops/attention.py)
    # "flash" — the causal block-skipping Pallas kernel always (causal +
    #           right-padding only — rejected for ALiBi / sliding window)
    # "auto"  — dense up to ``auto_flash_seq``, Pallas beyond it, where
    #           dense's S² score tensor would exhaust HBM (ALiBi /
    #           sliding-window configs always stay dense)
    attention_impl: str = "xla"
    auto_flash_seq: int = 1024
    # Decode-time KV cache storage dtype: "bf16" (the compute dtype —
    # bit-parity default) | "int8" (per-head symmetric scales, quantized on
    # append — ops/quant.quantize_kv).  Halves the cache bytes the
    # full-study row contract pins per in-flight batch (runtime/plan.py
    # kv_cache_bytes); the prompt forward itself always runs on exact
    # projections, so only decode / suffix-extension steps read
    # dequantized values (tolerance documented in PARITY.md).
    kv_cache_dtype: str = "bf16"

    def __post_init__(self):
        if self.num_kv_heads is None:
            object.__setattr__(self, "num_kv_heads", self.num_heads)
        if self.head_dim is None:
            object.__setattr__(self, "head_dim", self.hidden_size // self.num_heads)
        if self.intermediate_size is None:
            object.__setattr__(self, "intermediate_size", 4 * self.hidden_size)
        if self.attention_impl not in ("xla", "flash", "auto"):
            raise ValueError(f"unknown attention_impl {self.attention_impl!r}")
        if self.kv_cache_dtype not in ("bf16", "int8"):
            raise ValueError(f"unknown kv_cache_dtype {self.kv_cache_dtype!r}")
        if self.attention_impl == "flash" and (
            self.position_embedding == "alibi" or self.sliding_window is not None
        ):
            raise ValueError(
                "flash attention kernel supports causal+padding only "
                "(no ALiBi / sliding window)"
            )

    def use_flash_attention(self, seq_len: int) -> bool:
        """Resolve the attention impl for a prompt forward at ``seq_len``."""
        if self.attention_impl == "flash":
            return True
        if self.attention_impl == "auto":
            return (seq_len > self.auto_flash_seq
                    and self.position_embedding != "alibi"
                    and self.sliding_window is None)
        return False


@dataclasses.dataclass(frozen=True)
class T5Config:
    """Encoder-decoder config for the T0/tk-instruct/Flan-T5 scoring leg
    (reference scores the *first decoder token* — compare_instruct_models.py:178-225)."""

    vocab_size: int
    d_model: int
    num_layers: int          # encoder layers
    num_decoder_layers: int
    num_heads: int
    d_kv: int
    d_ff: int
    relative_attention_num_buckets: int = 32
    relative_attention_max_distance: int = 128
    norm_eps: float = 1e-6
    # T5 v1.1 / T0 use gated-gelu; original T5 uses relu
    feed_forward_proj: str = "gated-gelu"
    tie_word_embeddings: bool = False
    decoder_start_token_id: int = 0


# ---------------------------------------------------------------------------
# Family presets → HF config translation
# ---------------------------------------------------------------------------

def neox_config(hf) -> DecoderConfig:
    """GPT-NeoX: Pythia/Dolly, StableLM-alpha, RedPajama-INCITE, h2ogpt."""
    return DecoderConfig(
        vocab_size=hf.vocab_size,
        hidden_size=hf.hidden_size,
        num_layers=hf.num_hidden_layers,
        num_heads=hf.num_attention_heads,
        intermediate_size=hf.intermediate_size,
        position_embedding="rotary",
        rotary_pct=getattr(hf, "rotary_pct", 0.25),
        rope_theta=getattr(hf, "rotary_emb_base", 10000.0),
        max_position_embeddings=hf.max_position_embeddings,
        parallel_residual=getattr(hf, "use_parallel_residual", True),
        norm_eps=hf.layer_norm_eps,
        qkv_bias=True,
        out_bias=True,
        mlp_bias=True,
        fused_qkv=True,
        activation=_act(getattr(hf, "hidden_act", "gelu")),
        tie_word_embeddings=getattr(hf, "tie_word_embeddings", False),
    )


def falcon_config(hf) -> DecoderConfig:
    """Falcon-7B(-Instruct): MQA, parallel attention, shared LN, no biases."""
    new_arch = getattr(hf, "new_decoder_architecture", False)
    if new_arch:
        # new arch (falcon-40b/180b): num_kv_heads is authoritative
        num_kv = getattr(hf, "num_kv_heads", None) or hf.num_attention_heads
    else:
        # old arch (falcon-7b): multi_query governs; HF's num_kv_heads attr
        # defaults to num_attention_heads and is NOT used by the torch model
        num_kv = 1 if getattr(hf, "multi_query", True) else hf.num_attention_heads
    return DecoderConfig(
        vocab_size=hf.vocab_size,
        hidden_size=hf.hidden_size,
        num_layers=hf.num_hidden_layers,
        num_heads=hf.num_attention_heads,
        num_kv_heads=num_kv,
        intermediate_size=getattr(hf, "ffn_hidden_size", 4 * hf.hidden_size),
        position_embedding="alibi" if getattr(hf, "alibi", False) else "rotary",
        rope_theta=getattr(hf, "rope_theta", 10000.0),
        max_position_embeddings=getattr(hf, "max_position_embeddings", 2048),
        parallel_residual=getattr(hf, "parallel_attn", True),
        shared_layernorm=getattr(hf, "parallel_attn", True) and not new_arch,
        norm_eps=getattr(hf, "layer_norm_epsilon", 1e-5),
        qkv_bias=getattr(hf, "bias", False),
        out_bias=getattr(hf, "bias", False),
        mlp_bias=getattr(hf, "bias", False),
        fused_qkv=True,
        activation="gelu",
        tie_word_embeddings=True,
    )


def bloom_config(hf) -> DecoderConfig:
    return DecoderConfig(
        vocab_size=hf.vocab_size,
        hidden_size=hf.hidden_size,
        num_layers=hf.n_layer,
        num_heads=hf.n_head,
        intermediate_size=4 * hf.hidden_size,
        position_embedding="alibi",
        max_position_embeddings=getattr(hf, "seq_length", 2048),
        embedding_layernorm=True,
        norm_eps=hf.layer_norm_epsilon,
        qkv_bias=True,
        out_bias=True,
        mlp_bias=True,
        fused_qkv=True,
        activation="gelu_new",
        tie_word_embeddings=True,
    )


def llama_config(hf) -> DecoderConfig:
    """LLaMA-2 / Mistral / Baichuan2-7B / Qwen-style: RMSNorm + SwiGLU + RoPE."""
    return DecoderConfig(
        vocab_size=hf.vocab_size,
        hidden_size=hf.hidden_size,
        num_layers=hf.num_hidden_layers,
        num_heads=hf.num_attention_heads,
        num_kv_heads=getattr(hf, "num_key_value_heads", hf.num_attention_heads),
        head_dim=getattr(hf, "head_dim", None) or hf.hidden_size // hf.num_attention_heads,
        intermediate_size=hf.intermediate_size,
        position_embedding="rotary",
        rope_theta=getattr(hf, "rope_theta", 10000.0),
        max_position_embeddings=hf.max_position_embeddings,
        norm_type="rmsnorm",
        norm_eps=hf.rms_norm_eps,
        qkv_bias=getattr(hf, "attention_bias", False),
        out_bias=False,
        mlp_bias=getattr(hf, "mlp_bias", False),
        mlp_type="gated",
        activation=_act(hf.hidden_act),
        sliding_window=getattr(hf, "sliding_window", None),
        tie_word_embeddings=getattr(hf, "tie_word_embeddings", False),
    )


def qwen_config(hf) -> DecoderConfig:
    """Qwen-7B(-Chat) first generation (``model_type: "qwen"``, the
    trust_remote_code arch the reference loads — compare_instruct_models.py:159,
    compare_base_vs_instruct.py roster).  LLaMA-style RMSNorm+RoPE+SwiGLU block
    with three quirks: the HF config's ``intermediate_size`` is TWICE the MLP
    width (modeling_qwen splits it across the w1/w2 pair), QKV carries a bias
    while every other projection has none, and the word embeddings are untied.
    """
    return DecoderConfig(
        vocab_size=hf.vocab_size,
        hidden_size=hf.hidden_size,
        num_layers=hf.num_hidden_layers,
        num_heads=hf.num_attention_heads,
        head_dim=getattr(hf, "kv_channels", None) or hf.hidden_size // hf.num_attention_heads,
        intermediate_size=hf.intermediate_size // 2,
        position_embedding="rotary",
        rope_theta=getattr(hf, "rotary_emb_base", 10000.0),
        rotary_pct=getattr(hf, "rotary_pct", 1.0),
        max_position_embeddings=getattr(hf, "seq_length", 8192),
        norm_type="rmsnorm",
        norm_eps=hf.layer_norm_epsilon,
        qkv_bias=True,
        out_bias=False,
        mlp_bias=False,
        fused_qkv=True,
        mlp_type="gated",
        activation="silu",
        tie_word_embeddings=getattr(hf, "tie_word_embeddings", False),
    )


def qwen2_config(hf) -> DecoderConfig:
    """Qwen2/Qwen1.5: llama-shaped but QKV bias is hardwired on in the HF
    model (no ``attention_bias`` config attr).  Checkpoints ship a
    ``sliding_window`` value alongside ``use_sliding_window: false``; the
    window only applies when the latter is set."""
    cfg = dataclasses.replace(llama_config(hf), qkv_bias=True, out_bias=False)
    if not getattr(hf, "use_sliding_window", False):
        cfg = dataclasses.replace(cfg, sliding_window=None)
    return cfg


def baichuan_config(hf) -> DecoderConfig:
    """Baichuan(2)-7B/13B-Chat (``model_type: "baichuan"``,
    compare_instruct_models.py:146 roster; slow-tokenizer special case
    ibid.:422-428).  LLaMA block with a fused ``W_pack`` QKV projection.
    Size variants differ in position encoding — 7B (32 layers) is rotary,
    13B (40 layers) is ALiBi — and Baichuan2 checkpoints (vocab 125,696 vs
    Baichuan1's 64,000) add the NormHead output projection."""
    is_13b = hf.num_hidden_layers >= 40
    return DecoderConfig(
        vocab_size=hf.vocab_size,
        hidden_size=hf.hidden_size,
        num_layers=hf.num_hidden_layers,
        num_heads=hf.num_attention_heads,
        intermediate_size=hf.intermediate_size,
        position_embedding="alibi" if is_13b else "rotary",
        max_position_embeddings=getattr(hf, "max_position_embeddings", None)
        or getattr(hf, "model_max_length", 4096),
        norm_type="rmsnorm",
        norm_eps=hf.rms_norm_eps,
        qkv_bias=False,
        out_bias=False,
        mlp_bias=False,
        fused_qkv=True,
        mlp_type="gated",
        activation="silu",
        norm_head=hf.vocab_size > 100_000,  # Baichuan2
        tie_word_embeddings=getattr(hf, "tie_word_embeddings", False),
    )


def gptj_config(hf) -> DecoderConfig:
    """GPT-J-6B / GPT-JT-6B (``model_type: "gptj"`` — togethercomputer/GPT-JT
    in the reference's commented word-meaning roster,
    compare_instruct_models.py:162).  Parallel attn+mlp off ONE shared LN
    (Falcon-style block), interleaved RoPE on ``rotary_dim`` dims, no
    qkv/out biases but fc biases, untied lm_head WITH bias."""
    return DecoderConfig(
        vocab_size=hf.vocab_size,
        hidden_size=hf.n_embd,
        num_layers=hf.n_layer,
        num_heads=hf.n_head,
        intermediate_size=getattr(hf, "n_inner", None) or 4 * hf.n_embd,
        position_embedding="rotary",
        rotary_pct=(hf.rotary_dim or hf.n_embd // hf.n_head)
        / (hf.n_embd // hf.n_head),
        rotary_style="interleaved",
        max_position_embeddings=hf.n_positions,
        parallel_residual=True,
        shared_layernorm=True,
        norm_eps=hf.layer_norm_epsilon,
        qkv_bias=False,
        out_bias=False,
        mlp_bias=True,
        activation=_act(getattr(hf, "activation_function", "gelu_new")),
        tie_word_embeddings=getattr(hf, "tie_word_embeddings", False),
    )


def mpt_config(hf) -> DecoderConfig:
    """MPT-7B(-Instruct) (``model_type: "mpt"`` — mosaicml/mpt-7b-instruct in
    the reference's commented word-meaning roster,
    compare_instruct_models.py:157).  ALiBi, fused Wqkv, and — with the
    standard ``no_bias: true`` — no biases anywhere including LayerNorm."""
    attn_cfg = getattr(hf, "attn_config", None)
    alibi, kv_heads, clip_qkv, qk_ln = True, None, None, False
    if attn_cfg is not None:
        _get = attn_cfg.get if isinstance(attn_cfg, dict) else (
            lambda k, d=None: getattr(attn_cfg, k, d))
        alibi = _get("alibi", True)
        kv_heads = _get("kv_n_heads", None)
        clip_qkv = _get("clip_qkv", None)
        qk_ln = _get("qk_ln", False)
    if not alibi:
        # HF's MPT port itself has no learned-position path; neither do we.
        raise ValueError("MPT without ALiBi (attn_config.alibi=false) is unsupported")
    if kv_heads is not None and kv_heads != hf.n_heads:
        raise ValueError("GQA MPT (attn_config.kv_n_heads) is unsupported")
    if clip_qkv:
        raise ValueError("MPT clip_qkv (e.g. mpt-30b/storywriter) is unsupported")
    if qk_ln:
        raise ValueError("MPT qk_ln checkpoints are unsupported")
    no_bias = getattr(hf, "no_bias", True)
    return DecoderConfig(
        vocab_size=hf.vocab_size,
        hidden_size=hf.d_model,
        num_layers=hf.n_layers,
        num_heads=hf.n_heads,
        intermediate_size=int(getattr(hf, "expansion_ratio", 4) * hf.d_model),
        position_embedding="alibi",
        max_position_embeddings=getattr(hf, "max_seq_len", 2048),
        norm_eps=getattr(hf, "layer_norm_epsilon", 1e-5),
        qkv_bias=not no_bias,
        out_bias=not no_bias,
        mlp_bias=not no_bias,
        fused_qkv=True,
        activation="gelu",
        tie_word_embeddings=True,   # MPT always ties (no lm_head weight)
    )


def glm_config(hf) -> DecoderConfig:
    """GLM-4 (``model_type: "glm"``, HF-native GlmForCausalLM) — the current
    lineage of the ChatGLM family the reference's loader special-cases
    (compare_instruct_models.py:416-421).  LLaMA-shaped block with GQA, a
    partial GLM-convention RoPE, and QKV-only biases."""
    head_dim = getattr(hf, "head_dim", None) or hf.hidden_size // hf.num_attention_heads
    return DecoderConfig(
        vocab_size=hf.vocab_size,
        hidden_size=hf.hidden_size,
        num_layers=hf.num_hidden_layers,
        num_heads=hf.num_attention_heads,
        num_kv_heads=getattr(hf, "num_key_value_heads", hf.num_attention_heads),
        head_dim=head_dim,
        intermediate_size=hf.intermediate_size,
        position_embedding="rotary",
        rotary_pct=getattr(hf, "partial_rotary_factor", 0.5),
        rotary_style="glm",
        rope_theta=getattr(hf, "rope_theta", 10000.0),
        max_position_embeddings=getattr(hf, "max_position_embeddings", 131072),
        norm_type="rmsnorm",
        norm_eps=hf.rms_norm_eps,
        qkv_bias=getattr(hf, "attention_bias", True),
        out_bias=False,
        mlp_bias=False,
        mlp_type="gated",
        activation=_act(getattr(hf, "hidden_act", "silu")),
        tie_word_embeddings=getattr(hf, "tie_word_embeddings", False),
    )


def chatglm_config(hf) -> DecoderConfig:
    """ChatGLM2/3-6B (``model_type: "chatglm"``, the trust_remote_code arch in
    the reference's roster — compare_instruct_models.py:165 (commented) and
    its tokenizer special-case ibid.:416-421).  RMSNorm + SwiGLU + GQA
    (``multi_query_group_num``) with interleaved RoPE on half the head dims.
    No in-process HF oracle exists offline (remote-code only), so conversion
    is structurally tested; the GLM-4 leg above is oracle-tested."""
    return DecoderConfig(
        vocab_size=getattr(hf, "padded_vocab_size", None) or hf.vocab_size,
        hidden_size=hf.hidden_size,
        num_layers=hf.num_layers,
        num_heads=hf.num_attention_heads,
        num_kv_heads=(getattr(hf, "multi_query_group_num", None)
                      if getattr(hf, "multi_query_attention", False) else None),
        head_dim=getattr(hf, "kv_channels", None),
        intermediate_size=hf.ffn_hidden_size,
        position_embedding="rotary",
        rotary_pct=0.5,
        rotary_style="interleaved",
        rope_theta=10000.0 * getattr(hf, "rope_ratio", 1.0),
        max_position_embeddings=getattr(hf, "seq_length", 32768),
        norm_type="rmsnorm" if getattr(hf, "rmsnorm", True) else "layernorm",
        norm_eps=getattr(hf, "layernorm_epsilon", 1e-5),
        qkv_bias=getattr(hf, "add_qkv_bias", True),
        out_bias=getattr(hf, "add_bias_linear", False),
        mlp_bias=getattr(hf, "add_bias_linear", False),
        fused_qkv=True,
        mlp_type="gated",
        activation="silu",
        tie_word_embeddings=False,
    )


def opt_config(hf) -> DecoderConfig:
    return DecoderConfig(
        vocab_size=hf.vocab_size,
        hidden_size=hf.hidden_size,
        num_layers=hf.num_hidden_layers,
        num_heads=hf.num_attention_heads,
        intermediate_size=hf.ffn_dim,
        position_embedding="learned",
        learned_pos_offset=2,
        max_position_embeddings=hf.max_position_embeddings,
        norm_eps=1e-5,
        qkv_bias=True,
        out_bias=True,
        mlp_bias=True,
        activation=_act(hf.activation_function),
        tie_word_embeddings=getattr(hf, "tie_word_embeddings", True),
    )


def t5_config(hf) -> T5Config:
    return T5Config(
        vocab_size=hf.vocab_size,
        d_model=hf.d_model,
        num_layers=hf.num_layers,
        num_decoder_layers=getattr(hf, "num_decoder_layers", hf.num_layers),
        num_heads=hf.num_heads,
        d_kv=hf.d_kv,
        d_ff=hf.d_ff,
        relative_attention_num_buckets=hf.relative_attention_num_buckets,
        relative_attention_max_distance=getattr(hf, "relative_attention_max_distance", 128),
        norm_eps=hf.layer_norm_epsilon,
        feed_forward_proj="gated-gelu" if getattr(hf, "is_gated_act", False) else _act(hf.dense_act_fn),
        tie_word_embeddings=getattr(hf, "tie_word_embeddings", True),
        decoder_start_token_id=hf.decoder_start_token_id or 0,
    )


def _act(name: str) -> str:
    return {
        "gelu": "gelu",
        "gelu_new": "gelu_new",
        "gelu_fast": "gelu_new",
        "gelu_pytorch_tanh": "gelu_new",
        "silu": "silu",
        "swish": "silu",
        "relu": "relu",
    }[name]


#: HF ``model_type`` → (family name, config translator)
FAMILY_BY_MODEL_TYPE = {
    "gpt_neox": ("neox", neox_config),
    "falcon": ("falcon", falcon_config),
    "RefinedWeb": ("falcon", falcon_config),
    "RefinedWebModel": ("falcon", falcon_config),
    "bloom": ("bloom", bloom_config),
    "llama": ("llama", llama_config),
    "mistral": ("llama", llama_config),
    "qwen": ("qwen", qwen_config),
    "qwen2": ("llama", qwen2_config),
    "baichuan": ("baichuan", baichuan_config),
    "opt": ("opt", opt_config),
    "gptj": ("gptj", gptj_config),
    "mpt": ("mpt", mpt_config),
    "glm": ("glm", glm_config),
    "chatglm": ("chatglm", chatglm_config),
    # Salesforce XGen ships LLaMA-architecture weights behind remote code;
    # only its tokenizer needs special handling (compare_instruct_models.py:409-415)
    "xgen": ("llama", llama_config),
    "t5": ("t5", t5_config),
}


def from_hf_config(hf) -> Tuple[str, object]:
    """Map a HF ``PretrainedConfig`` to (family, our config)."""
    mt = getattr(hf, "model_type", None)
    if mt not in FAMILY_BY_MODEL_TYPE:
        raise ValueError(f"unsupported model_type {mt!r}")
    family, translate = FAMILY_BY_MODEL_TYPE[mt]
    return family, translate(hf)


# ---------------------------------------------------------------------------
# Benchmark geometries
# ---------------------------------------------------------------------------
# The two synthetic-weight geometries the bench and the auto-parallel plan
# search price (bench.py initializes them randomly on device — zero-egress
# image, throughput is architecture-bound).  Living HERE keeps bench.py and
# runtime/plan_search.py agreeing on what "falcon-7b" means geometrically.
FALCON_7B_GEOMETRY = dict(
    vocab_size=65024, hidden_size=4544, num_layers=32, num_heads=71,
    num_kv_heads=1, intermediate_size=18176, parallel_residual=True,
    shared_layernorm=True, qkv_bias=False, out_bias=False, mlp_bias=False,
    position_embedding="rotary", tie_word_embeddings=True,
    max_position_embeddings=2048,
)

SMALL_1B_GEOMETRY = dict(
    vocab_size=50304, hidden_size=2048, num_layers=16, num_heads=16,
    intermediate_size=8192, parallel_residual=True, qkv_bias=True,
    out_bias=True, mlp_bias=True, position_embedding="rotary",
    rotary_pct=0.25, max_position_embeddings=2048,
)

BENCH_GEOMETRIES = {"falcon-7b": FALCON_7B_GEOMETRY,
                    "small-1b": SMALL_1B_GEOMETRY}

#: Compile-check-scale Falcon architecture (MQA + parallel attention +
#: shared LN) — the geometry the multichip dryrun trains/scores
#: (__graft_entry__) and the plan-search dryrun prices; one spelling so
#: the acceptance leg can never price a different model than the dryrun
#: engine runs.
FLAGSHIP_SMALL_GEOMETRY = dict(
    vocab_size=1024, hidden_size=256, num_layers=4, num_heads=8,
    num_kv_heads=1, intermediate_size=1024, parallel_residual=True,
    shared_layernorm=True, qkv_bias=False, out_bias=False, mlp_bias=False,
    position_embedding="rotary", tie_word_embeddings=True,
    max_position_embeddings=512,
)
