"""HF checkpoint → param-pytree converters.

The reference loads checkpoints via ``AutoModelForCausalLM.from_pretrained``
with accelerate/bitsandbytes (run_base_vs_instruct_100q.py:414-451).  Here a
checkpoint is converted into the stacked-layer pytree documented in
models/decoder.py: per-family weight-name maps, fused-QKV de-interleaving, and
[out,in] → [in,out] transposes (torch Linear stores W as [out,in]; our matmuls
are ``x @ W``).

Converters read from any ``get(name) -> np.ndarray`` source so the same code
serves torch state dicts (tests) and streamed safetensors shards (runtime/loader).
"""

from __future__ import annotations

from typing import Callable, Dict

import numpy as np

from .config import DecoderConfig

Getter = Callable[[str], np.ndarray]


def _stack(arrays):
    return np.stack(arrays, axis=0)


def _linear(get: Getter, name: str) -> np.ndarray:
    return np.ascontiguousarray(get(f"{name}.weight").T)


def _maybe(get: Getter, name: str):
    try:
        return get(name)
    except KeyError:
        return None


def _ln(get: Getter, name: str, layers=None, bias=True):
    if layers is None:
        out = {"scale": get(f"{name}.weight")}
        if bias:
            out["bias"] = get(f"{name}.bias")
        return out
    out = {"scale": _stack([get(f"{name.format(i=i)}.weight") for i in layers])}
    if bias:
        out["bias"] = _stack([get(f"{name.format(i=i)}.bias") for i in layers])
    return out


def _split_neox_qkv(w: np.ndarray, b, n_heads: int, head_dim: int):
    """GPT-NeoX / BLOOM fused QKV: rows are head-major, [q(D); k(D); v(D)] per
    head.  w: [3*N*D, H] -> (wq, wk, wv) each [H, N*D]."""
    h = w.shape[1]
    w4 = w.reshape(n_heads, 3, head_dim, h)
    outs = []
    for j in range(3):
        outs.append(np.ascontiguousarray(w4[:, j].reshape(n_heads * head_dim, h).T))
    if b is None:
        return outs, (None, None, None)
    b4 = b.reshape(n_heads, 3, head_dim)
    bs = [np.ascontiguousarray(b4[:, j].reshape(n_heads * head_dim)) for j in range(3)]
    return outs, bs


def _split_falcon_qkv(w: np.ndarray, b, n_heads: int, n_kv: int, head_dim: int):
    """Falcon fused QKV.
    - old arch / MQA (falcon-7b): rows = [q(N*D); k(D); v(D)].
    - new arch / GQA: rows grouped per kv group: [q(g*D); k(D); v(D)] × n_kv.
    """
    h = w.shape[1]
    if n_kv == n_heads:
        # fully multi-head fused like neox? Falcon new arch with multi_query
        # false and kv==heads groups each q with its own kv.
        g = 1
    else:
        g = n_heads // n_kv
    wg = w.reshape(n_kv, g + 2, head_dim, h)
    wq = np.ascontiguousarray(wg[:, :g].reshape(n_heads * head_dim, h).T)
    wk = np.ascontiguousarray(wg[:, g].reshape(n_kv * head_dim, h).T)
    wv = np.ascontiguousarray(wg[:, g + 1].reshape(n_kv * head_dim, h).T)
    if b is None:
        return (wq, wk, wv), (None, None, None)
    bg = b.reshape(n_kv, g + 2, head_dim)
    return (wq, wk, wv), (
        bg[:, :g].reshape(-1),
        bg[:, g].reshape(-1),
        bg[:, g + 1].reshape(-1),
    )


def _attn_params(wq, wk, wv, wo, bq=None, bk=None, bv=None, bo=None):
    out = {"wq": wq, "wk": wk, "wv": wv, "wo": wo}
    if bq is not None:
        out.update(bq=bq, bk=bk, bv=bv)
    if bo is not None:
        out["bo"] = bo
    return out


# ---------------------------------------------------------------------------
# Per-family converters
# ---------------------------------------------------------------------------

def convert_neox(get: Getter, cfg: DecoderConfig) -> Dict:
    L = range(cfg.num_layers)
    qs, ks, vs, bqs, bks, bvs = [], [], [], [], [], []
    for i in L:
        (wq, wk, wv), (bq, bk, bv) = _split_neox_qkv(
            get(f"gpt_neox.layers.{i}.attention.query_key_value.weight"),
            get(f"gpt_neox.layers.{i}.attention.query_key_value.bias"),
            cfg.num_heads,
            cfg.head_dim,
        )
        qs.append(wq); ks.append(wk); vs.append(wv)
        bqs.append(bq); bks.append(bk); bvs.append(bv)
    params = {
        "embed": {"tokens": get("gpt_neox.embed_in.weight")},
        "layers": {
            "ln1": _ln(get, "gpt_neox.layers.{i}.input_layernorm", L),
            "ln2": _ln(get, "gpt_neox.layers.{i}.post_attention_layernorm", L),
            "attn": {
                "wq": _stack(qs), "wk": _stack(ks), "wv": _stack(vs),
                "bq": _stack(bqs), "bk": _stack(bks), "bv": _stack(bvs),
                "wo": _stack([_linear(get, f"gpt_neox.layers.{i}.attention.dense") for i in L]),
                "bo": _stack([get(f"gpt_neox.layers.{i}.attention.dense.bias") for i in L]),
            },
            "mlp": {
                "wi": _stack([_linear(get, f"gpt_neox.layers.{i}.mlp.dense_h_to_4h") for i in L]),
                "bi": _stack([get(f"gpt_neox.layers.{i}.mlp.dense_h_to_4h.bias") for i in L]),
                "wo": _stack([_linear(get, f"gpt_neox.layers.{i}.mlp.dense_4h_to_h") for i in L]),
                "bo": _stack([get(f"gpt_neox.layers.{i}.mlp.dense_4h_to_h.bias") for i in L]),
            },
        },
        "final_ln": _ln(get, "gpt_neox.final_layer_norm"),
        "lm_head": np.ascontiguousarray(get("embed_out.weight").T),
    }
    return params


def convert_falcon(get: Getter, cfg: DecoderConfig) -> Dict:
    L = range(cfg.num_layers)
    qs, ks, vs = [], [], []
    for i in L:
        (wq, wk, wv), _ = _split_falcon_qkv(
            get(f"transformer.h.{i}.self_attention.query_key_value.weight"),
            None,
            cfg.num_heads,
            cfg.num_kv_heads,
            cfg.head_dim,
        )
        qs.append(wq); ks.append(wk); vs.append(wv)
    ln1_name = (
        "transformer.h.{i}.input_layernorm"
        if _maybe(get, "transformer.h.0.input_layernorm.weight") is not None
        else "transformer.h.{i}.ln_attn"
    )
    layers = {
        "ln1": _ln(get, ln1_name, L),
        "attn": {
            "wq": _stack(qs), "wk": _stack(ks), "wv": _stack(vs),
            "wo": _stack([_linear(get, f"transformer.h.{i}.self_attention.dense") for i in L]),
        },
        "mlp": {
            "wi": _stack([_linear(get, f"transformer.h.{i}.mlp.dense_h_to_4h") for i in L]),
            "wo": _stack([_linear(get, f"transformer.h.{i}.mlp.dense_4h_to_h") for i in L]),
        },
    }
    if not cfg.shared_layernorm:
        layers["ln2"] = _ln(get, "transformer.h.{i}.ln_mlp", L)
    params = {
        "embed": {"tokens": get("transformer.word_embeddings.weight")},
        "layers": layers,
        "final_ln": _ln(get, "transformer.ln_f"),
    }
    head = _maybe(get, "lm_head.weight")
    if head is not None and not cfg.tie_word_embeddings:
        params["lm_head"] = np.ascontiguousarray(head.T)
    return params


def convert_bloom(get: Getter, cfg: DecoderConfig) -> Dict:
    L = range(cfg.num_layers)
    qs, ks, vs, bqs, bks, bvs = [], [], [], [], [], []
    for i in L:
        (wq, wk, wv), (bq, bk, bv) = _split_neox_qkv(
            get(f"transformer.h.{i}.self_attention.query_key_value.weight"),
            get(f"transformer.h.{i}.self_attention.query_key_value.bias"),
            cfg.num_heads,
            cfg.head_dim,
        )
        qs.append(wq); ks.append(wk); vs.append(wv)
        bqs.append(bq); bks.append(bk); bvs.append(bv)
    params = {
        "embed": {
            "tokens": get("transformer.word_embeddings.weight"),
            "ln": _ln(get, "transformer.word_embeddings_layernorm"),
        },
        "layers": {
            "ln1": _ln(get, "transformer.h.{i}.input_layernorm", L),
            "ln2": _ln(get, "transformer.h.{i}.post_attention_layernorm", L),
            "attn": {
                "wq": _stack(qs), "wk": _stack(ks), "wv": _stack(vs),
                "bq": _stack(bqs), "bk": _stack(bks), "bv": _stack(bvs),
                "wo": _stack([_linear(get, f"transformer.h.{i}.self_attention.dense") for i in L]),
                "bo": _stack([get(f"transformer.h.{i}.self_attention.dense.bias") for i in L]),
            },
            "mlp": {
                "wi": _stack([_linear(get, f"transformer.h.{i}.mlp.dense_h_to_4h") for i in L]),
                "bi": _stack([get(f"transformer.h.{i}.mlp.dense_h_to_4h.bias") for i in L]),
                "wo": _stack([_linear(get, f"transformer.h.{i}.mlp.dense_4h_to_h") for i in L]),
                "bo": _stack([get(f"transformer.h.{i}.mlp.dense_4h_to_h.bias") for i in L]),
            },
        },
        "final_ln": _ln(get, "transformer.ln_f"),
    }
    return params


def convert_llama(get: Getter, cfg: DecoderConfig) -> Dict:
    L = range(cfg.num_layers)
    attn = {
        "wq": _stack([_linear(get, f"model.layers.{i}.self_attn.q_proj") for i in L]),
        "wk": _stack([_linear(get, f"model.layers.{i}.self_attn.k_proj") for i in L]),
        "wv": _stack([_linear(get, f"model.layers.{i}.self_attn.v_proj") for i in L]),
        "wo": _stack([_linear(get, f"model.layers.{i}.self_attn.o_proj") for i in L]),
    }
    if cfg.qkv_bias:  # Qwen-style
        attn["bq"] = _stack([get(f"model.layers.{i}.self_attn.q_proj.bias") for i in L])
        attn["bk"] = _stack([get(f"model.layers.{i}.self_attn.k_proj.bias") for i in L])
        attn["bv"] = _stack([get(f"model.layers.{i}.self_attn.v_proj.bias") for i in L])
    params = {
        "embed": {"tokens": get("model.embed_tokens.weight")},
        "layers": {
            "ln1": _ln(get, "model.layers.{i}.input_layernorm", L, bias=False),
            "ln2": _ln(get, "model.layers.{i}.post_attention_layernorm", L, bias=False),
            "attn": attn,
            "mlp": {
                "wg": _stack([_linear(get, f"model.layers.{i}.mlp.gate_proj") for i in L]),
                "wi": _stack([_linear(get, f"model.layers.{i}.mlp.up_proj") for i in L]),
                "wo": _stack([_linear(get, f"model.layers.{i}.mlp.down_proj") for i in L]),
            },
        },
        "final_ln": _ln(get, "model.norm", bias=False),
    }
    head = _maybe(get, "lm_head.weight")
    if head is not None and not cfg.tie_word_embeddings:
        params["lm_head"] = np.ascontiguousarray(head.T)
    return params


def _split_concat_qkv(w: np.ndarray, b=None):
    """Straight-concat fused QKV (Qwen1 ``c_attn``, Baichuan ``W_pack``):
    rows are [q(all heads); k; v] with NO per-head interleaving.
    w: [3*N*D, H] -> (wq, wk, wv) each [H, N*D]."""
    wq, wk, wv = (np.ascontiguousarray(part.T) for part in np.split(w, 3, axis=0))
    if b is None:
        return (wq, wk, wv), (None, None, None)
    return (wq, wk, wv), tuple(np.split(b, 3))


def convert_qwen(get: Getter, cfg: DecoderConfig) -> Dict:
    """Qwen-7B first generation (modeling_qwen naming: transformer.h.{i} with
    ln_1/ln_2, fused attn.c_attn, and the w1/w2/c_proj gated MLP where SiLU is
    applied to the *w2* branch — so w2 is our gate and w1 our up projection)."""
    L = range(cfg.num_layers)
    qs, ks, vs, bqs, bks, bvs = [], [], [], [], [], []
    for i in L:
        (wq, wk, wv), (bq, bk, bv) = _split_concat_qkv(
            get(f"transformer.h.{i}.attn.c_attn.weight"),
            get(f"transformer.h.{i}.attn.c_attn.bias"),
        )
        qs.append(wq); ks.append(wk); vs.append(wv)
        bqs.append(bq); bks.append(bk); bvs.append(bv)
    params = {
        "embed": {"tokens": get("transformer.wte.weight")},
        "layers": {
            "ln1": _ln(get, "transformer.h.{i}.ln_1", L, bias=False),
            "ln2": _ln(get, "transformer.h.{i}.ln_2", L, bias=False),
            "attn": {
                "wq": _stack(qs), "wk": _stack(ks), "wv": _stack(vs),
                "bq": _stack(bqs), "bk": _stack(bks), "bv": _stack(bvs),
                "wo": _stack([_linear(get, f"transformer.h.{i}.attn.c_proj") for i in L]),
            },
            "mlp": {
                "wg": _stack([_linear(get, f"transformer.h.{i}.mlp.w2") for i in L]),
                "wi": _stack([_linear(get, f"transformer.h.{i}.mlp.w1") for i in L]),
                "wo": _stack([_linear(get, f"transformer.h.{i}.mlp.c_proj") for i in L]),
            },
        },
        "final_ln": _ln(get, "transformer.ln_f", bias=False),
    }
    head = _maybe(get, "lm_head.weight")
    if head is not None and not cfg.tie_word_embeddings:
        params["lm_head"] = np.ascontiguousarray(head.T)
    return params


def convert_baichuan(get: Getter, cfg: DecoderConfig) -> Dict:
    """Baichuan(2): llama naming except the fused ``self_attn.W_pack`` QKV,
    so delegate to convert_llama through a getter that synthesizes the split
    q/k/v projections.  For Baichuan2 (cfg.norm_head) the lm_head rows are
    then L2-normalized — the torch model normalizes on every forward, but
    inference weights are static so baking it into the checkpoint is exact."""
    import re

    def get_split(name: str) -> np.ndarray:
        m = re.fullmatch(
            r"model\.layers\.(\d+)\.self_attn\.([qkv])_proj\.weight", name
        )
        if m is None:
            return get(name)
        packed = get(f"model.layers.{m.group(1)}.self_attn.W_pack.weight")
        return np.split(packed, 3, axis=0)["qkv".index(m.group(2))]

    params = convert_llama(get_split, cfg)
    if cfg.norm_head and "lm_head" in params:
        # lm_head is stored transposed [H, V]: normalize each vocab column
        head = params["lm_head"]
        params["lm_head"] = head / np.maximum(
            np.linalg.norm(head, axis=0, keepdims=True), 1e-12
        )
    return params


def convert_opt(get: Getter, cfg: DecoderConfig) -> Dict:
    L = range(cfg.num_layers)
    pre = "model.decoder"
    params = {
        "embed": {
            "tokens": get(f"{pre}.embed_tokens.weight"),
            # HF stores the +2 offset inside the table; decoder.forward adds
            # cfg.learned_pos_offset back to positions.
            "pos": get(f"{pre}.embed_positions.weight"),
        },
        "layers": {
            "ln1": _ln(get, pre + ".layers.{i}.self_attn_layer_norm", L),
            "ln2": _ln(get, pre + ".layers.{i}.final_layer_norm", L),
            "attn": {
                "wq": _stack([_linear(get, f"{pre}.layers.{i}.self_attn.q_proj") for i in L]),
                "wk": _stack([_linear(get, f"{pre}.layers.{i}.self_attn.k_proj") for i in L]),
                "wv": _stack([_linear(get, f"{pre}.layers.{i}.self_attn.v_proj") for i in L]),
                "bq": _stack([get(f"{pre}.layers.{i}.self_attn.q_proj.bias") for i in L]),
                "bk": _stack([get(f"{pre}.layers.{i}.self_attn.k_proj.bias") for i in L]),
                "bv": _stack([get(f"{pre}.layers.{i}.self_attn.v_proj.bias") for i in L]),
                "wo": _stack([_linear(get, f"{pre}.layers.{i}.self_attn.out_proj") for i in L]),
                "bo": _stack([get(f"{pre}.layers.{i}.self_attn.out_proj.bias") for i in L]),
            },
            "mlp": {
                "wi": _stack([_linear(get, f"{pre}.layers.{i}.fc1") for i in L]),
                "bi": _stack([get(f"{pre}.layers.{i}.fc1.bias") for i in L]),
                "wo": _stack([_linear(get, f"{pre}.layers.{i}.fc2") for i in L]),
                "bo": _stack([get(f"{pre}.layers.{i}.fc2.bias") for i in L]),
            },
        },
        "final_ln": _ln(get, f"{pre}.final_layer_norm"),
    }
    return params


def convert_gptj(get: Getter, cfg: DecoderConfig) -> Dict:
    """GPT-J/GPT-JT: separate unbiased q/k/v/out, biased fc_in/fc_out off one
    shared LN (parallel block), untied lm_head with bias."""
    L = range(cfg.num_layers)
    params = {
        "embed": {"tokens": get("transformer.wte.weight")},
        "layers": {
            "ln1": _ln(get, "transformer.h.{i}.ln_1", L),
            "attn": {
                "wq": _stack([_linear(get, f"transformer.h.{i}.attn.q_proj") for i in L]),
                "wk": _stack([_linear(get, f"transformer.h.{i}.attn.k_proj") for i in L]),
                "wv": _stack([_linear(get, f"transformer.h.{i}.attn.v_proj") for i in L]),
                "wo": _stack([_linear(get, f"transformer.h.{i}.attn.out_proj") for i in L]),
            },
            "mlp": {
                "wi": _stack([_linear(get, f"transformer.h.{i}.mlp.fc_in") for i in L]),
                "bi": _stack([get(f"transformer.h.{i}.mlp.fc_in.bias") for i in L]),
                "wo": _stack([_linear(get, f"transformer.h.{i}.mlp.fc_out") for i in L]),
                "bo": _stack([get(f"transformer.h.{i}.mlp.fc_out.bias") for i in L]),
            },
        },
        "final_ln": _ln(get, "transformer.ln_f"),
        "lm_head": np.ascontiguousarray(get("lm_head.weight").T),
        "lm_head_bias": get("lm_head.bias"),
    }
    return params


def convert_mpt(get: Getter, cfg: DecoderConfig) -> Dict:
    """MPT: fused straight-concat Wqkv; with the standard ``no_bias: true``
    everything (incl. LN) is bias-free; tied embeddings (no lm_head tensor in
    the checkpoint).  Non-ALiBi / GQA variants are rejected in mpt_config."""
    L = range(cfg.num_layers)
    biased = cfg.qkv_bias                    # no_bias=false checkpoints
    qs, ks, vs, bqs, bks, bvs = [], [], [], [], [], []
    for i in L:
        (wq, wk, wv), (bq, bk, bv) = _split_concat_qkv(
            get(f"transformer.blocks.{i}.attn.Wqkv.weight"),
            get(f"transformer.blocks.{i}.attn.Wqkv.bias") if biased else None,
        )
        qs.append(wq); ks.append(wk); vs.append(wv)
        if biased:
            bqs.append(bq); bks.append(bk); bvs.append(bv)
    attn = {
        "wq": _stack(qs), "wk": _stack(ks), "wv": _stack(vs),
        "wo": _stack([_linear(get, f"transformer.blocks.{i}.attn.out_proj") for i in L]),
    }
    mlp = {
        "wi": _stack([_linear(get, f"transformer.blocks.{i}.ffn.up_proj") for i in L]),
        "wo": _stack([_linear(get, f"transformer.blocks.{i}.ffn.down_proj") for i in L]),
    }
    if biased:
        attn.update(
            bq=_stack(bqs), bk=_stack(bks), bv=_stack(bvs),
            bo=_stack([get(f"transformer.blocks.{i}.attn.out_proj.bias") for i in L]),
        )
        mlp.update(
            bi=_stack([get(f"transformer.blocks.{i}.ffn.up_proj.bias") for i in L]),
            bo=_stack([get(f"transformer.blocks.{i}.ffn.down_proj.bias") for i in L]),
        )
    params = {
        "embed": {"tokens": get("transformer.wte.weight")},
        "layers": {
            "ln1": _ln(get, "transformer.blocks.{i}.norm_1", L, bias=biased),
            "ln2": _ln(get, "transformer.blocks.{i}.norm_2", L, bias=biased),
            "attn": attn,
            "mlp": mlp,
        },
        "final_ln": _ln(get, "transformer.norm_f", bias=biased),
    }
    return params


def convert_glm(get: Getter, cfg: DecoderConfig) -> Dict:
    """HF GLM-4: llama-shaped keys except the fused ``gate_up_proj`` (rows are
    [gate; up] — modeling_glm.GlmMLP chunks on the output dim)."""
    L = range(cfg.num_layers)
    gates, ups = [], []
    for i in L:
        w = get(f"model.layers.{i}.mlp.gate_up_proj.weight")   # [2F, H]
        g, u = np.split(w, 2, axis=0)
        gates.append(np.ascontiguousarray(g.T))
        ups.append(np.ascontiguousarray(u.T))
    attn = {
        "wq": _stack([_linear(get, f"model.layers.{i}.self_attn.q_proj") for i in L]),
        "wk": _stack([_linear(get, f"model.layers.{i}.self_attn.k_proj") for i in L]),
        "wv": _stack([_linear(get, f"model.layers.{i}.self_attn.v_proj") for i in L]),
        "wo": _stack([_linear(get, f"model.layers.{i}.self_attn.o_proj") for i in L]),
    }
    if cfg.qkv_bias:
        attn["bq"] = _stack([get(f"model.layers.{i}.self_attn.q_proj.bias") for i in L])
        attn["bk"] = _stack([get(f"model.layers.{i}.self_attn.k_proj.bias") for i in L])
        attn["bv"] = _stack([get(f"model.layers.{i}.self_attn.v_proj.bias") for i in L])
    params = {
        "embed": {"tokens": get("model.embed_tokens.weight")},
        "layers": {
            "ln1": _ln(get, "model.layers.{i}.input_layernorm", L, bias=False),
            "ln2": _ln(get, "model.layers.{i}.post_attention_layernorm", L, bias=False),
            "attn": attn,
            "mlp": {"wg": _stack(gates), "wi": _stack(ups),
                    "wo": _stack([_linear(get, f"model.layers.{i}.mlp.down_proj") for i in L])},
        },
        "final_ln": _ln(get, "model.norm", bias=False),
    }
    head = _maybe(get, "lm_head.weight")
    if head is not None and not cfg.tie_word_embeddings:
        params["lm_head"] = np.ascontiguousarray(head.T)
    return params


def convert_chatglm(get: Getter, cfg: DecoderConfig) -> Dict:
    """ChatGLM2/3-6B (THUDM remote-code checkpoints): fused
    ``query_key_value`` is a straight concat [q(N*D); k(Nkv*D); v(Nkv*D)] and
    ``dense_h_to_4h`` is [gate; up] on the output dim (modeling_chatglm's
    swiglu chunks in half)."""
    L = range(cfg.num_layers)
    pre = "transformer.encoder.layers"
    nd = cfg.num_heads * cfg.head_dim
    kvd = cfg.num_kv_heads * cfg.head_dim
    attn = {"wq": [], "wk": [], "wv": [], "bq": [], "bk": [], "bv": []}
    gates, ups = [], []
    for i in L:
        w = get(f"{pre}.{i}.self_attention.query_key_value.weight")  # [nd+2kvd, H]
        attn["wq"].append(np.ascontiguousarray(w[:nd].T))
        attn["wk"].append(np.ascontiguousarray(w[nd:nd + kvd].T))
        attn["wv"].append(np.ascontiguousarray(w[nd + kvd:].T))
        if cfg.qkv_bias:
            b = get(f"{pre}.{i}.self_attention.query_key_value.bias")
            attn["bq"].append(b[:nd]); attn["bk"].append(b[nd:nd + kvd])
            attn["bv"].append(b[nd + kvd:])
        g, u = np.split(get(f"{pre}.{i}.mlp.dense_h_to_4h.weight"), 2, axis=0)
        gates.append(np.ascontiguousarray(g.T))
        ups.append(np.ascontiguousarray(u.T))
    attn = {k: _stack(v) for k, v in attn.items() if v}
    attn["wo"] = _stack([_linear(get, f"{pre}.{i}.self_attention.dense") for i in L])
    params = {
        "embed": {"tokens": get("transformer.embedding.word_embeddings.weight")},
        "layers": {
            "ln1": _ln(get, pre + ".{i}.input_layernorm", L, bias=False),
            "ln2": _ln(get, pre + ".{i}.post_attention_layernorm", L, bias=False),
            "attn": attn,
            "mlp": {"wg": _stack(gates), "wi": _stack(ups),
                    "wo": _stack([_linear(get, f"{pre}.{i}.mlp.dense_4h_to_h") for i in L])},
        },
        "final_ln": _ln(get, "transformer.encoder.final_layernorm", bias=False),
        "lm_head": np.ascontiguousarray(get("transformer.output_layer.weight").T),
    }
    return params


CONVERTERS = {
    "neox": convert_neox,
    "falcon": convert_falcon,
    "bloom": convert_bloom,
    "llama": convert_llama,
    "qwen": convert_qwen,
    "baichuan": convert_baichuan,
    "opt": convert_opt,
    "gptj": convert_gptj,
    "mpt": convert_mpt,
    "glm": convert_glm,
    "chatglm": convert_chatglm,
}


def convert(family: str, get: Getter, cfg: DecoderConfig, dtype=None) -> Dict:
    """Convert a checkpoint to our pytree; optionally cast to ``dtype``."""
    params = CONVERTERS[family](get, cfg)
    if dtype is not None:
        import jax.numpy as jnp

        params = _cast_tree(params, dtype, jnp)
    return params


def _cast_tree(tree, dtype, jnp):
    if isinstance(tree, dict):
        return {k: _cast_tree(v, dtype, jnp) for k, v in tree.items()}
    return jnp.asarray(tree, dtype=dtype)


def getter_from_torch_state_dict(state_dict) -> Getter:
    """Adapt a torch ``state_dict`` (tests use tiny HF models)."""

    def get(name: str) -> np.ndarray:
        if name not in state_dict:
            raise KeyError(name)
        t = state_dict[name]
        return t.detach().to("cpu").float().numpy()

    return get


# ---------------------------------------------------------------------------
# T5 encoder-decoder
# ---------------------------------------------------------------------------

def convert_t5(get: Getter, cfg) -> Dict:
    """T5/T0/tk-instruct/Flan-T5 (HF ``T5ForConditionalGeneration`` names)."""

    def attn(prefix):
        return {
            "wq": _linear(get, f"{prefix}.q"),
            "wk": _linear(get, f"{prefix}.k"),
            "wv": _linear(get, f"{prefix}.v"),
            "wo": _linear(get, f"{prefix}.o"),
        }

    def mlp(prefix):
        if cfg.feed_forward_proj == "gated-gelu":
            return {
                "wi0": _linear(get, f"{prefix}.wi_0"),
                "wi1": _linear(get, f"{prefix}.wi_1"),
                "wo": _linear(get, f"{prefix}.wo"),
            }
        return {"wi": _linear(get, f"{prefix}.wi"), "wo": _linear(get, f"{prefix}.wo")}

    enc_layers = {
        "ln1": {"scale": _stack([get(f"encoder.block.{i}.layer.0.layer_norm.weight") for i in range(cfg.num_layers)])},
        "ln2": {"scale": _stack([get(f"encoder.block.{i}.layer.1.layer_norm.weight") for i in range(cfg.num_layers)])},
        "attn": {k: _stack([attn(f"encoder.block.{i}.layer.0.SelfAttention")[k] for i in range(cfg.num_layers)]) for k in ("wq", "wk", "wv", "wo")},
        "mlp": {k: _stack([mlp(f"encoder.block.{i}.layer.1.DenseReluDense")[k] for i in range(cfg.num_layers)]) for k in mlp("encoder.block.0.layer.1.DenseReluDense")},
    }
    Ld = cfg.num_decoder_layers
    dec_layers = {
        "ln1": {"scale": _stack([get(f"decoder.block.{i}.layer.0.layer_norm.weight") for i in range(Ld)])},
        "ln2": {"scale": _stack([get(f"decoder.block.{i}.layer.1.layer_norm.weight") for i in range(Ld)])},
        "ln3": {"scale": _stack([get(f"decoder.block.{i}.layer.2.layer_norm.weight") for i in range(Ld)])},
        "self_attn": {k: _stack([attn(f"decoder.block.{i}.layer.0.SelfAttention")[k] for i in range(Ld)]) for k in ("wq", "wk", "wv", "wo")},
        "cross_attn": {k: _stack([attn(f"decoder.block.{i}.layer.1.EncDecAttention")[k] for i in range(Ld)]) for k in ("wq", "wk", "wv", "wo")},
        "mlp": {k: _stack([mlp(f"decoder.block.{i}.layer.2.DenseReluDense")[k] for i in range(Ld)]) for k in mlp("decoder.block.0.layer.2.DenseReluDense")},
    }
    params = {
        "shared": get("shared.weight"),
        "encoder": {
            "rel_bias": get("encoder.block.0.layer.0.SelfAttention.relative_attention_bias.weight"),
            "layers": enc_layers,
            "final_ln": {"scale": get("encoder.final_layer_norm.weight")},
        },
        "decoder": {
            "rel_bias": get("decoder.block.0.layer.0.SelfAttention.relative_attention_bias.weight"),
            "layers": dec_layers,
            "final_ln": {"scale": get("decoder.final_layer_norm.weight")},
        },
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = np.ascontiguousarray(get("lm_head.weight").T)
    return params


CONVERTERS["t5"] = convert_t5
