"""T5-family encoder-decoder — pure-functional JAX.

Covers the reference's enc-dec scoring leg: T0_3B, tk-instruct-3b, Flan-T5
(compare_instruct_models.py:178-225 scores the *first decoder token*;
run_base_vs_instruct_100q.py:287-326 greedy-decodes with scores).

T5 specifics honored here:
- RMS layer norm without mean subtraction, eps inside rsqrt (fp32).
- Relative-position bias from a bucket table owned by layer 0 and shared by all
  layers (bidirectional buckets in the encoder, causal in the decoder).
- NO 1/sqrt(d) attention scaling (folded into initialization by T5).
- Gated-GeLU FFN for v1.1/T0 (wi_0/wi_1) or ReLU FFN for original T5.
- When embeddings are tied, decoder output is scaled by d_model**-0.5.

Param pytree:
    shared                      [V, D]
    encoder/rel_bias            [num_buckets, N]
    encoder/layers/ln1,ln2      [L, D]        (scale only)
    encoder/layers/attn/{wq,wk,wv,wo}
    encoder/layers/mlp/{wi|wi0,wi1, wo}
    encoder/final_ln            [D]
    decoder/rel_bias            [num_buckets, N]
    decoder/layers/ln1,ln2,ln3  [L, D]
    decoder/layers/self_attn/*, cross_attn/*, mlp/*
    decoder/final_ln            [D]
    lm_head                     [D, V]        (absent when tied)
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .config import T5Config
from .decoder import NEG_INF, rms_norm


def _relative_position_bucket(relative_position, bidirectional: bool, num_buckets: int, max_distance: int):
    """T5 bucketing (matches HF T5Attention._relative_position_bucket)."""
    ret = jnp.zeros_like(relative_position)
    n = -relative_position
    if bidirectional:
        num_buckets //= 2
        ret = ret + (n < 0).astype(jnp.int32) * num_buckets
        n = jnp.abs(n)
    else:
        n = jnp.maximum(n, 0)
    max_exact = num_buckets // 2
    is_small = n < max_exact
    val_if_large = max_exact + (
        jnp.log(n.astype(jnp.float32) / max_exact + 1e-6)
        / np.log(max_distance / max_exact)
        * (num_buckets - max_exact)
    ).astype(jnp.int32)
    val_if_large = jnp.minimum(val_if_large, num_buckets - 1)
    return ret + jnp.where(is_small, n, val_if_large)


def _position_bias(cfg: T5Config, rel_bias_table, q_pos, k_pos, bidirectional: bool):
    """[B?, S, T] query/key positions -> fp32 bias [1_or_B, N, S, T]."""
    rel = k_pos[..., None, :] - q_pos[..., :, None]  # [..., S, T]
    buckets = _relative_position_bucket(
        rel, bidirectional, cfg.relative_attention_num_buckets,
        cfg.relative_attention_max_distance,
    )
    bias = jnp.take(rel_bias_table, buckets, axis=0)  # [..., S, T, N]
    return jnp.moveaxis(bias, -1, -3).astype(jnp.float32)  # [..., N, S, T]


def _t5_attention(ap, q_in, kv_in, bias, num_heads: int, d_kv: int):
    b, s, _ = q_in.shape
    t = kv_in.shape[1]
    q = (q_in @ ap["wq"]).reshape(b, s, num_heads, d_kv)
    k = (kv_in @ ap["wk"]).reshape(b, t, num_heads, d_kv)
    v = (kv_in @ ap["wv"]).reshape(b, t, num_heads, d_kv)
    scores = jnp.einsum("bsnd,btnd->bnst", q, k).astype(jnp.float32) + bias
    probs = jax.nn.softmax(scores, axis=-1).astype(q_in.dtype)
    out = jnp.einsum("bnst,btnd->bsnd", probs, v).reshape(b, s, num_heads * d_kv)
    return out @ ap["wo"]


def _t5_mlp(cfg: T5Config, mp, x):
    if cfg.feed_forward_proj == "gated-gelu":
        h = jax.nn.gelu(x @ mp["wi0"], approximate=True) * (x @ mp["wi1"])
    else:
        h = jax.nn.relu(x @ mp["wi"])
    return h @ mp["wo"]


def encode(params, cfg: T5Config, enc_ids, enc_mask):
    b, s = enc_ids.shape
    x = jnp.take(params["shared"], enc_ids, axis=0)
    pos = jnp.arange(s)
    bias = _position_bias(cfg, params["encoder"]["rel_bias"], pos, pos, bidirectional=True)
    bias = bias[None] + jnp.where(enc_mask[:, None, None, :].astype(bool), 0.0, NEG_INF)

    def body(h, lp):
        h = h + _t5_attention(
            lp["attn"], rms_norm(h, lp["ln1"]["scale"], cfg.norm_eps),
            rms_norm(h, lp["ln1"]["scale"], cfg.norm_eps), bias, cfg.num_heads, cfg.d_kv
        )
        h = h + _t5_mlp(cfg, lp["mlp"], rms_norm(h, lp["ln2"]["scale"], cfg.norm_eps))
        return h, None

    x, _ = lax.scan(body, x, params["encoder"]["layers"])
    return rms_norm(x, params["encoder"]["final_ln"]["scale"], cfg.norm_eps)


def _decoder_stack(params, cfg: T5Config, x, self_bias, cross_bias, enc_hidden):
    def body(h, lp):
        h = h + _t5_attention(
            lp["self_attn"], rms_norm(h, lp["ln1"]["scale"], cfg.norm_eps),
            rms_norm(h, lp["ln1"]["scale"], cfg.norm_eps), self_bias,
            cfg.num_heads, cfg.d_kv,
        )
        h = h + _t5_attention(
            lp["cross_attn"], rms_norm(h, lp["ln2"]["scale"], cfg.norm_eps),
            enc_hidden, cross_bias, cfg.num_heads, cfg.d_kv,
        )
        h = h + _t5_mlp(cfg, lp["mlp"], rms_norm(h, lp["ln3"]["scale"], cfg.norm_eps))
        return h, None

    x, _ = lax.scan(body, x, params["decoder"]["layers"])
    return rms_norm(x, params["decoder"]["final_ln"]["scale"], cfg.norm_eps)


def _unembed(params, cfg: T5Config, x):
    if cfg.tie_word_embeddings:
        x = x * (cfg.d_model ** -0.5)
        table = params["shared"].T
    else:
        table = params["lm_head"]
    return x.astype(jnp.float32) @ table.astype(jnp.float32)


@functools.partial(jax.jit, static_argnames=("cfg",))
def forward(params, cfg: T5Config, enc_ids, enc_mask, dec_ids):
    """Teacher-forced decoder logits [B, S_dec, V] (causal self-attention)."""
    enc_hidden = encode(params, cfg, enc_ids, enc_mask)
    b, sd = dec_ids.shape
    pos = jnp.arange(sd)
    self_bias = _position_bias(
        cfg, params["decoder"]["rel_bias"], pos, pos, bidirectional=False
    )[None]
    causal = pos[None, :, None] >= pos[None, None, :]
    self_bias = self_bias + jnp.where(causal[:, None], 0.0, NEG_INF)
    cross_bias = jnp.where(enc_mask[:, None, None, :].astype(bool), 0.0, NEG_INF)
    x = jnp.take(params["shared"], dec_ids, axis=0)
    x = _decoder_stack(params, cfg, x, self_bias, cross_bias, enc_hidden)
    return _unembed(params, cfg, x)


@functools.partial(jax.jit, static_argnames=("cfg", "num_steps", "score_steps"))
def greedy_decode(params, cfg: T5Config, enc_ids, enc_mask, num_steps: int,
                  eos_token_id: Optional[int] = None,
                  score_steps: Optional[int] = None):
    """Greedy generation from ``decoder_start_token_id``.

    Returns (tokens [B, num_steps], scores [B, K, V]) — scores[i] is the fp32
    distribution from which token i was picked, mirroring HF
    ``generate(output_scores=True)`` as consumed by the reference's
    MAX_LOOK_AHEAD scan (run_base_vs_instruct_100q.py:310-320).  K is
    ``score_steps`` (default: all steps): completion-only steps past the scan
    window run in a second, score-free scan so the [B, steps, V] fp32 buffer
    covers only the positions the scan can read (50-token completion decodes
    would otherwise stack 5× the scores for nothing).

    The decoder re-runs over the (static-length) token prefix each step; for
    the ≤50-token generations of the reference this trades a tiny amount of
    redundant FLOPs for one simple scanned program without a decoder KV cache.
    """
    b = enc_ids.shape[0]
    enc_hidden = encode(params, cfg, enc_ids, enc_mask)
    total = num_steps + 1
    tokens = jnp.full((b, total), cfg.decoder_start_token_id, jnp.int32)
    k = num_steps if score_steps is None else min(score_steps, num_steps)

    pos = jnp.arange(total)
    self_bias_full = _position_bias(
        cfg, params["decoder"]["rel_bias"], pos, pos, bidirectional=False
    )[None]
    causal = pos[None, :, None] >= pos[None, None, :]
    cross_bias = jnp.where(enc_mask[:, None, None, :].astype(bool), 0.0, NEG_INF)

    def step(carry, i):
        tokens, done = carry
        # mask out future positions (> i) so the prefix decode is exact
        valid = pos[None, None, :] <= i
        self_bias = self_bias_full + jnp.where(causal[:, None] & valid[:, None], 0.0, NEG_INF)
        x = jnp.take(params["shared"], tokens, axis=0)
        x = _decoder_stack(params, cfg, x, self_bias, cross_bias, enc_hidden)
        logits = _unembed(params, cfg, x)
        step_logits = jnp.take_along_axis(
            logits, jnp.full((b, 1, 1), i).astype(jnp.int32), axis=1
        )[:, 0, :]
        next_tok = jnp.argmax(step_logits, axis=-1).astype(jnp.int32)
        if eos_token_id is not None:
            next_tok = jnp.where(done, eos_token_id, next_tok)
            done = done | (next_tok == eos_token_id)
        tokens = lax.dynamic_update_slice(tokens, next_tok[:, None], (0, i + 1))
        return (tokens, done), (next_tok, step_logits)

    def step_tokens_only(carry, i):
        carry, (next_tok, _) = step(carry, i)
        return carry, next_tok

    carry = (tokens, jnp.zeros((b,), bool))
    carry, (out_toks, out_scores) = lax.scan(step, carry, jnp.arange(k))
    if k < num_steps:
        _, tail_toks = lax.scan(step_tokens_only, carry,
                                jnp.arange(k, num_steps))
        out_toks = jnp.concatenate([out_toks, tail_toks], axis=0)
    return jnp.swapaxes(out_toks, 0, 1), jnp.swapaxes(out_scores, 0, 1)
