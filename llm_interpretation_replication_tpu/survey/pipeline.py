"""Human-survey analysis pipeline (reference: survey_analysis/, 4,727 LoC).

Behavioral replicas of survey_analysis_consolidated.py: Qualtrics loading with
S{n}_ prefixing, the three preregistered exclusions, header question-text
extraction and exact-string matching to LLM prompts, per-question stats,
human–LLM correlation with bootstrap, per-item pairwise agreement, and the
cross-prompt (within-group) correlation machinery with bootstrap-by-question.

Deviations from the reference: bootstrap uses an explicit seeded Generator
(reference used global numpy state), and the all-pairs rater correlation is
the vectorized ``DataFrame.corr`` it was already equivalent to.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import pandas as pd
from scipy.stats import pearsonr


# ---------------------------------------------------------------------------
# Loading / cleaning
# ---------------------------------------------------------------------------

def load_and_clean_survey_data(filepaths) -> Tuple[pd.DataFrame, List[str]]:
    """Load Qualtrics exports (2 meta rows skipped), prefix question columns
    with S{survey}_ and coerce them (and Duration) to numeric."""
    if isinstance(filepaths, str):
        filepaths = [filepaths]
    dfs = []
    for survey_idx, filepath in enumerate(filepaths, start=1):
        raw = pd.read_csv(filepath)
        data = raw[2:].reset_index(drop=True)
        rename = {}
        for group in range(1, 6):
            for question in range(1, 12):
                old = f"Q{group}_{question}"
                if old in data.columns:
                    rename[old] = f"S{survey_idx}_Q{group}_{question}"
        dfs.append(data.rename(columns=rename))
    df = pd.concat(dfs, ignore_index=True)
    df["Duration (in seconds)"] = pd.to_numeric(df["Duration (in seconds)"], errors="coerce")
    question_cols = []
    for survey_idx in range(1, len(filepaths) + 1):
        for group in range(1, 6):
            for question in range(1, 12):
                col = f"S{survey_idx}_Q{group}_{question}"
                if col in df.columns:
                    question_cols.append(col)
                    df[col] = pd.to_numeric(df[col], errors="coerce")
    return df, question_cols


def apply_exclusion_criteria(df: pd.DataFrame, question_cols: Sequence[str]):
    """Preregistered exclusions: (1) duration < 20% of median, (2) identical
    substantive slider values, (3) any attention check (\\*_8) ≠ 100."""
    initial = len(df)
    stats: Dict = {}

    median_duration = df["Duration (in seconds)"].median()
    min_duration = 0.2 * median_duration
    stats["median_duration"] = median_duration
    stats["min_duration_threshold"] = min_duration
    stats["duration_excluded"] = int((df["Duration (in seconds)"] < min_duration).sum())
    df = df[df["Duration (in seconds)"] >= min_duration]

    substantive = [q for q in question_cols if not q.endswith("_8")]
    identical_idx = []
    for idx, row in df.iterrows():
        answered = [q for q in substantive if pd.notna(row[q])]
        if len(answered) > 1:
            values = {row[q] for q in answered}
            if len(values) == 1:
                identical_idx.append(idx)
    stats["identical_excluded"] = len(identical_idx)
    df = df.drop(identical_idx)

    attention_cols = [q for q in question_cols if q.endswith("_8")]
    failed_idx = []
    for idx, row in df.iterrows():
        for col in attention_cols:
            if pd.notna(row[col]) and row[col] != 100:
                failed_idx.append(idx)
                break
    stats["attention_failed"] = len(failed_idx)
    df = df.drop(failed_idx)

    stats["final_count"] = len(df)
    stats["total_excluded"] = initial - len(df)
    return df, stats


def extract_question_text(filepaths) -> Dict[str, str]:
    """S{n}_Q{g}_{q} -> question text parsed from the Qualtrics header row
    (last ' - '-separated segment)."""
    if isinstance(filepaths, str):
        filepaths = [filepaths]
    mapping: Dict[str, str] = {}
    for survey_idx, filepath in enumerate(filepaths, start=1):
        raw = pd.read_csv(filepath)
        headers = raw.iloc[0]
        for col in raw.columns:
            if col.startswith("Q") and "_" in col:
                text = headers[col]
                if pd.notna(text) and isinstance(text, str) and " - " in text:
                    mapping[f"S{survey_idx}_{col}"] = text.split(" - ")[-1].strip()
    return mapping


def match_survey_to_llm_questions(llm_df: pd.DataFrame, survey_filepaths) -> Tuple[Dict, Dict]:
    """Exact question-text join of LLM prompts onto survey columns."""
    mapping = extract_question_text(survey_filepaths)
    mapping = {k: v for k, v in mapping.items() if not k.endswith("_8")}
    prompt_to_question = {text: qid for qid, text in mapping.items()}
    matches = {
        prompt: prompt_to_question[prompt]
        for prompt in llm_df["prompt"].unique()
        if prompt in prompt_to_question
    }
    return matches, mapping


# ---------------------------------------------------------------------------
# Per-question stats + correlation
# ---------------------------------------------------------------------------

def human_responses_by_question(df: pd.DataFrame, question_cols: Sequence[str]) -> Dict:
    out = {}
    for q in question_cols:
        if q.endswith("_8"):
            continue
        responses = df[q].dropna()
        if len(responses):
            out[q] = {
                "mean": float(np.mean(responses)),
                "std": float(np.std(responses)),
                "n": int(len(responses)),
                "responses": responses.tolist(),
            }
    return out


def llm_responses_by_question(llm_df: pd.DataFrame) -> Dict:
    out = {}
    for prompt in llm_df["prompt"].unique():
        vals = llm_df[llm_df["prompt"] == prompt]["relative_prob"]
        out[prompt] = {
            "mean": float(np.mean(vals)),
            "std": float(np.std(vals)),
            "n": int(len(vals)),
            "model_responses": vals.tolist(),
        }
    return out


def pearson_with_bootstrap(x, y, n_bootstrap: int = 1000, confidence_level: float = 0.95,
                           seed: int = 42) -> Dict:
    x = np.asarray(x, dtype=float)
    y = np.asarray(y, dtype=float)
    corr, p_value = pearsonr(x, y)
    rng = np.random.default_rng(seed)
    idx = rng.integers(0, len(x), size=(n_bootstrap, len(x)))
    boots = np.array([pearsonr(x[row], y[row])[0] for row in idx])
    alpha = 1 - confidence_level
    return {
        "correlation": float(corr),
        "p_value": float(p_value),
        "ci_lower": float(np.percentile(boots, 100 * alpha / 2)),
        "ci_upper": float(np.percentile(boots, 100 * (1 - alpha / 2))),
        "standard_error": float(np.std(boots)),
    }


def human_llm_correlation(human_stats: Dict, llm_stats: Dict, matches: Dict,
                          seed: int = 42) -> Optional[Dict]:
    human_means, llm_means, matched = [], [], []
    for llm_prompt, survey_q in matches.items():
        if survey_q in human_stats and llm_prompt in llm_stats:
            h = human_stats[survey_q]["mean"] / 100.0
            m = llm_stats[llm_prompt]["mean"]
            human_means.append(h)
            llm_means.append(m)
            matched.append(
                {"survey_question": survey_q, "llm_prompt": llm_prompt,
                 "human_mean": h, "llm_mean": m}
            )
    if len(human_means) < 2:
        return None
    result = pearson_with_bootstrap(human_means, llm_means, seed=seed)
    result["n_questions"] = len(human_means)
    result["matched_questions"] = matched
    return result


# ---------------------------------------------------------------------------
# Per-item agreement (1 − |Δ|)
# ---------------------------------------------------------------------------

def _pairwise_agreements(values: np.ndarray, scale: float) -> np.ndarray:
    """mean over pairs of (scale − |vi − vj|)/scale without the O(n²) loop."""
    diffs = np.abs(values[:, None] - values[None, :])
    iu = np.triu_indices(len(values), k=1)
    return (scale - diffs[iu]) / scale


def per_item_agreement_humans(df: pd.DataFrame, question_cols: Sequence[str],
                              n_bootstrap: int = 1000, seed: int = 42) -> Dict:
    per_item, avgs = {}, []
    for q in question_cols:
        if q.endswith("_8"):
            continue
        responses = df[q].dropna().to_numpy(dtype=float)
        if len(responses) >= 2:
            agreements = _pairwise_agreements(responses, 100.0)
            per_item[q] = {
                "mean_agreement": float(np.mean(agreements)),
                "std_agreement": float(np.std(agreements)),
                "n_pairs": int(len(agreements)),
                "response_variance": float(np.var(responses)),
                "n_responses": int(len(responses)),
            }
            avgs.append(float(np.mean(agreements)))
    return _agreement_summary(per_item, avgs, n_bootstrap, seed)


def per_item_agreement_llms(llm_df: pd.DataFrame, n_bootstrap: int = 1000,
                            seed: int = 42) -> Dict:
    per_item, avgs = {}, []
    models = llm_df["model"].unique()
    for prompt in llm_df["prompt"].unique():
        sub = llm_df[llm_df["prompt"] == prompt]
        vals = []
        for model in models:
            v = sub[sub["model"] == model]["relative_prob"].values
            if len(v) and not np.isnan(v[0]):
                vals.append(float(v[0]))
        if len(vals) >= 2:
            agreements = _pairwise_agreements(np.asarray(vals), 1.0)
            per_item[prompt] = {
                "mean_agreement": float(np.mean(agreements)),
                "std_agreement": float(np.std(agreements)),
                "n_pairs": int(len(agreements)),
                "response_variance": float(np.var(vals)),
                "n_models": len(vals),
            }
            avgs.append(float(np.mean(agreements)))
    return _agreement_summary(per_item, avgs, n_bootstrap, seed)


def _agreement_summary(per_item, avgs, n_bootstrap, seed):
    if avgs:
        rng = np.random.default_rng(seed)
        idx = rng.integers(0, len(avgs), size=(n_bootstrap, len(avgs)))
        boots = np.mean(np.asarray(avgs)[idx], axis=1)
        ci = (float(np.percentile(boots, 2.5)), float(np.percentile(boots, 97.5)))
    else:
        ci = (0.0, 0.0)
    return {
        "per_item": per_item,
        "overall_mean": float(np.mean(avgs)) if avgs else 0.0,
        "overall_std": float(np.std(avgs)) if avgs else 0.0,
        "n_items": len(avgs),
        "overall_mean_ci_lower": ci[0],
        "overall_mean_ci_upper": ci[1],
    }


# ---------------------------------------------------------------------------
# Cross-prompt (within-group) correlations
# ---------------------------------------------------------------------------

def _question_groups(question_cols: Sequence[str]) -> Dict[str, List[str]]:
    groups: Dict[str, List[str]] = {}
    for col in question_cols:
        if col.endswith("_8"):
            continue
        prefix = col.rsplit("_", 1)[0]  # S1_Q3
        groups.setdefault(prefix, []).append(col)
    return dict(sorted(groups.items()))


def _rater_matrix(df: pd.DataFrame, group_questions: List[str], min_answered: int = 5):
    """questions × respondents matrix (0-1 scale) for raters who answered ≥5."""
    first = group_questions[0]
    sub = df[df[first].notna()]
    data = sub[group_questions].to_numpy(dtype=float) / 100.0
    keep = np.sum(~np.isnan(data), axis=1) >= min_answered
    return pd.DataFrame(data[keep].T, index=group_questions)


def _pairwise_rater_correlations(matrix: pd.DataFrame) -> List[float]:
    corr = matrix.corr(method="pearson").to_numpy()
    iu = np.triu_indices(corr.shape[0], k=1)
    vals = corr[iu]
    return [float(v) for v in vals if not np.isnan(v)]


def human_cross_prompt_correlations(df: pd.DataFrame, question_cols: Sequence[str],
                                    n_bootstrap: int = 100, seed: int = 42) -> Dict:
    """All-pairs rater correlations within each 10-question group; CI from
    resampling questions within groups."""
    groups = _question_groups(question_cols)
    all_corrs: List[float] = []
    group_results: Dict[str, Dict] = {}
    for group_id, questions in groups.items():
        if len(questions) < 2:
            continue
        matrix = _rater_matrix(df, questions)
        if matrix.shape[1] < 2:
            continue
        corrs = _pairwise_rater_correlations(matrix)
        all_corrs.extend(corrs)
        group_results[group_id] = {
            "n_respondents": matrix.shape[1],
            "n_pairs": len(corrs),
            "mean_correlation": float(np.mean(corrs)) if corrs else 0.0,
            "correlations": corrs,
        }
    rng = np.random.default_rng(seed)
    boot_means = []
    for _ in range(n_bootstrap):
        boot_corrs: List[float] = []
        for group_id, questions in groups.items():
            if group_id not in group_results or len(questions) < 2:
                continue
            sampled = [questions[i] for i in rng.integers(0, len(questions), size=len(questions))]
            matrix = _rater_matrix(df, questions)
            if matrix.shape[1] < 2:
                continue
            sampled_matrix = matrix.loc[sampled]
            boot_corrs.extend(_pairwise_rater_correlations(sampled_matrix))
        if boot_corrs:
            boot_means.append(np.mean(boot_corrs))
    base_mean = float(np.mean(all_corrs)) if all_corrs else 0.0
    ci = (
        (float(np.percentile(boot_means, 2.5)), float(np.percentile(boot_means, 97.5)))
        if boot_means
        else (base_mean, base_mean)
    )
    return {
        "group_results": group_results,
        "pairwise_correlations": all_corrs,
        "mean_correlation": base_mean,
        "std_correlation": float(np.std(all_corrs)) if all_corrs else 0.0,
        "n_pairs": len(all_corrs),
        "ci_lower": ci[0],
        "ci_upper": ci[1],
    }


def llm_cross_prompt_correlations(llm_df: pd.DataFrame, question_mapping: Dict[str, str],
                                  n_bootstrap: int = 100, seed: int = 42) -> Dict:
    """Model-pair correlations within the human question groups: each model is
    a 'rater' over the group's questions."""
    text_to_qid = {}
    for qid, text in question_mapping.items():
        if not qid.endswith("_8"):
            text_to_qid[text] = qid
    llm = llm_df.copy()
    llm["question_id"] = llm["prompt"].map(text_to_qid)
    llm = llm[llm["question_id"].notna()]
    llm["group"] = llm["question_id"].map(lambda q: q.rsplit("_", 1)[0])

    all_corrs: List[float] = []
    group_results: Dict[str, Dict] = {}
    groups = sorted(llm["group"].unique())
    pivots = {}
    for group_id in groups:
        sub = llm[llm["group"] == group_id]
        pivot = sub.pivot_table(index="question_id", columns="model", values="relative_prob")
        pivots[group_id] = pivot
        if pivot.shape[0] < 2 or pivot.shape[1] < 2:
            continue
        corrs = _pairwise_rater_correlations(pivot)
        all_corrs.extend(corrs)
        group_results[group_id] = {
            "n_models": pivot.shape[1],
            "n_questions": pivot.shape[0],
            "n_pairs": len(corrs),
            "mean_correlation": float(np.mean(corrs)) if corrs else 0.0,
            "correlations": corrs,
        }
    rng = np.random.default_rng(seed)
    boot_means = []
    for _ in range(n_bootstrap):
        boot_corrs: List[float] = []
        for group_id, pivot in pivots.items():
            n_q = pivot.shape[0]
            if group_id not in group_results or n_q < 2:
                continue
            sampled = pivot.iloc[rng.integers(0, n_q, size=n_q)]
            boot_corrs.extend(_pairwise_rater_correlations(sampled))
        if boot_corrs:
            boot_means.append(np.mean(boot_corrs))
    base_mean = float(np.mean(all_corrs)) if all_corrs else 0.0
    ci = (
        (float(np.percentile(boot_means, 2.5)), float(np.percentile(boot_means, 97.5)))
        if boot_means
        else (base_mean, base_mean)
    )
    return {
        "group_results": group_results,
        "pairwise_correlations": all_corrs,
        "mean_correlation": base_mean,
        "std_correlation": float(np.std(all_corrs)) if all_corrs else 0.0,
        "n_pairs": len(all_corrs),
        "ci_lower": ci[0],
        "ci_upper": ci[1],
    }


def cross_prompt_difference_ci(human_result: Dict, llm_result: Dict,
                               n_bootstrap: int = 1000, seed: int = 42) -> Dict:
    """CI for (human − LLM) mean cross-prompt correlation by resampling each
    side's pairwise-correlation pool (survey_analysis_consolidated.py:676-807)."""
    h = np.asarray(human_result["pairwise_correlations"], dtype=float)
    l = np.asarray(llm_result["pairwise_correlations"], dtype=float)
    observed = float(np.mean(h) - np.mean(l))
    rng = np.random.default_rng(seed)
    hb = np.mean(h[rng.integers(0, len(h), size=(n_bootstrap, len(h)))], axis=1)
    lb = np.mean(l[rng.integers(0, len(l), size=(n_bootstrap, len(l)))], axis=1)
    diffs = hb - lb
    if observed > 0:
        p = 2 * float(np.mean(diffs <= 0))
    else:
        p = 2 * float(np.mean(diffs >= 0))
    return {
        "difference": observed,
        "ci_lower": float(np.percentile(diffs, 2.5)),
        "ci_upper": float(np.percentile(diffs, 97.5)),
        "p_value": min(p, 1.0),
    }


def meta_correlation(human_agreements: Dict, llm_agreements: Dict,
                     matches: Dict, n_bootstrap: int = 1000,
                     seed: int = 42) -> Dict:
    """Correlation between per-item human and LLM agreement patterns
    (survey_analysis_consolidated.py:808-852): do humans and models find the
    SAME questions contentious?"""
    h_vals, l_vals = [], []
    for llm_prompt, survey_q in matches.items():
        h = human_agreements["per_item"].get(survey_q)
        l = llm_agreements["per_item"].get(llm_prompt)
        if h is not None and l is not None:
            h_vals.append(h["mean_agreement"])
            l_vals.append(l["mean_agreement"])
    base = {
        "n_matched_items": len(h_vals),
        "human_mean_agreement": human_agreements["overall_mean"],
        "human_std_agreement": human_agreements["overall_std"],
        "llm_mean_agreement": llm_agreements["overall_mean"],
        "llm_std_agreement": llm_agreements["overall_std"],
    }
    if len(h_vals) < 2:
        return {**base, "correlation": None,
                "interpretation": "Insufficient matched items for correlation"}
    result = pearson_with_bootstrap(h_vals, l_vals, n_bootstrap=n_bootstrap, seed=seed)
    return {**base, **result,
            "interpretation": "Correlation between human and LLM per-item "
                              "agreement patterns"}


def run_consolidated_analysis(
    survey_csvs: Sequence[str],
    llm_csv: str,
    output_dir: str,
    n_bootstrap: int = 1000,
    cross_prompt_bootstrap: int = 100,
    seed: int = 42,
    log=print,
) -> Dict:
    """The consolidated survey analysis end-to-end
    (survey_analysis_consolidated.py main(), :1028-1104): load + clean both
    survey parts, apply the preregistered exclusions, match LLM prompts,
    compute human/LLM stats, question-level correlation, per-item agreements,
    meta-correlation, cross-prompt correlations and their difference CI, then
    write ``report.txt`` + ``results.json``."""
    import json
    import os

    os.makedirs(output_dir, exist_ok=True)
    df, cols = load_and_clean_survey_data(survey_csvs)
    llm_df = pd.read_csv(llm_csv)
    clean, exclusions = apply_exclusion_criteria(df, cols)
    log(f"Exclusions: {exclusions}")
    matches, mapping = match_survey_to_llm_questions(llm_df, survey_csvs)
    human_stats = human_responses_by_question(clean, cols)
    llm_stats = llm_responses_by_question(llm_df)
    corr = human_llm_correlation(human_stats, llm_stats, matches, seed=seed)
    hum_item = per_item_agreement_humans(clean, cols, n_bootstrap=n_bootstrap, seed=seed)
    llm_item = per_item_agreement_llms(llm_df, n_bootstrap=n_bootstrap, seed=seed)
    meta = meta_correlation(hum_item, llm_item, matches, n_bootstrap=n_bootstrap, seed=seed)
    hum_cp = human_cross_prompt_correlations(clean, cols, n_bootstrap=cross_prompt_bootstrap, seed=seed)
    llm_cp = llm_cross_prompt_correlations(llm_df, mapping, n_bootstrap=cross_prompt_bootstrap, seed=seed)
    diff = cross_prompt_difference_ci(hum_cp, llm_cp, n_bootstrap=n_bootstrap, seed=seed)

    results = {
        "exclusions": exclusions,
        "n_survey_questions": len(human_stats),
        "n_llm_prompts": len(llm_stats),
        "n_matched": len(matches),
        "human_llm_correlation": (
            {k: v for k, v in corr.items() if k != "matched_questions"}
            if corr else None
        ),
        "human_agreement": {k: v for k, v in hum_item.items() if k != "per_item"},
        "llm_agreement": {k: v for k, v in llm_item.items() if k != "per_item"},
        "meta_correlation": meta,
        # per-pair pools are large; the report keeps the summary statistics
        "human_cross_prompt": {k: v for k, v in hum_cp.items()
                               if k not in ("group_results", "pairwise_correlations")},
        "llm_cross_prompt": {k: v for k, v in llm_cp.items()
                             if k not in ("group_results", "pairwise_correlations")},
        "cross_prompt_difference": diff,
    }
    with open(os.path.join(output_dir, "results.json"), "w") as f:
        json.dump(results, f, indent=2, default=float)

    lines = [
        "=" * 80,
        "CONSOLIDATED SURVEY ANALYSIS - HUMAN vs LLM ORDINARY MEANING AGREEMENT",
        "=" * 80,
        "",
        "EXCLUSION STATISTICS:",
        f"  Initial respondents: {exclusions['final_count'] + exclusions['total_excluded']}",
        f"  Excluded for short duration: {exclusions['duration_excluded']}",
        f"  Excluded for identical responses: {exclusions['identical_excluded']}",
        f"  Excluded for attention check failure: {exclusions['attention_failed']}",
        f"  Final sample size: {exclusions['final_count']}",
        "",
        "QUESTION MATCHING:",
        f"  Survey questions: {len(human_stats)}; LLM prompts: {len(llm_stats)}; "
        f"matched: {len(matches)}",
        "",
    ]
    if corr:
        lines += [
            "HUMAN-LLM CORRELATION (question level):",
            f"  Pearson r = {corr['correlation']:.3f} "
            f"[{corr['ci_lower']:.3f}, {corr['ci_upper']:.3f}] "
            f"(n={corr['n_questions']})",
            "",
        ]
    lines += [
        "PER-ITEM AGREEMENT (1 - |delta|):",
        f"  Humans: {hum_item['overall_mean']:.3f} over {hum_item['n_items']} items",
        f"  LLMs:   {llm_item['overall_mean']:.3f} over {llm_item['n_items']} items",
        "",
        "CROSS-PROMPT CORRELATIONS (within 10-question groups):",
        f"  Humans: {hum_cp['mean_correlation']:.3f} "
        f"[{hum_cp['ci_lower']:.3f}, {hum_cp['ci_upper']:.3f}]",
        f"  LLMs:   {llm_cp['mean_correlation']:.3f} "
        f"[{llm_cp['ci_lower']:.3f}, {llm_cp['ci_upper']:.3f}]",
        f"  Difference: {diff['difference']:.3f} "
        f"[{diff['ci_lower']:.3f}, {diff['ci_upper']:.3f}], p={diff['p_value']:.4f}",
    ]
    with open(os.path.join(output_dir, "report.txt"), "w") as f:
        f.write("\n".join(lines) + "\n")
    log("\n".join(lines))
    return results
