"""Prolific demographics → summary table (reference:
survey_analysis/generate_demographics_table.py)."""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import pandas as pd


def load_demographics(filepaths) -> pd.DataFrame:
    if isinstance(filepaths, str):
        filepaths = [filepaths]
    return pd.concat([pd.read_csv(p) for p in filepaths], ignore_index=True)


def summarize_categorical(df: pd.DataFrame, column: str, top_n: Optional[int] = None) -> pd.DataFrame:
    counts = df[column].fillna("(missing)").value_counts()
    if top_n:
        counts = counts.head(top_n)
    out = counts.rename("count").to_frame()
    out["percent"] = 100.0 * out["count"] / len(df)
    return out.reset_index(names=column)


def summarize_age(df: pd.DataFrame, column: str = "Age") -> Dict:
    ages = pd.to_numeric(df[column], errors="coerce").dropna()
    return {
        "n": int(len(ages)),
        "mean": float(ages.mean()) if len(ages) else float("nan"),
        "median": float(ages.median()) if len(ages) else float("nan"),
        "min": float(ages.min()) if len(ages) else float("nan"),
        "max": float(ages.max()) if len(ages) else float("nan"),
    }


def demographics_latex_table(df: pd.DataFrame, columns: Sequence[str]) -> str:
    """Counts/percent LaTeX fragment for the appendix."""
    lines = [
        "\\begin{tabular}{lrr}",
        "\\hline",
        "Category & N & \\% \\\\",
        "\\hline",
    ]
    for column in columns:
        if column not in df.columns:
            continue
        lines.append(f"\\multicolumn{{3}}{{l}}{{\\textbf{{{column}}}}} \\\\")
        for _, row in summarize_categorical(df, column).iterrows():
            label = str(row[column]).replace("&", "\\&").replace("%", "\\%")
            lines.append(f"{label} & {int(row['count'])} & {row['percent']:.1f} \\\\")
    lines += ["\\hline", "\\end{tabular}"]
    return "\n".join(lines)
