"""Survey-analysis variants: 3-way base/instruct/human comparison, per-family
metric differences, and the ground-truth distribution figure.

Rebuilds the three standalone reference scripts that have no condensed
equivalent in the main pipeline:

- ``analyze_base_vs_instruct_vs_human.py:1-244`` — per-model Pearson/Spearman/
  MAE against human proportions, output-validity audit, probability-
  distribution stats, best-model scatter figure.
- ``analyze_llm_human_agreement_bootstrap.py`` (the JSON producer) +
  ``analyze_model_family_differences.py:1-231`` — respondent-level bootstrap
  of MAE/MSE/MAPE per model, then per-family instruct − base differences with
  quadrature-combined CIs.
- ``visualize_ground_truth_distribution.py:1-265`` — human ground-truth
  histogram with fitted normal + random-baseline panel, and the simplified
  single-panel variant.
- ``analyze_llm_human_agreement.py:1-315`` — per-model point-estimate
  agreement with the cleaned human means (MAE/RMSE/MAPE/Pearson/Spearman,
  worst questions, cross-model question variance) →
  ``llm_human_agreement_analysis.json``.
- ``analyze_llm_agreement_simple_bootstrap.py:1-482`` — QUESTION-level
  bootstrap of the same metrics (the respondent-level variant is
  ``agreement_bootstrap`` above) plus a base-vs-instruct group comparison
  with a permutation p-value → ``llm_human_agreement_bootstrap.json``.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Sequence

import numpy as np
import pandas as pd

from .mae_100q import MODEL_FAMILIES


# ---------------------------------------------------------------------------
# 3-way base vs instruct vs human (analyze_base_vs_instruct_vs_human.py)
# ---------------------------------------------------------------------------

def human_proportions_by_prompt(survey_df: pd.DataFrame,
                                question_cols: Sequence[str],
                                mapping: Dict[str, str]) -> Dict[str, float]:
    """prompt -> human proportion-yes (mean slider / 100).  The reference
    consumed a pre-built ``proportion_yes`` from its saved JSON
    (analyze_base_vs_instruct_vs_human.py:71-74); the producer is not in the
    replication package, so the paper's convention (mean response normalized
    to 0-1) is used throughout — one source: pipeline.human_responses_by_question."""
    from .pipeline import human_responses_by_question

    cols = [q for q in mapping if q in survey_df.columns]
    stats = human_responses_by_question(survey_df, cols)
    return {mapping[qid]: s["mean"] / 100.0 for qid, s in stats.items()}


def model_human_correlations(llm_df: pd.DataFrame,
                             human_proportions: Dict[str, float],
                             min_questions: int = 10) -> pd.DataFrame:
    """Per-model Pearson/Spearman/MAE vs human proportions, sorted by Pearson
    (reference :81-125)."""
    from scipy.stats import pearsonr, spearmanr

    records = []
    for model in llm_df["model"].unique():
        sub = llm_df[llm_df["model"] == model]
        pairs = [
            (human_proportions[row["prompt"]], row["relative_prob"])
            for _, row in sub.iterrows()
            if row["prompt"] in human_proportions
            and pd.notna(row["relative_prob"])
        ]
        if len(pairs) < min_questions:
            continue
        h, m = np.array(pairs).T
        pr, pp = pearsonr(h, m)
        sr, sp = spearmanr(h, m)
        records.append({
            "model": model, "n_questions": len(pairs),
            "pearson_r": float(pr), "pearson_p": float(pp),
            "spearman_r": float(sr), "spearman_p": float(sp),
            "mae": float(np.mean(np.abs(h - m))),
        })
    df = pd.DataFrame(records)
    if len(df):
        df = df.sort_values("pearson_r", ascending=False).reset_index(drop=True)
    return df


def output_validity_audit(llm_df: pd.DataFrame) -> List[Dict]:
    """Rows whose model_output contains neither Yes nor No (reference
    :128-148)."""
    invalid = []
    if "model_output" not in llm_df.columns:
        return invalid
    for _, row in llm_df.iterrows():
        output = str(row["model_output"]).lower()
        if "yes" not in output and "no" not in output:
            invalid.append({"model": row["model"], "prompt": row["prompt"],
                            "output": row["model_output"]})
    return invalid


def probability_distribution_stats(llm_df: pd.DataFrame) -> pd.DataFrame:
    """Per-model relative_prob mean/std/min/max with the reference's
    yes/no-bias warnings (:151-172)."""
    records = []
    for model in llm_df["model"].unique():
        probs = llm_df[llm_df["model"] == model]["relative_prob"].dropna()
        if not len(probs):
            continue
        mean = float(probs.mean())
        warning = ""
        if mean < 0.3:
            warning = "tends to answer 'No' (low mean probability)"
        elif mean > 0.7:
            warning = "tends to answer 'Yes' (high mean probability)"
        records.append({
            "model": model, "mean": mean, "std": float(probs.std()),
            "min": float(probs.min()), "max": float(probs.max()),
            "warning": warning,
        })
    return pd.DataFrame(records)


def human_vs_model_scatter(llm_df: pd.DataFrame,
                           human_proportions: Dict[str, float],
                           model: str, pearson_r: float,
                           output_path: str) -> str:
    """Scatter of the best-correlated model vs humans with identity line
    (reference :175-214)."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    sub = llm_df[llm_df["model"] == model]
    pairs = [
        (human_proportions[row["prompt"]], row["relative_prob"])
        for _, row in sub.iterrows() if row["prompt"] in human_proportions
    ]
    h, m = np.array(pairs).T
    fig, ax = plt.subplots(figsize=(12, 8))
    ax.scatter(h, m, alpha=0.6)
    ax.plot([0, 1], [0, 1], "r--", alpha=0.5)
    ax.set_xlabel('Human Proportion "Yes"')
    ax.set_ylabel('Model Probability "Yes"')
    ax.set_title(f"Human vs Model Responses\n({model})")
    ax.set_xlim(-0.05, 1.05)
    ax.set_ylim(-0.05, 1.05)
    ax.text(0.05, 0.95, f"Pearson r = {pearson_r:.3f}",
            transform=ax.transAxes, verticalalignment="top")
    os.makedirs(os.path.dirname(os.path.abspath(output_path)), exist_ok=True)
    fig.tight_layout()
    fig.savefig(output_path, dpi=150)
    plt.close(fig)
    return output_path


def three_way_report(llm_df: pd.DataFrame, survey_df: pd.DataFrame,
                     question_cols: Sequence[str], mapping: Dict[str, str],
                     output_dir: str, make_figures: bool = True) -> Dict:
    """The full 3-way analysis: correlations CSV + audit + distribution stats
    + best-model scatter (analyze_base_vs_instruct_vs_human.py end-to-end)."""
    os.makedirs(output_dir, exist_ok=True)
    props = human_proportions_by_prompt(survey_df, question_cols, mapping)
    corr = model_human_correlations(llm_df, props)
    invalid = output_validity_audit(llm_df)
    dist = probability_distribution_stats(llm_df)
    corr_path = os.path.join(output_dir, "model_human_correlations.csv")
    corr.to_csv(corr_path, index=False)
    out = {
        "human_questions": len(props),
        "correlations": corr,
        "invalid_responses": invalid,
        "distribution_stats": dist,
        "correlations_csv": corr_path,
    }
    if make_figures and len(corr):
        best = corr.iloc[0]
        out["figure"] = human_vs_model_scatter(
            llm_df, props, best["model"], best["pearson_r"],
            os.path.join(output_dir, "human_vs_model_comparison.png"),
        )
    return out


# ---------------------------------------------------------------------------
# Respondent-level agreement bootstrap + per-family differences
# ---------------------------------------------------------------------------

def _metric_summary(name: str, vals, alpha: float = 0.05) -> Dict:
    """mean/std/percentile-CI record fields for one bootstrap metric —
    shared by the respondent-level and question-level bootstraps."""
    vals = np.asarray(vals, float)
    vals = vals[np.isfinite(vals)]
    if not vals.size:
        nan = float("nan")
        return {f"{name}_mean": nan, f"{name}_ci_lower": nan,
                f"{name}_ci_upper": nan, f"{name}_std": nan}
    return {
        f"{name}_mean": float(np.mean(vals)),
        f"{name}_ci_lower": float(np.percentile(vals, alpha / 2 * 100)),
        f"{name}_ci_upper": float(np.percentile(vals, (1 - alpha / 2) * 100)),
        f"{name}_std": float(np.std(vals)),
    }


def agreement_bootstrap(llm_df: pd.DataFrame, survey_df: pd.DataFrame,
                        question_cols: Sequence[str], mapping: Dict[str, str],
                        n_bootstrap: int = 100, seed: int = 42,
                        min_questions: int = 10) -> Dict:
    """Per-model MAE/MSE/MAPE/pearson vs human means with a respondent-level
    bootstrap (analyze_llm_human_agreement_bootstrap.py): resample survey
    respondents, recompute per-question human means, re-score every model
    against them; report mean/std/95% CI per metric."""
    prompt_for = {qid: prompt for qid, prompt in mapping.items()
                  if not qid.endswith("_8")}
    cols = [q for q in question_cols
            if q in prompt_for and q in survey_df.columns]
    rng = np.random.default_rng(seed)
    n_resp = len(survey_df)
    model_rows = {
        model: {
            row["prompt"]: row["relative_prob"]
            for _, row in llm_df[llm_df["model"] == model].iterrows()
            if pd.notna(row["relative_prob"])
        }
        for model in llm_df["model"].unique()
    }
    # [n_bootstrap, n_cols] bootstrapped human means (0-1)
    values = survey_df[cols].to_numpy(dtype=float)
    boot_means = np.empty((n_bootstrap, len(cols)))
    for b in range(n_bootstrap):
        idx = rng.integers(0, n_resp, size=n_resp)
        boot_means[b] = np.nanmean(values[idx], axis=0) / 100.0

    results = []
    for model, by_prompt in model_rows.items():
        keep = [j for j, q in enumerate(cols) if prompt_for[q] in by_prompt]
        if len(keep) < min_questions:
            continue
        preds = np.array([by_prompt[prompt_for[cols[j]]] for j in keep])
        h = boot_means[:, keep]                    # [n_bootstrap, n_q]
        err = h - preds[None, :]
        mae = np.abs(err).mean(axis=1)
        mse = (err ** 2).mean(axis=1)
        # MAPE mirrors the reference's finite-filter semantics
        # (analyze_llm_human_agreement_bootstrap.py:179-182): every FINITE
        # |err|/h term is kept — including tiny-but-nonzero human means,
        # whose terms are huge but finite — and only inf (h == 0) and nan
        # terms drop; a resample with no finite terms reports nan.
        with np.errstate(divide="ignore", invalid="ignore"):
            ape = np.abs(err) / h
        finite = np.isfinite(ape)
        n_fin = finite.sum(axis=1)
        mape = np.where(
            n_fin > 0,
            np.where(finite, ape, 0.0).sum(axis=1) / np.maximum(n_fin, 1),
            np.nan,
        ) * 100
        hc = h - h.mean(axis=1, keepdims=True)
        pc = preds - preds.mean()
        denom = np.sqrt((hc ** 2).sum(axis=1) * (pc ** 2).sum())
        pearson = np.where(denom > 0, (hc * pc[None, :]).sum(axis=1) / denom, np.nan)
        rec = {"model": model, "n_questions": len(keep),
               "n_bootstrap": n_bootstrap}
        for name, vals in (("mae", mae), ("mse", mse), ("mape", mape),
                           ("pearson_r", pearson)):
            rec.update(_metric_summary(name, vals))
        results.append(rec)
    return {
        "analysis_type": "llm_human_agreement_bootstrap",
        "bootstrap_parameters": {"n_iterations": n_bootstrap, "seed": seed},
        "model_results": results,
    }


def family_differences(agreement: Dict,
                       families: Optional[Dict] = None,
                       metrics: Sequence[str] = ("mae", "mse", "mape")) -> List[Dict]:
    """Per-family instruct − base differences per metric with the reference's
    quadrature-combined CI (analyze_model_family_differences.py:51-120):
    half-width = sqrt(base_range² + instruct_range²) / 2; significant when the
    CI excludes zero."""
    families = families or {
        k: v for k, v in MODEL_FAMILIES.items()
        if k in ("Falcon", "StableLM", "RedPajama")
    }
    by_model = {r["model"]: r for r in agreement["model_results"]}
    records = []
    for family, pair in families.items():
        base = by_model.get(pair["base"])
        inst = by_model.get(pair["instruct"])
        if base is None or inst is None:
            records.append({"family": family, "missing": True})
            continue
        for metric in metrics:
            diff = inst[f"{metric}_mean"] - base[f"{metric}_mean"]
            base_range = base[f"{metric}_ci_upper"] - base[f"{metric}_ci_lower"]
            inst_range = inst[f"{metric}_ci_upper"] - inst[f"{metric}_ci_lower"]
            half = float(np.sqrt(base_range ** 2 + inst_range ** 2)) / 2
            lo, hi = diff - half, diff + half
            records.append({
                "family": family, "metric": metric, "missing": False,
                "base_mean": base[f"{metric}_mean"],
                "base_ci": (base[f"{metric}_ci_lower"], base[f"{metric}_ci_upper"]),
                "instruct_mean": inst[f"{metric}_mean"],
                "instruct_ci": (inst[f"{metric}_ci_lower"], inst[f"{metric}_ci_upper"]),
                "diff": float(diff), "ci_lower": float(lo), "ci_upper": float(hi),
                "relative_change_pct": float(diff / base[f"{metric}_mean"] * 100)
                if base[f"{metric}_mean"] else float("nan"),
                "significant": bool(lo * hi > 0),
            })
    return records


def family_differences_text(records: List[Dict]) -> str:
    """The reference's printed per-family report + summary table."""
    lines = ["=== PER-FAMILY BASE vs INSTRUCT DIFFERENCES ===",
             "With 95% Confidence Intervals", "=" * 100]
    for rec in records:
        if rec.get("missing"):
            lines.append(f"\n{rec['family'].upper()}\nMissing data")
            continue
        if rec["metric"] == "mae":
            lines.append(f"\n{rec['family'].upper()}\n" + "-" * 60)
        pct = "%" if rec["metric"] == "mape" else ""
        fmt = ".1f" if rec["metric"] == "mape" else ".4f"
        lines += [
            f"\n{rec['metric'].upper()} Difference (Instruct - Base):",
            f"  Base:     {rec['base_mean']:{fmt}}{pct} "
            f"[{rec['base_ci'][0]:{fmt}}, {rec['base_ci'][1]:{fmt}}]",
            f"  Instruct: {rec['instruct_mean']:{fmt}}{pct} "
            f"[{rec['instruct_ci'][0]:{fmt}}, {rec['instruct_ci'][1]:{fmt}}]",
            f"  Absolute Difference: {rec['diff']:+{fmt}}{pct} "
            f"[{rec['ci_lower']:+{fmt}}, {rec['ci_upper']:+{fmt}}]",
            f"  Relative Change: {rec['relative_change_pct']:+.1f}%",
            ("  -> " + ("Significantly worse" if rec["diff"] > 0
                        else "Significantly better") + " (95% CI excludes 0)")
            if rec["significant"] else "  -> Not significant (95% CI includes 0)",
        ]
    lines += ["", "=== SUMMARY TABLE ===", "-" * 100,
              f"{'Family':<12} {'Metric':<6} {'Base':<12} {'Instruct':<12} "
              f"{'Difference':<14} {'Significant?':<14}", "-" * 100]
    for rec in records:
        if rec.get("missing"):
            continue
        fmt = ".1f" if rec["metric"] == "mape" else ".4f"
        lines.append(
            f"{rec['family']:<12} {rec['metric'].upper():<6} "
            f"{rec['base_mean']:<12{fmt}} {rec['instruct_mean']:<12{fmt}} "
            f"{rec['diff']:<+14{fmt}} "
            f"{'YES' if rec['significant'] else 'no':<14}"
        )
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# Ground-truth distribution figures
# ---------------------------------------------------------------------------

def ground_truth_values(survey_df: pd.DataFrame,
                        question_cols: Sequence[str]) -> np.ndarray:
    """Per-question human mean (0-1) — the 'ground truth' each model is scored
    against (visualize_ground_truth_distribution.py:22-76); delegates to the
    pipeline helper so the normalization convention has one home."""
    from .pipeline import human_responses_by_question

    cols = [q for q in question_cols if q in survey_df.columns]
    stats = human_responses_by_question(survey_df, cols)
    return np.asarray([s["mean"] / 100.0 for s in stats.values()])


def ground_truth_figures(human_values: np.ndarray, output_dir: str) -> Dict:
    """Two-panel (histogram + fitted normal; random-baseline overlay) and
    simplified single-panel figures (reference :79-199)."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt
    from scipy import stats as sstats

    os.makedirs(output_dir, exist_ok=True)
    pct = human_values * 100
    mean_pct, std_pct = float(np.mean(pct)), float(np.std(pct))
    x = np.linspace(0, 100, 200)

    fig, (ax1, ax2) = plt.subplots(1, 2, figsize=(14, 6))
    ax1.hist(pct, bins=30, density=True, alpha=0.7, color="#2ca02c",
             edgecolor="black", label="Actual Human Responses")
    ax1.plot(x, sstats.norm.pdf(x, mean_pct, std_pct), "r-", linewidth=2,
             label=f"Fitted Normal\nN({mean_pct:.1f}, {std_pct:.1f})")
    ax1.axvline(mean_pct, color="red", linestyle="--", linewidth=1.5,
                alpha=0.8, label=f"Mean: {mean_pct:.1f}%")
    ax1.axvline(mean_pct - std_pct, color="orange", linestyle=":", alpha=0.6)
    ax1.axvline(mean_pct + std_pct, color="orange", linestyle=":", alpha=0.6,
                label=f"±1 SD: {std_pct:.1f}%")
    ax1.set_xlabel('Percentage "Yes" Responses (%)')
    ax1.set_ylabel("Probability Density")
    ax1.set_title("Distribution of Human Ground Truth Values")
    ax1.set_xlim(0, 100)
    ax1.legend(loc="upper left", fontsize=9)

    rng = np.random.default_rng(42)
    samples = np.clip(rng.normal(mean_pct, std_pct, 10_000), 0, 100)
    ax2.hist(pct, bins=30, density=True, alpha=0.5, color="#2ca02c",
             edgecolor="black", label="Actual Human Data")
    ax2.hist(samples, bins=30, density=True, alpha=0.5, color="#17becf",
             edgecolor="black", label="Random Baseline\n(Sampled)")
    ax2.plot(x, sstats.norm.pdf(x, mean_pct, std_pct), "r-", linewidth=2,
             alpha=0.8, label=f"Theoretical N({mean_pct:.1f}, {std_pct:.1f})")
    ax2.axvline(mean_pct, color="red", linestyle="--", alpha=0.8)
    ax2.set_xlabel('Percentage "Yes" Responses (%)')
    ax2.set_ylabel("Probability Density")
    ax2.set_title("Random Baseline Distribution")
    ax2.set_xlim(0, 100)
    ax2.legend(loc="upper left", fontsize=9)
    fig.suptitle("Ground Truth Distribution Analysis for Random Baseline")
    fig.tight_layout()
    two_panel = os.path.join(output_dir, "ground_truth_distribution.png")
    fig.savefig(two_panel, dpi=150, bbox_inches="tight")
    plt.close(fig)

    fig, ax = plt.subplots(figsize=(10, 6))
    n, bins, _ = ax.hist(pct, bins=30, density=True, alpha=0.7,
                         color="#1f77b4", edgecolor="black")
    centers = (bins[:-1] + bins[1:]) / 2
    smoothed = _lowess(n, centers, frac=0.3)
    ax.plot(smoothed[:, 0], smoothed[:, 1], "r-", linewidth=2.5,
            label="Smoothed empirical distribution")
    ax.axvline(mean_pct, color="red", linestyle="--", linewidth=2, alpha=0.8,
               label=f"Mean = {mean_pct:.1f}%")
    ax.set_xlabel('Percentage of "Yes" Responses (%)')
    ax.set_ylabel("Probability Density")
    ax.set_xlim(0, 100)
    ax.legend(loc="upper left")
    fig.tight_layout()
    simple = os.path.join(output_dir, "ground_truth_distribution_simple.png")
    fig.savefig(simple, dpi=150, bbox_inches="tight")
    plt.close(fig)

    return {"two_panel": two_panel, "simple": simple,
            "mean": mean_pct / 100, "std": std_pct / 100,
            "n": int(human_values.size)}


def _lowess(y: np.ndarray, x: np.ndarray, frac: float = 0.3) -> np.ndarray:
    """Minimal tricube-weighted local linear smoother — the reference uses
    statsmodels' lowess (visualize_ground_truth_distribution.py:176-182),
    which is not in this image; same algorithm, one iteration."""
    order = np.argsort(x)
    x, y = np.asarray(x, float)[order], np.asarray(y, float)[order]
    n = len(x)
    span = max(2, int(np.ceil(frac * n)))
    out = np.empty(n)
    for i in range(n):
        d = np.abs(x - x[i])
        cutoff = np.sort(d)[span - 1]
        w = np.clip(1 - (d / max(cutoff, 1e-12)) ** 3, 0, 1) ** 3
        sw = w.sum()
        xm = (w * x).sum() / sw
        ym = (w * y).sum() / sw
        cov = (w * (x - xm) * (y - ym)).sum()
        var = (w * (x - xm) ** 2).sum()
        beta = cov / var if var > 0 else 0.0
        out[i] = ym + beta * (x[i] - xm)
    return np.column_stack([x, out])


def save_agreement_json(agreement: Dict, path: str) -> str:
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(agreement, f, indent=2, default=float)
    return path


# ---------------------------------------------------------------------------
# Point-estimate + question-bootstrap agreement reports
# (analyze_llm_human_agreement.py, analyze_llm_agreement_simple_bootstrap.py)
# ---------------------------------------------------------------------------

def human_agreement_means(survey_filepaths, llm_df: pd.DataFrame) -> Dict[str, float]:
    """prompt → cleaned human mean on the 0-1 scale.

    Rebuilds the ``survey_analysis_detailed.json`` input both agreement
    scripts consume (results.by_question.*.mean_response / 100) from the raw
    Qualtrics export: preregistered exclusions, then per-question means for
    every survey column whose question text matches an LLM prompt
    (analyze_llm_human_agreement.py:14-96)."""
    from .pipeline import (
        apply_exclusion_criteria,
        load_and_clean_survey_data,
        match_survey_to_llm_questions,
    )

    df, cols = load_and_clean_survey_data(survey_filepaths)
    clean, _ = apply_exclusion_criteria(df, cols)
    matches, _ = match_survey_to_llm_questions(llm_df, survey_filepaths)
    out: Dict[str, float] = {}
    for prompt, qid in matches.items():
        vals = pd.to_numeric(clean[qid], errors="coerce").dropna()
        if len(vals):
            out[prompt] = float(vals.mean()) / 100.0
    return out


def _matched_probs(model_df: pd.DataFrame, human_means: Dict[str, float]):
    """(prompt, human, model) triples; relative_prob preferred, yes/no
    fallback for CSVs without it (the base-model comparison CSV) — the
    scripts' column handling (analyze_llm_human_agreement.py:100-118)."""
    rows = []
    for _, row in model_df.iterrows():
        prompt = row["prompt"]
        if prompt not in human_means:
            continue
        if "relative_prob" in row.index and pd.notna(row.get("relative_prob")):
            p = float(row["relative_prob"])
        elif pd.notna(row.get("yes_prob")) and pd.notna(row.get("no_prob")):
            total = float(row["yes_prob"]) + float(row["no_prob"])
            p = float(row["yes_prob"]) / total if total > 0 else 0.5
        else:
            continue
        rows.append((prompt, human_means[prompt], p))
    return rows


def _model_frames(instruct_df, base_df):
    frames = []
    if base_df is not None:
        frames.extend((m, "base", base_df[base_df["model"] == m])
                      for m in base_df["model"].unique())
    frames.extend((m, "instruct", instruct_df[instruct_df["model"] == m])
                  for m in instruct_df["model"].unique())
    return frames


def human_agreement_report(
    instruct_df: pd.DataFrame,
    base_df: Optional[pd.DataFrame],
    human_means: Dict[str, float],
    min_questions: int = 10,
) -> Dict:
    """Point-estimate agreement per model (analyze_llm_human_agreement.py):
    MAE, RMSE, MAPE, Pearson/Spearman vs the cleaned human means, ranked by
    MAE, plus cross-model per-question variance — the exact
    ``llm_human_agreement_analysis.json`` shape (ibid.:289-307).

    The returned dict carries a non-serialized ``detailed`` list with each
    model's matched rows and 5 worst-disagreement questions (printed, not
    saved, by the reference)."""
    from scipy.stats import pearsonr, spearmanr

    results, detailed = [], []
    for model, model_type, mdf in _model_frames(instruct_df, base_df):
        rows = _matched_probs(mdf, human_means)
        if len(rows) < min_questions:
            continue
        h = np.array([r[1] for r in rows])
        p = np.array([r[2] for r in rows])
        mae = float(np.mean(np.abs(h - p)))
        rmse = float(np.sqrt(np.mean((h - p) ** 2)))
        # The reference divides unconditionally
        # (analyze_llm_human_agreement.py:130), so a near-zero human mean
        # would blow its MAPE up to inf.  No real survey question has a mean
        # <= 0.01; assert that so data violating it fails LOUDLY here rather
        # than silently dropping terms the reference would have included.
        if not (h > 0.01).all():
            raise ValueError(
                f"{model}: human mean <= 0.01 would make the reference's "
                f"unconditional MAPE non-finite")
        mape = float(np.mean(np.abs((h - p) / h)) * 100)
        pr, pp = pearsonr(h, p)
        sr, sp = spearmanr(h, p)
        order = np.argsort(-np.abs(h - p))
        worst = [
            {"prompt": rows[i][0], "human_avg": float(h[i]),
             "model_prob": float(p[i]), "difference": float(abs(h[i] - p[i]))}
            for i in order[:5]
        ]
        results.append({
            "model": model, "model_type": model_type, "mae": mae,
            "rmse": rmse, "mape": mape, "pearson_r": float(pr),
            "n_questions": len(rows),
        })
        detailed.append({
            "model": model, "model_type": model_type,
            "pearson_p": float(pp), "spearman_r": float(sr),
            "spearman_p": float(sp), "worst_questions": worst,
            "matched": rows,
        })
    order = np.argsort([r["mae"] for r in results])
    results = [results[i] for i in order]
    detailed = [detailed[i] for i in order]

    question_variance = {}
    for prompt, human_avg in human_means.items():
        probs = [p for d in detailed for (q, _, p) in d["matched"] if q == prompt]
        if probs:
            question_variance[prompt] = {
                "human_avg": float(human_avg),
                "model_mean": float(np.mean(probs)),
                "model_std": float(np.std(probs)),
                "n_models": len(probs),
            }
    return {
        "analysis_type": "llm_human_agreement",
        "description": "Comparison of LLM outputs to human average ratings "
                       "per question",
        "model_results": results,
        "question_variance": question_variance,
        "detailed": detailed,
    }


def agreement_question_bootstrap(
    instruct_df: pd.DataFrame,
    base_df: Optional[pd.DataFrame],
    human_means: Dict[str, float],
    n_bootstrap: int = 1000,
    confidence_level: float = 0.95,
    seed: int = 42,
    min_questions: int = 10,
    n_diff_bootstrap: int = 10000,
) -> Dict:
    """QUESTION-level bootstrap agreement
    (analyze_llm_agreement_simple_bootstrap.py): resample question indices
    with replacement, score each model on the sampled questions, report
    mean/95% CI/std per metric, then compare base vs instruct model groups
    with a bootstrap difference CI and a permutation p-value — the exact
    ``llm_human_agreement_bootstrap.json`` shape (ibid.:440-478).

    Faithfully reproduces the reference's membership-matching quirk: a
    question drawn twice still contributes ONCE per iteration (`prompt in
    sampled_questions`, ibid.:99-106), so each iteration is effectively a
    ~63% unique-question subsample.  The reference runs numpy's global
    unseeded RNG; ``seed`` makes ours reproducible."""
    alpha = 1 - confidence_level
    rng = np.random.default_rng(seed)
    all_questions = list(human_means.keys())
    n_q = len(all_questions)
    qindex = {q: j for j, q in enumerate(all_questions)}

    model_results = []
    base_count = instruct_count = 0
    for model, model_type, mdf in _model_frames(instruct_df, base_df):
        rows = _matched_probs(mdf, human_means)
        h_full = np.full(n_q, np.nan)
        p_full = np.full(n_q, np.nan)
        for prompt, h, p in rows:
            h_full[qindex[prompt]] = h
            p_full[qindex[prompt]] = p
        per_iter = {"mae": [], "mse": [], "mape": [], "pearson_r": []}
        ok = 0
        for _ in range(n_bootstrap):
            sampled = np.unique(rng.integers(0, n_q, size=n_q))
            mask = np.zeros(n_q, bool)
            mask[sampled] = True
            mask &= np.isfinite(p_full)
            if mask.sum() < min_questions:
                continue
            ok += 1
            h = h_full[mask]
            p = p_full[mask]
            err = h - p
            per_iter["mae"].append(np.mean(np.abs(err)))
            per_iter["mse"].append(np.mean(err ** 2))
            with np.errstate(divide="ignore", invalid="ignore"):
                ape = np.abs(err / h)
            ape = ape[np.isfinite(ape)]
            per_iter["mape"].append(np.mean(ape) * 100 if ape.size else np.nan)
            if h.std() > 0 and p.std() > 0:
                per_iter["pearson_r"].append(float(np.corrcoef(h, p)[0, 1]))
            else:
                per_iter["pearson_r"].append(np.nan)
        # the reference's "at least 100 successful bootstraps" floor, scaled
        # down when the caller requests fewer iterations overall (otherwise a
        # small --bootstrap run silently drops every model)
        if ok < min(100, n_bootstrap):
            continue
        rec = {"model": model, "model_type": model_type, "n_bootstrap": ok}
        for metric, vals in per_iter.items():
            rec.update(_metric_summary(metric, vals, alpha))
        model_results.append(rec)
        if model_type == "base":
            base_count += 1
        else:
            instruct_count += 1

    model_results.sort(key=lambda r: r["mae_mean"])

    overall = {"base_models_count": base_count,
               "instruct_models_count": instruct_count, "metrics": {}}
    base_recs = [r for r in model_results if r["model_type"] == "base"]
    inst_recs = [r for r in model_results if r["model_type"] == "instruct"]
    for metric in ("mae", "mse", "mape"):
        bv = np.array([r[f"{metric}_mean"] for r in base_recs
                       if np.isfinite(r[f"{metric}_mean"])])
        iv = np.array([r[f"{metric}_mean"] for r in inst_recs
                       if np.isfinite(r[f"{metric}_mean"])])
        if not (bv.size and iv.size):
            continue
        diff = float(bv.mean() - iv.mean())
        n1, n2 = len(bv), len(iv)
        boot = np.empty(n_diff_bootstrap)
        for b in range(n_diff_bootstrap):
            boot[b] = (rng.choice(bv, n1, replace=True).mean()
                       - rng.choice(iv, n2, replace=True).mean())
        pooled = np.concatenate([bv, iv])
        null = np.empty(n_diff_bootstrap)
        for b in range(n_diff_bootstrap):
            perm = rng.permutation(pooled)
            null[b] = perm[:n1].mean() - perm[n1:].mean()
        lo, hi = alpha / 2 * 100, (1 - alpha / 2) * 100
        overall["metrics"][metric] = {
            "base_mean": float(bv.mean()),
            "base_ci": [float(np.percentile(bv, lo)), float(np.percentile(bv, hi))],
            "instruct_mean": float(iv.mean()),
            "instruct_ci": [float(np.percentile(iv, lo)), float(np.percentile(iv, hi))],
            "difference": diff,
            "difference_ci": [float(np.percentile(boot, lo)),
                              float(np.percentile(boot, hi))],
            "p_value": float(np.mean(np.abs(null) >= abs(diff))),
        }
    return {
        "analysis_type": "llm_human_agreement_bootstrap_questions",
        "description": "Comparison of LLM outputs to human average ratings "
                       "with bootstrap confidence intervals (sampling "
                       "questions)",
        "bootstrap_parameters": {
            "n_iterations": n_bootstrap,
            "confidence_level": confidence_level,
            "bootstrap_method": "questions_with_replacement",
        },
        "model_results": model_results,
        "overall_comparison": overall,
    }
