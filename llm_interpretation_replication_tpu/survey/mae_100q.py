"""Base-vs-instruct MAE vs human means (paper Table 5).

Behavioral replica of survey_analysis/analyze_base_vs_instruct_mae_100q.py:
MODEL_FAMILIES map, data-quality gates (std < 0.01 or > 50% NaN excludes a
model), per-family MAE against the human mean, and a paired bootstrap (10k,
seed 42) of the instruct − base MAE difference with CI and two-sided p.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import pandas as pd

MODEL_FAMILIES = {
    "Falcon": {"base": "tiiuae/falcon-7b", "instruct": "tiiuae/falcon-7b-instruct"},
    "StableLM": {
        "base": "stabilityai/stablelm-base-alpha-7b",
        "instruct": "stabilityai/stablelm-tuned-alpha-7b",
    },
    "RedPajama": {
        "base": "togethercomputer/RedPajama-INCITE-7B-Base",
        "instruct": "togethercomputer/RedPajama-INCITE-7B-Instruct",
    },
    "BLOOM": {"base": "bigscience/bloom-7b1", "instruct": "bigscience/bloomz-7b1"},
    "Pythia-Dolly": {
        "base": "EleutherAI/pythia-6.9b",
        "instruct": "databricks/dolly-v2-7b",
    },
    "Mistral": {
        "base": "mistralai/Mistral-7B-v0.1",
        "instruct": "mistralai/Mistral-7B-Instruct-v0.2",
    },
}

MIN_STD_THRESHOLD = 0.01
MAX_NAN_FRACTION = 0.5
N_BOOTSTRAP = 10_000
RANDOM_SEED = 42


def validate_model_data(model_df: pd.DataFrame, model_name: str) -> Tuple[bool, str]:
    """Quality gates: enough data, not mostly NaN, not constant."""
    data = model_df[model_df["model"] == model_name]["relative_prob"]
    if len(data) == 0:
        return False, "No data found"
    nan_fraction = data.isna().sum() / len(data)
    if nan_fraction > MAX_NAN_FRACTION:
        return False, f"{nan_fraction * 100:.0f}% NaN values"
    valid = data.dropna()
    if len(valid) > 1 and valid.std() < MIN_STD_THRESHOLD:
        return False, f"Constant values (std={valid.std():.4f})"
    return True, "OK"


def mae_per_model(
    model_df: pd.DataFrame,
    human_avgs: Dict[str, float],
    matches: Dict[str, str],
    model_name: str,
) -> Tuple[Optional[float], List[float], List[str]]:
    """(MAE, per-question |error| list, matched prompts) vs human means (0-1)."""
    sub = model_df[model_df["model"] == model_name]
    errors, prompts = [], []
    for _, row in sub.iterrows():
        prompt = row["prompt"]
        qid = matches.get(prompt)
        if qid is not None and qid in human_avgs:
            if pd.notna(row["relative_prob"]):
                errors.append(abs(float(row["relative_prob"]) - human_avgs[qid]))
                prompts.append(prompt)
    if errors:
        return float(np.mean(errors)), errors, prompts
    return None, [], []


def paired_bootstrap_mae_difference(
    base_errors: Sequence[float],
    instruct_errors: Sequence[float],
    n_bootstrap: int = N_BOOTSTRAP,
    seed: int = RANDOM_SEED,
) -> Dict:
    """Paired resampling of question indices; CI + two-sided p for
    instruct − base MAE."""
    base = np.asarray(base_errors, dtype=float)
    inst = np.asarray(instruct_errors, dtype=float)
    n = min(len(base), len(inst))
    base, inst = base[:n], inst[:n]
    observed = float(np.mean(inst) - np.mean(base))
    rng = np.random.default_rng(seed)
    idx = rng.integers(0, n, size=(n_bootstrap, n))
    diffs = np.mean(inst[idx], axis=1) - np.mean(base[idx], axis=1)
    if observed > 0:
        p = 2 * float(np.mean(diffs <= 0))
    else:
        p = 2 * float(np.mean(diffs >= 0))
    return {
        "observed_diff": observed,
        "base_mae": float(np.mean(base)),
        "instruct_mae": float(np.mean(inst)),
        "ci_lower": float(np.percentile(diffs, 2.5)),
        "ci_upper": float(np.percentile(diffs, 97.5)),
        "p_value": min(p, 1.0),
        "n": int(n),
    }


def analyze_families(
    model_df: pd.DataFrame,
    human_avgs: Dict[str, float],
    matches: Dict[str, str],
    families: Optional[Dict] = None,
    n_bootstrap: int = N_BOOTSTRAP,
    seed: int = RANDOM_SEED,
) -> Dict[str, Dict]:
    """Per-family Table-5 records; pooled record under key '_overall'."""
    families = families or MODEL_FAMILIES
    results: Dict[str, Dict] = {}
    pooled_base: List[float] = []
    pooled_inst: List[float] = []
    for family, pair in families.items():
        rec: Dict = {"base_model": pair["base"], "instruct_model": pair["instruct"]}
        ok_b, why_b = validate_model_data(model_df, pair["base"])
        ok_i, why_i = validate_model_data(model_df, pair["instruct"])
        if not ok_b or not ok_i:
            rec["excluded"] = True
            rec["reason"] = f"base: {why_b}; instruct: {why_i}"
            results[family] = rec
            continue
        base_mae, base_err, base_prompts = mae_per_model(model_df, human_avgs, matches, pair["base"])
        inst_mae, inst_err, inst_prompts = mae_per_model(model_df, human_avgs, matches, pair["instruct"])
        if base_mae is None or inst_mae is None:
            rec["excluded"] = True
            rec["reason"] = "no matched questions"
            results[family] = rec
            continue
        # pair on common prompts for the paired bootstrap
        common = [p for p in base_prompts if p in set(inst_prompts)]
        b_map = dict(zip(base_prompts, base_err))
        i_map = dict(zip(inst_prompts, inst_err))
        base_paired = [b_map[p] for p in common]
        inst_paired = [i_map[p] for p in common]
        boot = paired_bootstrap_mae_difference(base_paired, inst_paired, n_bootstrap, seed)
        # boot's base/instruct MAE are over paired prompts only; keep the
        # all-prompt MAEs as the headline values (reference behavior)
        boot.pop("base_mae", None)
        boot.pop("instruct_mae", None)
        rec.update(excluded=False, base_mae=base_mae, instruct_mae=inst_mae, **boot)
        results[family] = rec
        pooled_base.extend(base_paired)
        pooled_inst.extend(inst_paired)
    if pooled_base:
        results["_overall"] = paired_bootstrap_mae_difference(
            pooled_base, pooled_inst, n_bootstrap, seed
        )
    return results
