from .demographics import (
    demographics_latex_table,
    load_demographics,
    summarize_age,
    summarize_categorical,
)
from .mae_100q import (
    MODEL_FAMILIES,
    analyze_families,
    mae_per_model,
    paired_bootstrap_mae_difference,
    validate_model_data,
)
from .variants import (
    agreement_bootstrap,
    family_differences,
    family_differences_text,
    ground_truth_figures,
    ground_truth_values,
    human_proportions_by_prompt,
    model_human_correlations,
    output_validity_audit,
    probability_distribution_stats,
    three_way_report,
)
from .pipeline import (
    apply_exclusion_criteria,
    cross_prompt_difference_ci,
    extract_question_text,
    human_cross_prompt_correlations,
    human_llm_correlation,
    human_responses_by_question,
    llm_cross_prompt_correlations,
    llm_responses_by_question,
    load_and_clean_survey_data,
    match_survey_to_llm_questions,
    pearson_with_bootstrap,
    per_item_agreement_humans,
    per_item_agreement_llms,
)
