from .mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    PIPE_AXIS,
    SEQ_AXIS,
    data_sharded,
    enumerate_mesh_shapes,
    initialize_distributed,
    make_mesh,
    mesh_shape_for,
    replicated,
)
from .pipeline import (
    pipeline_apply,
    pipeline_decoder_forward,
    split_stage_params,
)
from .ring_attention import ring_attention, ring_attention_sharded
from .ulysses import ulysses_attention, ulysses_attention_sharded
from .sharding import (
    activation_spec,
    batch_spec,
    constrain,
    param_specs,
    shard_params,
)

__all__ = [
    "DATA_AXIS",
    "MODEL_AXIS",
    "PIPE_AXIS",
    "SEQ_AXIS",
    "pipeline_apply",
    "pipeline_decoder_forward",
    "split_stage_params",
    "data_sharded",
    "initialize_distributed",
    "enumerate_mesh_shapes",
    "make_mesh",
    "mesh_shape_for",
    "replicated",
    "ring_attention",
    "ring_attention_sharded",
    "ulysses_attention",
    "ulysses_attention_sharded",
    "activation_spec",
    "batch_spec",
    "constrain",
    "param_specs",
    "shard_params",
]
