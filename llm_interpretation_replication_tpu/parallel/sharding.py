"""GSPMD sharding rules for model param pytrees and activations.

Tensor parallelism follows the Megatron pattern expressed as GSPMD
annotations (XLA inserts the collectives — scaling-book recipe):

- attention: Q/K/V projections column-sharded over heads (``model`` axis on
  the N*D output dim), output projection row-sharded (``model`` on the N*D
  input dim) → one psum per attention block, emitted by XLA.
- MLP: up/gate column-sharded, down row-sharded → one psum per MLP.
- embeddings / lm_head sharded on the vocab dim; layernorms replicated.
- the stacked layer axis L is never sharded.

This replaces the reference's single-GPU ``device_map="auto"`` layer offload
(run_base_vs_instruct_100q.py:427) — a 7B bf16 model fits a v5e slice by
sharding, not by int8 quantization.
"""

from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import DATA_AXIS, MODEL_AXIS, SEQ_AXIS


def _decoder_param_specs() -> dict:
    """PartitionSpec tree matching models/decoder.py's param layout."""
    attn = {
        "wq": P(None, None, MODEL_AXIS),
        "wk": P(None, None, MODEL_AXIS),
        "wv": P(None, None, MODEL_AXIS),
        "wo": P(None, MODEL_AXIS, None),
        "bq": P(None, MODEL_AXIS),
        "bk": P(None, MODEL_AXIS),
        "bv": P(None, MODEL_AXIS),
        "bo": P(None),
        # w8a8 int8 scales (ops/quant.py): per-output-channel, so they shard
        # with the weight's output dim (column-sharded) or replicate (row-).
        "wq_qscale": P(None, MODEL_AXIS),
        "wk_qscale": P(None, MODEL_AXIS),
        "wv_qscale": P(None, MODEL_AXIS),
        "wo_qscale": P(None),
    }
    mlp = {
        "wi": P(None, None, MODEL_AXIS),
        "wg": P(None, None, MODEL_AXIS),
        "bi": P(None, MODEL_AXIS),
        "bg": P(None, MODEL_AXIS),
        "wo": P(None, MODEL_AXIS, None),
        "bo": P(None),
        "wi_qscale": P(None, MODEL_AXIS),
        "wg_qscale": P(None, MODEL_AXIS),
        "wo_qscale": P(None),
    }
    ln = {"scale": P(None), "bias": P(None)}
    return {
        "embed": {"tokens": P(MODEL_AXIS, None), "pos": P(None), "ln": {"scale": P(), "bias": P()}},
        "layers": {"ln1": ln, "ln2": ln, "attn": attn, "mlp": mlp},
        "final_ln": {"scale": P(), "bias": P()},
        "lm_head": P(None, MODEL_AXIS),
    }


def _t5_param_specs() -> dict:
    attn = {
        "wq": P(None, None, MODEL_AXIS),
        "wk": P(None, None, MODEL_AXIS),
        "wv": P(None, None, MODEL_AXIS),
        "wo": P(None, MODEL_AXIS, None),
    }
    mlp = {
        "wi": P(None, None, MODEL_AXIS),
        "wi0": P(None, None, MODEL_AXIS),
        "wi1": P(None, None, MODEL_AXIS),
        "wo": P(None, MODEL_AXIS, None),
    }
    ln = {"scale": P(None)}
    return {
        "shared": P(MODEL_AXIS, None),
        "encoder": {
            "rel_bias": P(),
            "layers": {"ln1": ln, "ln2": ln, "attn": attn, "mlp": mlp},
            "final_ln": {"scale": P()},
        },
        "decoder": {
            "rel_bias": P(),
            "layers": {
                "ln1": ln, "ln2": ln, "ln3": ln,
                "self_attn": attn, "cross_attn": attn, "mlp": mlp,
            },
            "final_ln": {"scale": P()},
        },
        "lm_head": P(None, MODEL_AXIS),
    }


def _match_tree(params, spec_tree, path=""):
    """Walk ``params``; for every leaf take the spec at the same path (falling
    back to replicated)."""
    if isinstance(params, dict):
        out = {}
        for k, v in params.items():
            sub = spec_tree.get(k, {}) if isinstance(spec_tree, dict) else {}
            out[k] = _match_tree(v, sub, f"{path}/{k}")
        return out
    return spec_tree if isinstance(spec_tree, P) else P()


def param_specs(params, kind: str = "decoder") -> dict:
    """PartitionSpec pytree for a params pytree (missing entries replicate)."""
    table = _decoder_param_specs() if kind == "decoder" else _t5_param_specs()
    return _match_tree(params, table)


def shard_params(params, mesh: Mesh, kind: str = "decoder"):
    """Place a host pytree onto the mesh with TP sharding (HBM-resident)."""
    specs = param_specs(params, kind)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs
    )


def batch_spec() -> P:
    """Activations: batch over data axis, sequence optionally over seq axis."""
    return P(DATA_AXIS)


def activation_spec(seq_sharded: bool = False) -> P:
    return P(DATA_AXIS, SEQ_AXIS if seq_sharded else None)


def constrain(x, mesh: Mesh, spec: P):
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))
