"""Ulysses-style all-to-all sequence parallelism.

The second long-context strategy next to ring attention
(parallel/ring_attention.py): instead of rotating K/V blocks around a ring,
one ``all_to_all`` redistributes the sequence-sharded activations into
head-sharded ones — each device then holds the FULL sequence for a subset of
heads and runs ordinary dense attention locally — and a second ``all_to_all``
restores sequence sharding afterwards (DeepSpeed-Ulysses, arXiv:2309.14509).

Tradeoffs vs the ring (why both exist):
- Ulysses moves activations twice over ICI regardless of sequence length and
  needs ``num_heads`` divisible by the seq-axis size; attention itself is the
  plain XLA kernel (full S locally, so peak memory carries an S x S score
  block per local head).
- Ring keeps O(S/n) K/V per device (no head-count constraint, O(S/n) score
  blocks) but pays n ppermute hops and an online-softmax recurrence.
For the sweep's bucket lengths Ulysses wins on simplicity; for very long
sequences where S x S scores do not fit, use the ring.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .mesh import SEQ_AXIS
from .ring_attention import _block_bias, sharded_seq_attention


def ulysses_attention(
    q,            # [B, S/n, N, D]  sequence-sharded local block
    k,            # [B, S/n, N, D]
    v,            # [B, S/n, N, D]
    q_pos,        # [B, S/n]  absolute positions of local queries
    kv_valid,     # [B, S/n]  bool validity of local keys
    axis_name: str = SEQ_AXIS,
    causal: bool = True,
):
    """Per-shard Ulysses body (run under shard_map with ``axis_name`` bound).

    all_to_all #1: seq-sharded [B, S/n, N, D] -> head-sharded [B, S, N/n, D];
    dense attention over the full sequence locally; all_to_all #2 back.
    """
    n = lax.axis_size(axis_name)
    b, s_local, nh, d = q.shape
    if nh % n != 0:
        raise ValueError(f"num_heads {nh} not divisible by seq axis {n}")

    def scatter_heads(x):
        # split the head axis n-ways, concatenate the sequence axis
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    qg = scatter_heads(q)                      # [B, S, N/n, D]
    kg = scatter_heads(k)
    vg = scatter_heads(v)
    pos = lax.all_gather(q_pos, axis_name, axis=1, tiled=True)      # [B, S]
    valid = lax.all_gather(kv_valid, axis_name, axis=1, tiled=True)

    scale = 1.0 / jnp.sqrt(d).astype(qg.dtype)
    scores = jnp.einsum("bsnd,btnd->bnst", qg * scale, kg).astype(jnp.float32)
    scores = scores + _block_bias(pos, pos, valid, causal)  # shared mask logic
    probs = jax.nn.softmax(scores, axis=-1).astype(qg.dtype)
    out = jnp.einsum("bnst,btnd->bsnd", probs, vg)                  # [B, S, N/n, D]
    # fully-masked batch rows (no valid key anywhere) would softmax uniformly
    # over the NEG_INF scores; return 0 like the ring's l>0 guard
    out = jnp.where(jnp.any(valid, axis=-1)[:, None, None, None], out, 0.0)

    # inverse redistribution: split sequence, concatenate heads
    return lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2, tiled=True)


def ulysses_attention_sharded(mesh, q, k, v, attention_mask, causal: bool = True):
    """Drive Ulysses attention over a (data, model, seq) mesh — the same
    calling convention (and shared driver) as ``ring_attention_sharded``.

    q/k/v: [B, S, N, D] with S divisible by the seq-axis size and N divisible
    by seq_axis * model_axis; attention_mask [B, S].
    """
    def body(q, k, v, pos, val):
        return ulysses_attention(q, k, v, pos, val, SEQ_AXIS, causal)

    return sharded_seq_attention(mesh, body, q, k, v, attention_mask)
