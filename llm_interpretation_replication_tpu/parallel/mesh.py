"""Device-mesh construction and multi-host initialization.

The reference has no distributed runtime (SURVEY.md §2.7: concurrency is
thread-level API fan-out only); scaling here is TPU-native: a
``jax.sharding.Mesh`` over (data, model, seq) axes, GSPMD shardings from
parallel/sharding.py, and XLA collectives over ICI/DCN.  Multi-host pods
bootstrap via ``jax.distributed.initialize``.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"
PIPE_AXIS = "pipe"


def make_mesh(
    data: Optional[int] = None,
    model: int = 1,
    seq: int = 1,
    pipe: int = 1,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a (data, pipe, model, seq) mesh.  ``data`` defaults to whatever
    is left after pipe×model×seq divides the device count.  The pipe axis sits
    between data and model so pipeline-neighbor ``ppermute`` hops stay within
    a contiguous device block while TP collectives ride the innermost ring."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if data is None:
        if n % (pipe * model * seq):
            raise ValueError(
                f"{n} devices not divisible by pipe={pipe} × model={model} × seq={seq}"
            )
        data = n // (pipe * model * seq)
    if data * pipe * model * seq != n:
        raise ValueError(f"mesh {data}×{pipe}×{model}×{seq} != {n} devices")
    arr = np.asarray(devices).reshape(data, pipe, model, seq)
    return Mesh(arr, (DATA_AXIS, PIPE_AXIS, MODEL_AXIS, SEQ_AXIS))


def mesh_shape_for(n_devices: int, want_model: int = 1, want_seq: int = 1) -> Tuple[int, int, int]:
    """Largest data axis given desired model/seq parallelism, shrinking model
    then seq until they divide the device count."""
    model, seq = want_model, want_seq
    while n_devices % (model * seq) and model > 1:
        model //= 2
    while n_devices % (model * seq) and seq > 1:
        seq //= 2
    return n_devices // (model * seq), model, seq


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def data_sharded(mesh: Mesh, rank: int = 2) -> NamedSharding:
    """Batch-leading arrays sharded over the data axis."""
    return NamedSharding(mesh, P(DATA_AXIS, *([None] * (rank - 1))))


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Multi-host bootstrap.  No-op (returns False) outside a pod/cluster so
    single-host dev keeps working; honors the standard JAX env vars when args
    are not given."""
    coordinator_address = coordinator_address or os.environ.get("JAX_COORDINATOR_ADDRESS")
    if num_processes is None:
        num_processes = int(os.environ.get("JAX_NUM_PROCESSES", "0")) or None
    if process_id is None:
        pid = os.environ.get("JAX_PROCESS_ID")
        process_id = int(pid) if pid is not None else None
    if not coordinator_address and num_processes in (None, 1):
        return False
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return True
