"""Device-mesh construction and multi-host initialization.

The reference has no distributed runtime (SURVEY.md §2.7: concurrency is
thread-level API fan-out only); scaling here is TPU-native: a
``jax.sharding.Mesh`` over (data, model, seq) axes, GSPMD shardings from
parallel/sharding.py, and XLA collectives over ICI/DCN.  Multi-host pods
bootstrap via ``jax.distributed.initialize``.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"
PIPE_AXIS = "pipe"


def make_mesh(
    data: Optional[int] = None,
    model: int = 1,
    seq: int = 1,
    pipe: int = 1,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a (data, pipe, model, seq) mesh.  ``data`` defaults to whatever
    is left after pipe×model×seq divides the device count.  The pipe axis sits
    between data and model so pipeline-neighbor ``ppermute`` hops stay within
    a contiguous device block while TP collectives ride the innermost ring."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if data is None:
        if n % (pipe * model * seq):
            raise ValueError(
                f"{n} devices not divisible by pipe={pipe} × model={model} × seq={seq}"
            )
        data = n // (pipe * model * seq)
    if data * pipe * model * seq != n:
        raise ValueError(f"mesh {data}×{pipe}×{model}×{seq} != {n} devices")
    arr = np.asarray(devices).reshape(data, pipe, model, seq)
    return Mesh(arr, (DATA_AXIS, PIPE_AXIS, MODEL_AXIS, SEQ_AXIS))


def enumerate_mesh_shapes(
    n_devices: int,
    max_model: Optional[int] = None,
    max_pipe: int = 1,
) -> Tuple[Tuple[int, int, int], ...]:
    """Every (data, pipe, model) factorization of ``n_devices`` (seq=1).

    The auto-parallel plan search (runtime/plan_search.py) enumerates these
    as its mesh axis of the candidate space; listing them HERE, next to
    :func:`make_mesh`, keeps the enumeration and the constructor agreeing on
    what a legal mesh is (every returned shape satisfies
    ``data * pipe * model == n_devices`` and builds without error).
    ``max_model``/``max_pipe`` bound the model/pipe degrees (a tp or pp
    degree beyond the caller's interconnect or layer count is never a
    candidate worth pricing); shapes are ordered data-major (pure dp first).
    """
    if n_devices < 1:
        raise ValueError(f"n_devices must be >= 1, got {n_devices}")
    shapes = []
    for pipe in range(1, max(1, max_pipe) + 1):
        if n_devices % pipe:
            continue
        rem = n_devices // pipe
        for model in range(1, rem + 1):
            if rem % model:
                continue
            if max_model is not None and model > max_model:
                continue
            shapes.append((rem // model, pipe, model))
    return tuple(sorted(set(shapes), key=lambda s: (-s[0], s[1], s[2])))


def carve_slices(
    n_slices: Optional[int] = None,
    devices: Optional[Sequence] = None,
    counts: Optional[Sequence[int]] = None,
) -> Tuple[Tuple, ...]:
    """Partition the pod's device list into per-replica slices.

    The EnginePool (serve/pool.py) calls this once per roster so each
    replica binds a :func:`make_mesh` over ITS slice instead of the whole
    device view — contiguous runs, because ICI neighbors stay neighbors
    inside a contiguous block and a replica's collectives should never
    straddle another replica's chips.  Two spellings:

    - ``counts=(4, 2, 2)`` — explicit per-slice chip counts for a
      heterogeneous roster (the disaggregated prefill/decode fleet gives
      prefill replicas wider slices than decode replicas); must sum to
      the device count.
    - ``n_slices=N`` — N equal slices; the device count must divide.

    When there are FEWER devices than slices (the CPU harness: one host
    device, many replicas) every slice degenerates to the full device
    list — shared placement, exactly the pre-slice behavior.  This keeps
    the two-role pool runnable on the CPU harness; the record's
    ``placement`` field says ``shared`` so nobody mistakes it for real
    disaggregation.
    """
    devices = tuple(devices if devices is not None else jax.devices())
    n = len(devices)
    if counts is not None:
        counts = tuple(int(c) for c in counts)
        if n_slices is not None and len(counts) != n_slices:
            raise ValueError(
                f"counts has {len(counts)} entries for n_slices={n_slices}")
        if any(c < 1 for c in counts):
            raise ValueError(f"every slice needs >= 1 device, got {counts}")
        if n < len(counts):
            return tuple(devices for _ in counts)
        if sum(counts) != n:
            raise ValueError(
                f"counts {counts} sum to {sum(counts)}, not {n} devices")
        out, at = [], 0
        for c in counts:
            out.append(devices[at:at + c])
            at += c
        return tuple(out)
    if n_slices is None or n_slices < 1:
        raise ValueError(f"n_slices must be >= 1, got {n_slices}")
    if n < n_slices:
        return tuple(devices for _ in range(n_slices))
    if n % n_slices:
        raise ValueError(
            f"{n} devices not divisible into {n_slices} equal slices; "
            f"pass counts= for a heterogeneous split")
    per = n // n_slices
    return tuple(devices[i * per:(i + 1) * per] for i in range(n_slices))


def mesh_shape_for(n_devices: int, want_model: int = 1, want_seq: int = 1) -> Tuple[int, int, int]:
    """Largest data axis given desired model/seq parallelism, shrinking model
    then seq until they divide the device count."""
    model, seq = want_model, want_seq
    while n_devices % (model * seq) and model > 1:
        model //= 2
    while n_devices % (model * seq) and seq > 1:
        seq //= 2
    return n_devices // (model * seq), model, seq


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def data_sharded(mesh: Mesh, rank: int = 2) -> NamedSharding:
    """Batch-leading arrays sharded over the data axis."""
    return NamedSharding(mesh, P(DATA_AXIS, *([None] * (rank - 1))))


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Multi-host bootstrap.  No-op (returns False) outside a pod/cluster so
    single-host dev keeps working; honors the standard JAX env vars when args
    are not given."""
    coordinator_address = coordinator_address or os.environ.get("JAX_COORDINATOR_ADDRESS")
    if num_processes is None:
        num_processes = int(os.environ.get("JAX_NUM_PROCESSES", "0")) or None
    if process_id is None:
        pid = os.environ.get("JAX_PROCESS_ID")
        process_id = int(pid) if pid is not None else None
    if not coordinator_address and num_processes in (None, 1):
        return False
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return True
