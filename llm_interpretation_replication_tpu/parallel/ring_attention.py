"""Ring attention: sequence/context parallelism over the ``seq`` mesh axis.

Long-context first-class support (absent in the reference — SURVEY.md §2.7):
K/V blocks rotate around the ring via ``ppermute`` while each device keeps its
resident Q block, combining partial results with an online (flash-style)
softmax — O(S/n) memory per device, compute overlapped with ICI transfers by
XLA's latency-hiding scheduler.

``ring_attention`` is the per-shard body (call under ``shard_map``);
``ring_attention_sharded`` wraps it for a (data, model, seq) mesh.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from .mesh import DATA_AXIS, MODEL_AXIS, SEQ_AXIS

NEG_INF = -1e9


def _block_bias(q_pos, kv_pos, kv_valid, causal: bool):
    """fp32 additive bias [B, 1, Sq, Skv] from absolute positions."""
    ok = kv_valid[:, None, :]
    if causal:
        ok = ok & (q_pos[:, :, None] >= kv_pos[:, None, :])
    return jnp.where(ok[:, None], 0.0, NEG_INF).astype(jnp.float32)


def ring_attention(
    q,            # [B, Sq, N, D]  local query block
    k,            # [B, Skv, N, D] local key block (will rotate)
    v,            # [B, Skv, N, D]
    q_pos,        # [B, Sq]  absolute positions of local queries
    kv_pos,       # [B, Skv] absolute positions of local keys
    kv_valid,     # [B, Skv] bool validity of local keys
    axis_name: str = SEQ_AXIS,
    causal: bool = True,
):
    """Per-shard ring attention body.  Must run inside shard_map with
    ``axis_name`` bound to the sequence mesh axis."""
    n = lax.axis_size(axis_name)
    b, sq, nh, d = q.shape
    scale = 1.0 / jnp.sqrt(d).astype(q.dtype)

    m = jnp.full((b, nh, sq), NEG_INF, jnp.float32)      # running max
    l = jnp.zeros((b, nh, sq), jnp.float32)              # running denominator
    o = jnp.zeros((b, sq, nh, d), jnp.float32)           # running numerator

    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, _):
        m, l, o, k, v, kv_pos, kv_valid = carry
        scores = jnp.einsum("bsnd,btnd->bnst", q * scale, k).astype(jnp.float32)
        scores = scores + _block_bias(q_pos, kv_pos, kv_valid, causal)
        blk_max = jnp.max(scores, axis=-1)                       # [B,N,Sq]
        m_new = jnp.maximum(m, blk_max)
        correction = jnp.exp(m - m_new)
        p = jnp.exp(scores - m_new[..., None])                   # [B,N,Sq,Skv]
        l_new = l * correction + jnp.sum(p, axis=-1)
        pv = jnp.einsum("bnst,btnd->bsnd", p.astype(v.dtype), v).astype(jnp.float32)
        o_new = o * jnp.moveaxis(correction, 1, 2)[..., None] + pv
        # rotate K/V (and their metadata) one hop around the ring
        k = lax.ppermute(k, axis_name, perm)
        v = lax.ppermute(v, axis_name, perm)
        kv_pos = lax.ppermute(kv_pos, axis_name, perm)
        kv_valid = lax.ppermute(kv_valid, axis_name, perm)
        return (m_new, l_new, o_new, k, v, kv_pos, kv_valid), None

    (m, l, o, *_), _ = lax.scan(step, (m, l, o, k, v, kv_pos, kv_valid), None, length=n)
    denom = jnp.moveaxis(l, 1, 2)[..., None]
    out = jnp.where(denom > 0, o / jnp.maximum(denom, 1e-30), 0.0)
    # Batch rows with no valid key on ANY shard: the finite NEG_INF bias makes
    # p = exp(0-ish) per masked entry, so denom stays positive and the result
    # is softmax-of-garbage.  Zero those rows explicitly (the Ulysses leg does
    # the same, keeping the two SP strategies bit-consistent).
    has_key = lax.psum(jnp.any(kv_valid, axis=-1).astype(jnp.int32), axis_name) > 0
    out = jnp.where(has_key[:, None, None, None], out, 0.0)
    return out.astype(q.dtype)


def sharded_seq_attention(mesh, body, q, k, v, attention_mask):
    """Shared shard_map driver for every sequence-parallel attention strategy
    (ring, Ulysses): batch over ``data``, heads over ``model``, sequence over
    ``seq``.  ``body(q, k, v, pos, valid)`` is the per-shard computation."""
    b, s, nh, d = q.shape
    positions = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32)[None], (b, s))
    valid = attention_mask.astype(bool)

    qkv_spec = P(DATA_AXIS, SEQ_AXIS, MODEL_AXIS, None)
    meta_spec = P(DATA_AXIS, SEQ_AXIS)

    run = functools.partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, meta_spec, meta_spec),
        out_specs=qkv_spec,
        check_vma=False,
    )(body)
    return run(q, k, v, positions, valid)


def ring_attention_sharded(mesh, q, k, v, attention_mask, causal: bool = True):
    """Drive ring attention over a (data, model, seq) mesh.

    q/k/v: [B, S, N, D] with S divisible by the seq-axis size; attention_mask
    [B, S].
    """
    def body(q, k, v, pos, val):
        return ring_attention(q, k, v, pos, pos, val, SEQ_AXIS, causal)

    return sharded_seq_attention(mesh, body, q, k, v, attention_mask)
