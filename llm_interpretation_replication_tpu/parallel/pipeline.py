"""Pipeline parallelism: GPipe-style microbatched stages over a ``pipe`` axis.

Beyond-reference capability (SURVEY.md §2.7 marks PP absent upstream): layers
split into contiguous stages, one stage per device along the ``pipe`` mesh
axis; microbatches stream through the ring with ``ppermute`` handing each
stage's activations to the next, M + P - 1 ticks total (the usual GPipe
bubble).  TPU-first mechanics:

- ``shard_map(axis_names={'pipe'})`` makes only the pipe axis manual — data
  and tensor parallelism inside a stage stay GSPMD-automatic, so dp×pp×tp
  composes on one mesh without hand-written model collectives;
- the tick loop is a ``lax.scan`` (static trip count, one compiled program);
- stage handoff is a single ``ppermute`` per tick riding ICI neighbors;
- autodiff flows through scan+ppermute, so ``jax.grad`` of a pipelined loss
  just works (activations rematerialized by XLA as needed).

``pipeline_apply`` is the generic engine; ``pipeline_decoder_forward`` wires
it to models/decoder.py's stacked-layer params (embed and unembed run outside
the pipeline under plain GSPMD).
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .mesh import PIPE_AXIS


def split_stage_params(params, n_stages: int):
    """Reshape stacked-layer leaves ``[L, ...]`` → ``[P, L/P, ...]`` so the
    leading axis can shard over the pipe axis (stage s holds layers
    ``[s·L/P, (s+1)·L/P)``)."""

    def reshape(x):
        L = x.shape[0]
        if L % n_stages:
            raise ValueError(f"{L} layers not divisible into {n_stages} stages")
        return x.reshape(n_stages, L // n_stages, *x.shape[1:])

    return jax.tree.map(reshape, params)


def pipeline_apply(
    stage_fn: Callable,
    stage_params,
    xs,
    mesh: Mesh,
):
    """Run ``xs`` microbatches through ``stage_fn`` pipelined over ``pipe``.

    stage_fn: ``(params_for_one_stage, x) -> x`` — same pytree structure and
       shapes in and out, so activations can flow stage to stage.
    stage_params: pytree with leading stage axis ``P`` on every leaf
       (see :func:`split_stage_params`).
    xs: pytree of microbatched arrays ``[M, ...]`` (microbatch-major).
    Returns the same pytree, ``[M, ...]``, fully processed by all stages.
    """
    n_stages = mesh.shape[PIPE_AXIS]
    xs_leaves = jax.tree.leaves(xs)
    if not xs_leaves:
        return xs
    n_micro = xs_leaves[0].shape[0]
    if n_micro < 1:
        raise ValueError("need at least one microbatch")

    param_specs = jax.tree.map(lambda _: P(PIPE_AXIS), stage_params)
    x_specs = jax.tree.map(lambda _: P(), xs)
    ring = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def body(sp, xs):
        pid = lax.axis_index(PIPE_AXIS)
        my_params = jax.tree.map(lambda a: a[0], sp)  # local [1, ...] block
        take = lambda tree, t: jax.tree.map(lambda a: a[t], tree)

        buf = take(xs, 0)  # stage-resident activation (garbage until fed)
        outs = jax.tree.map(jnp.zeros_like, xs)

        def tick(carry, t):
            buf, outs = carry
            inject = take(xs, jnp.minimum(t, n_micro - 1))
            cur = jax.tree.map(
                lambda i, b: jnp.where(pid == 0, i, b), inject, buf
            )
            y = stage_fn(my_params, cur)
            nxt = jax.tree.map(
                lambda a: lax.ppermute(a, PIPE_AXIS, ring), y
            )
            out_t = t - (n_stages - 1)
            write = (out_t >= 0) & (pid == n_stages - 1)
            outs = jax.tree.map(
                lambda o, v: jnp.where(
                    write,
                    lax.dynamic_update_index_in_dim(
                        o, v, jnp.maximum(out_t, 0), 0
                    ),
                    o,
                ),
                outs, y,
            )
            return (nxt, outs), None

        (_, outs), _ = lax.scan(
            tick, (buf, outs), jnp.arange(n_micro + n_stages - 1)
        )
        # Only the last stage holds real outputs; replicate over the ring.
        outs = jax.tree.map(
            lambda o: lax.psum(jnp.where(pid == n_stages - 1, o, 0), PIPE_AXIS),
            outs,
        )
        return outs

    mapped = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(param_specs, x_specs),
        out_specs=x_specs,
        axis_names=frozenset({PIPE_AXIS}),
        check_vma=False,
    )
    # Partial-manual shard_map (axis_names ⊂ mesh axes) only lowers under a
    # jit trace — its eager impl path rejects the auto axes — so always wrap
    # in jit.  Inside a caller's jit (the production path; see
    # pipeline_decoder_forward's cached jit) this traces inline and caches
    # with the outer executable; only bare eager calls pay a per-call trace.
    return jax.jit(mapped)(stage_params, xs)


@functools.partial(
    jax.jit, static_argnames=("cfg", "mesh", "n_microbatches")
)
def pipeline_decoder_forward(
    params,
    cfg,
    token_ids,      # [B, S] int32
    attention_mask, # [B, S] int32
    mesh: Mesh,
    n_microbatches: int = 2,
):
    """models/decoder.py forward with the layer trunk pipelined over ``pipe``.

    Embedding and final-norm/unembed run outside the pipeline under plain
    GSPMD (they are a rounding error of the FLOPs); each stage recomputes the
    attention bias from the positions/mask it receives with its microbatch so
    nothing positional needs to be resident per stage.  Returns full logits
    ``[B, S, V]`` — numerically identical to ``decoder.forward``.
    """
    from ..models import decoder as dmod

    b = token_ids.shape[0]
    if b % n_microbatches:
        raise ValueError(f"batch {b} not divisible into {n_microbatches} microbatches")
    n_stages = mesh.shape[PIPE_AXIS]

    positions = jnp.cumsum(attention_mask, axis=-1) - 1
    positions = jnp.maximum(positions, 0)
    x = dmod._embed(cfg, params, token_ids, positions)

    def micro(a):  # [B, ...] -> [M, B/M, ...]
        return a.reshape(n_microbatches, b // n_microbatches, *a.shape[1:])

    xs = {
        "h": micro(x),
        "pos": micro(positions),
        "mask": micro(attention_mask),
    }
    stage_layers = split_stage_params(params["layers"], n_stages)

    def stage_fn(layers, mb):
        # decoder.run_layers is the same per-layer driver _trunk uses, so the
        # pipelined path inherits any attention-dispatch change automatically.
        h = dmod.run_layers(cfg, layers, mb["h"], mb["pos"], mb["mask"])
        return {"h": h, "pos": mb["pos"], "mask": mb["mask"]}

    outs = pipeline_apply(stage_fn, stage_layers, xs, mesh)
    h = outs["h"].reshape(b, *outs["h"].shape[2:])
    return dmod._unembed(cfg, params, h)
