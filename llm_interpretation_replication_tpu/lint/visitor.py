"""AST walking infrastructure for graftlint.

The linter runs TWO passes per file.  Pass 1 (:class:`ModuleGraph`)
builds a module-level call graph — every function/method under a dotted
qualname, module import aliases resolved (``from x import y as z``),
edges from call sites to same-module callees — and propagates
device-region membership interprocedurally from the roots (jit-decorated
functions, ``launch`` pipeline closures) down to bounded depth, seeding
each reached helper with the parameters that actually receive
traced-looking arguments at its device call sites.  Pass 2 is the
original :class:`ast.NodeVisitor` walk that keeps a stack of
:class:`FunctionInfo` frames (now graph-aware: a helper reachable from a
jit region carries ``in_jit``/``in_device_region`` and the seeded traced
params) and dispatches each node to every rule that declares a matching
``check_<nodetype>`` method.  Rules stay declarative — all the
JAX-specific context resolution (what counts as a jit decorator, which
arguments are static, what a "device region" is) lives here, once.

Terminology the rules share:

- **jit region** — the body of a function decorated with ``jax.jit`` /
  ``pjit`` (directly or through ``functools.partial``), where Python
  control flow runs at TRACE time and any host sync is a bug.
- **device region** — a jit region, or a ``launch``-named closure inside a
  hot-path module: the engine's pipeline contract (runtime/engine.
  _run_pipelined) is that ``launch`` only dispatches device programs and
  ``consume`` is the sanctioned host-fetch point, so host syncs inside
  ``launch`` stall the very pipeline the PR-2 work built.
- **hot path** — runtime/engine.py + runtime/batching.py + models/ + ops/:
  the per-batch code where one stray ``.item()`` multiplies by every batch
  of a 10k-row sweep.
"""

from __future__ import annotations

import ast
import re
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from .report import Finding, parse_suppressions, suppressed

#: interprocedural propagation bound: device-region membership flows at
#: most this many call hops from a jit/launch root.  Deep enough for the
#: real helper chains in this repo (engine → pool → gather helper is 2-3
#: hops); a bound keeps pathological/recursive graphs terminating and the
#: findings explainable (every message names its root and depth).
INTERPROCEDURAL_DEPTH = 4

#: Path fragments marking the per-batch hot path (see module docstring).
HOT_PATH_MARKERS = (
    "runtime/engine.py",
    "runtime/batching.py",
    "/models/",
    "models/decoder.py",
    "/ops/",
)

#: Path fragments where G05 (broad except) applies: every layer that sits
#: between a device error and runtime/faults.py's OOM/transient
#: classification.  serve/ is in scope from day one — the scheduler's
#: micro-batch launches are exactly where a swallowed RESOURCE_EXHAUSTED
#: would skip the split/re-queue ladder.  obs/ is in scope too: its spans
#: wrap the engine's launch/consume callbacks, so a swallowed error there
#: would hide a device failure inside the instrumentation (its deliberate
#: best-effort catches — memory-stats probes, profiler start/stop — carry
#: disable annotations).  Analysis/stats/viz modules keep their defensive
#: catches — nothing there handles device errors.
FAULT_PATH_MARKERS = (
    "/runtime/", "/ops/", "/models/", "/sweeps/", "/parallel/", "/native/",
    "/serve/", "/obs/", "/scoring/",
    "runtime/", "ops/", "models/", "sweeps/", "parallel/", "native/",
    "serve/", "obs/", "scoring/",
)


def dotted_name(node: ast.AST) -> str:
    """'jax.random.normal' for a Name/Attribute chain; '' otherwise."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


#: jax callables that return HOST structure (lists of leaves, treedefs,
#: shapes), not traced arrays — a local bound from them is host data.
_HOST_STRUCTURAL_RE = re.compile(
    r"jax\.(tree|tree_util|tree_structure|eval_shape)")

#: array attributes that are Python-static under trace — THE shared
#: definition (G01's cast scan, G02's host-static predicate, and G07's
#: operand walk all key on it; keep one copy so they cannot drift).
METADATA_ATTRS = ("shape", "size", "dtype", "ndim", "itemsize")


def host_static_value(value: ast.expr) -> bool:
    """True when ``value`` is Python-static under trace: metadata access
    (``x.shape[0]``, ``x.dtype``, ``x.ndim``) or an identity comparison
    (``x is None`` — tracers are never None, so the result is a host
    bool; the int8 layout flag ``quantized = cache.k_scale is not None``
    is the canonical case)."""
    for sub in ast.walk(value):
        if isinstance(sub, ast.Attribute) and sub.attr in METADATA_ATTRS:
            return True
    if isinstance(value, ast.Compare) and all(
            isinstance(op, (ast.Is, ast.IsNot)) for op in value.ops):
        return True
    return False


def _jit_decorator_info(dec: ast.expr,
                        resolve: Optional[Callable[[str], str]] = None
                        ) -> Optional[Tuple[Set[str], Set[int]]]:
    """(static_argnames, static_argnums) when ``dec`` is a jit decorator,
    else None.  Recognizes ``jax.jit``, ``jit``, ``pjit``, ``jax.pjit``,
    and ``functools.partial(jax.jit, static_argnames=(...))``; with a
    ``resolve`` callable (the module alias map), import aliases like
    ``from jax import jit as fastjit`` resolve too."""
    target = dec
    names: Set[str] = set()
    nums: Set[int] = set()
    resolve = resolve or (lambda n: n)
    if isinstance(dec, ast.Call):
        fn = resolve(dotted_name(dec.func))
        if fn.endswith("partial") and dec.args:
            target = dec.args[0]
            kws = dec.keywords
        else:
            target = dec.func
            kws = dec.keywords
        for kw in kws:
            if kw.arg == "static_argnames":
                names |= set(_const_strings(kw.value))
            elif kw.arg == "static_argnums":
                nums |= set(_const_ints(kw.value))
    name = resolve(dotted_name(target))
    if name in ("jax.jit", "jit", "pjit", "jax.pjit", "pjit.pjit",
                "jax.experimental.pjit.pjit"):
        return names, nums
    return None


def _const_strings(node: ast.expr) -> List[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [e.value for e in node.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)]
    return []


def _const_ints(node: ast.expr) -> List[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [e.value for e in node.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, int)]
    return []


class _FnNode:
    """One function in the module call graph (pass 1)."""

    __slots__ = ("node", "qualname", "name", "params", "static_params",
                 "is_jit", "is_launch", "is_method", "calls",
                 "traced_locals", "seeded", "reached_kind",
                 "reached_depth", "reached_via", "children")

    def __init__(self, node, qualname: str, is_method: bool):
        self.node = node
        self.qualname = qualname
        self.name = node.name
        args = node.args
        self.params: List[str] = [
            a.arg for a in (args.posonlyargs + args.args + args.kwonlyargs)]
        self.static_params: Set[str] = set()
        self.is_jit = False
        self.is_launch = False
        self.is_method = is_method
        #: [(callee _FnNode, ast.Call)] same-module edges
        self.calls: List[Tuple["_FnNode", ast.Call]] = []
        #: locals bound from jnp./jax./lax. expressions (host approximation)
        self.traced_locals: Set[str] = set()
        #: params that receive traced-looking args at device call sites
        self.seeded: Set[str] = set()
        self.reached_kind: Optional[str] = None   # "jit" | "launch"
        self.reached_depth: Optional[int] = None  # 0 for roots
        self.reached_via: Optional[str] = None    # root qualname
        #: directly-nested function name -> _FnNode (lexical resolution)
        self.children: Dict[str, "_FnNode"] = {}

    def effective_traced(self) -> Set[str]:
        """Names plausibly traced inside this function, for seeding its
        callees: a jit root contributes its non-static params, a reached
        helper its seeded params, a launch closure only jax-derived
        locals (its params are host batch metadata)."""
        if self.is_jit:
            return ((set(self.params) - self.static_params
                     - {"self", "cls"}) | self.traced_locals)
        if self.reached_kind == "jit":
            return set(self.seeded) | self.traced_locals
        return set(self.traced_locals)


class ModuleGraph:
    """Pass 1: module-level call graph + interprocedural device regions.

    Scope is deliberately ONE module: the linter never imports code, and
    the conventions it guards (engine helpers, decode reshapes, pipeline
    closures) live next to their callers.  Aliases are resolved for
    imports (``from jax import jit as fastjit``, ``import jax.numpy as
    jnp``) and for module-level function rebinds (``score = _score``);
    propagation is bounded by :data:`INTERPROCEDURAL_DEPTH` so recursive
    or cyclic call chains terminate with an explainable depth."""

    def __init__(self, tree: ast.Module, hot_module: bool,
                 max_depth: int = INTERPROCEDURAL_DEPTH):
        self.max_depth = max_depth
        self.functions: Dict[str, _FnNode] = {}
        self.aliases: Dict[str, str] = {}
        self._methods: Dict[str, Dict[str, _FnNode]] = {}
        self._module_fns: Dict[str, _FnNode] = {}
        self._collect_aliases(tree)
        self._collect_functions(tree, hot_module)
        self._collect_edges()
        self._propagate()

    # -- alias handling ---------------------------------------------------

    def _collect_aliases(self, tree: ast.Module) -> None:
        for node in tree.body:
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        self.aliases[a.asname] = a.name
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                for a in node.names:
                    if a.name == "*":
                        continue
                    full = f"{mod}.{a.name}" if mod else a.name
                    self.aliases[a.asname or a.name] = full

    def resolve(self, dotted: str) -> str:
        """Resolve the leading segment of a dotted name through the
        module's import aliases: ``jnp.where`` -> ``jax.numpy.where``."""
        if not dotted:
            return dotted
        head, _, rest = dotted.partition(".")
        full = self.aliases.get(head)
        if full is None:
            return dotted
        return f"{full}.{rest}" if rest else full

    # -- function + edge collection ---------------------------------------

    def _collect_functions(self, tree: ast.Module, hot_module: bool) -> None:
        graph = self

        class Collector(ast.NodeVisitor):
            def __init__(self):
                self.qual: List[str] = []
                self.fn_stack: List[_FnNode] = []
                self.class_depth = 0

            def _function(self, node):
                qualname = ".".join(self.qual + [node.name])
                is_method = (self.class_depth > 0
                             and bool(self.qual)
                             and not self.fn_stack)
                fn = _FnNode(node, qualname, is_method)
                for dec in node.decorator_list:
                    info = _jit_decorator_info(dec, graph.resolve)
                    if info is not None:
                        fn.is_jit = True
                        names, nums = info
                        fn.static_params |= names
                        for i in nums:
                            if 0 <= i < len(fn.params):
                                fn.static_params.add(fn.params[i])
                fn.is_launch = hot_module and node.name == "launch"
                graph.functions[qualname] = fn
                if self.fn_stack:
                    self.fn_stack[-1].children[node.name] = fn
                elif self.class_depth == 0:
                    graph._module_fns[node.name] = fn
                if is_method:
                    graph._methods.setdefault(
                        self.qual[-1], {})[node.name] = fn
                self.qual.append(node.name)
                self.fn_stack.append(fn)
                try:
                    for child in node.body:
                        self.visit(child)
                finally:
                    self.fn_stack.pop()
                    self.qual.pop()

            visit_FunctionDef = _function
            visit_AsyncFunctionDef = _function

            def visit_ClassDef(self, node):
                self.qual.append(node.name)
                self.class_depth += 1
                try:
                    for child in node.body:
                        self.visit(child)
                finally:
                    self.class_depth -= 1
                    self.qual.pop()

        Collector().visit(tree)
        # module-level function rebinds: `score = _score` aliases the graph
        for node in tree.body:
            if (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Name)
                    and node.value.id in self._module_fns):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self._module_fns.setdefault(
                            t.id, self._module_fns[node.value.id])

    def _owner_chain(self, fn: _FnNode) -> List[_FnNode]:
        """Lexically-enclosing function nodes, innermost first."""
        chain = []
        parts = fn.qualname.split(".")
        for i in range(len(parts) - 1, 0, -1):
            parent = self.functions.get(".".join(parts[:i]))
            if parent is not None:
                chain.append(parent)
        return chain

    def _resolve_call(self, fn: _FnNode, call: ast.Call
                      ) -> Optional[_FnNode]:
        func = call.func
        if isinstance(func, ast.Name):
            name = func.id
            # lexical scope chain: own nested defs, enclosing functions'
            # nested defs, then module level
            if name in fn.children:
                return fn.children[name]
            for parent in self._owner_chain(fn):
                if name in parent.children:
                    return parent.children[name]
            target = self._module_fns.get(name)
            if target is not None:
                return target
            # import alias of a same-module name never resolves (the
            # linter is per-file); a foreign alias is simply not ours
            return None
        if isinstance(func, ast.Attribute):
            dotted = dotted_name(func)
            parts = dotted.split(".")
            if len(parts) == 2 and parts[0] in ("self", "cls"):
                # method call: the enclosing class is the first qualname
                # segment that owns a method table
                for seg in fn.qualname.split("."):
                    table = self._methods.get(seg)
                    if table and parts[1] in table:
                        return table[parts[1]]
        return None

    def _collect_edges(self) -> None:
        for fn in self.functions.values():
            for stmt in fn.node.body:
                for sub in self._iter_body_nodes(stmt):
                    if isinstance(sub, ast.Call):
                        callee = self._resolve_call(fn, sub)
                        if callee is not None and callee is not fn:
                            fn.calls.append((callee, sub))
                    elif isinstance(sub, (ast.Assign, ast.AugAssign)):
                        self._note_traced(fn, sub)

    @staticmethod
    def _iter_body_nodes(stmt):
        """Walk a statement without descending into nested function /
        class bodies (those belong to their own graph nodes)."""
        stack = [stmt]
        while stack:
            node = stack.pop()
            yield node
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef,
                                      ast.Lambda, ast.ClassDef)):
                    continue
                stack.append(child)

    def _note_traced(self, fn: _FnNode, stmt) -> None:
        """Host-side approximation of the visitor's traced-locals rule:
        a local assigned from a jnp./jax./lax. expression is traced,
        unless the expression is metadata access (shape/dtype/...)."""
        value = stmt.value
        targets = (stmt.targets if isinstance(stmt, ast.Assign)
                   else [stmt.target])
        if host_static_value(value):
            return
        traced = False
        for sub in ast.walk(value):
            if isinstance(sub, ast.Call):
                callee = self.resolve(dotted_name(sub.func))
                if (callee.split(".", 1)[0] in ("jnp", "jax", "lax")
                        and not _HOST_STRUCTURAL_RE.match(callee)):
                    traced = True
                    break
        if traced:
            for t in targets:
                for name_node in ast.walk(t):
                    if isinstance(name_node, ast.Name):
                        fn.traced_locals.add(name_node.id)

    # -- propagation -------------------------------------------------------

    def _arg_is_traced(self, caller: _FnNode, arg: ast.expr,
                       caller_traced: Set[str]) -> bool:
        if host_static_value(arg):
            return False
        for sub in ast.walk(arg):
            if isinstance(sub, ast.Name) and sub.id in caller_traced:
                return True
            if isinstance(sub, ast.Call):
                fn = self.resolve(dotted_name(sub.func))
                if (fn.split(".", 1)[0] in ("jnp", "jax", "lax")
                        and not _HOST_STRUCTURAL_RE.match(fn)):
                    return True
        return False

    def _seed_callee(self, caller: _FnNode, callee: _FnNode,
                     call: ast.Call) -> bool:
        """Mark callee params receiving traced-looking args; True when
        the seed set grew."""
        caller_traced = caller.effective_traced()
        params = callee.params
        offset = 1 if params[:1] in (["self"], ["cls"]) else 0
        grew = False
        for i, arg in enumerate(call.args):
            if isinstance(arg, ast.Starred):
                continue
            idx = i + offset
            if idx < len(params) and self._arg_is_traced(
                    caller, arg, caller_traced):
                if params[idx] not in callee.seeded:
                    callee.seeded.add(params[idx])
                    grew = True
        for kw in call.keywords:
            if kw.arg and kw.arg in params and self._arg_is_traced(
                    caller, kw.value, caller_traced):
                if kw.arg not in callee.seeded:
                    callee.seeded.add(kw.arg)
                    grew = True
        return grew

    def _propagate(self) -> None:
        for fn in self.functions.values():
            if fn.is_jit or fn.is_launch:
                fn.reached_kind = "jit" if fn.is_jit else "launch"
                fn.reached_depth = 0
                fn.reached_via = fn.qualname
        # fixpoint over (reach, seeds): both only grow and are bounded,
        # so this terminates; the depth bound caps the frontier
        changed = True
        while changed:
            changed = False
            for fn in self.functions.values():
                if fn.reached_kind is None:
                    continue
                if fn.reached_depth >= self.max_depth:
                    continue
                for callee, call in fn.calls:
                    kind = fn.reached_kind
                    depth = fn.reached_depth + 1
                    via = fn.reached_via or fn.qualname
                    upgrade = (
                        callee.reached_kind is None
                        or (kind == "jit"
                            and callee.reached_kind == "launch")
                        or (kind == callee.reached_kind
                            and depth < (callee.reached_depth or 0)))
                    if upgrade and not callee.is_jit:
                        callee.reached_kind = kind
                        callee.reached_depth = depth
                        callee.reached_via = via
                        changed = True
                    if kind == "jit" and self._seed_callee(
                            fn, callee, call):
                        changed = True

    def lookup(self, qualname: str) -> Optional[_FnNode]:
        return self.functions.get(qualname)


class FunctionInfo:
    """One frame of the visitor's function stack."""

    def __init__(self, node, parent: Optional["FunctionInfo"],
                 hot_module: bool,
                 graph_node: Optional[_FnNode] = None,
                 resolve: Optional[Callable[[str], str]] = None):
        self.node = node
        self.parent = parent
        self.name = getattr(node, "name", "<lambda>")
        args = node.args
        self.params: List[str] = [
            a.arg for a in (args.posonlyargs + args.args + args.kwonlyargs)]
        self.static_params: Set[str] = set()
        self.is_jit = False
        for dec in getattr(node, "decorator_list", ()):
            info = _jit_decorator_info(dec, resolve)
            if info is not None:
                self.is_jit = True
                names, nums = info
                self.static_params |= names
                for i in nums:
                    if 0 <= i < len(self.params):
                        self.static_params.add(self.params[i])
        # the engine pipeline contract: `launch` closures dispatch device
        # programs and must not fetch (see module docstring)
        self.is_launch = hot_module and self.name == "launch"
        self.in_jit = self.is_jit or (parent is not None and parent.in_jit)
        self.in_device_region = (
            self.is_jit or self.is_launch
            or (parent is not None and parent.in_device_region))
        #: interprocedural reach: (kind, root qualname, depth) when the
        #: module call graph proved this function is reachable from a
        #: device region — the PR-15 upgrade over the per-function walk
        self.device_path: Optional[Tuple[str, str, int]] = None
        #: params that receive traced args at device call sites (only
        #: meaningful when seeded_only)
        self.seeded: Set[str] = set()
        #: True for interprocedurally-reached helpers: traced_names()
        #: then returns ONLY the seeded params + jax-derived locals, so
        #: a helper with host-only params never floods G01/G02
        self.seeded_only = False
        if (graph_node is not None and graph_node.reached_kind is not None
                and not self.is_jit and not self.is_launch):
            self.device_path = (graph_node.reached_kind,
                                graph_node.reached_via or "?",
                                graph_node.reached_depth or 0)
            self.in_device_region = True
            if graph_node.reached_kind == "jit":
                self.in_jit = True
                self.seeded_only = True
                self.seeded = set(graph_node.seeded)
        #: locals assigned from jnp./jax./lax. expressions — treated as
        #: traced values by G02's control-flow rule
        self.traced_locals: Set[str] = set()
        self.loop_depth = 0

    def traced_names(self) -> Set[str]:
        """Names holding (potentially) traced arrays in this jit frame."""
        if self.seeded_only:
            return set(self.seeded) | self.traced_locals
        return (set(self.params) - self.static_params
                - {"self", "cls"}) | self.traced_locals

    def region_desc(self) -> str:
        """Human description of why this frame is a device region — the
        interprocedural path when the call graph supplied one."""
        if self.is_jit:
            return "a jit region"
        if self.is_launch:
            return "a launch pipeline closure"
        if self.device_path is not None:
            kind, via, depth = self.device_path
            root = "jit region" if kind == "jit" else "launch closure"
            return (f"a helper reachable from {root} '{via}' "
                    f"({depth} call hop{'s' if depth != 1 else ''})")
        if self.parent is not None:
            return self.parent.region_desc()
        return "a device region"


class FileContext:
    """Per-file state shared by every rule."""

    def __init__(self, path: str, text: str):
        self.path = path.replace("\\", "/")
        self.text = text
        self.lines = text.splitlines()
        self.suppressions = parse_suppressions(self.lines)
        self.hot_module = any(m in self.path for m in HOT_PATH_MARKERS)
        self.fault_module = any(m in self.path for m in FAULT_PATH_MARKERS)

    def source_line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""


class LintVisitor(ast.NodeVisitor):
    """Drives the rules over one parsed file.

    Rules implement any of ``check_call / check_if / check_while /
    check_ifexp / check_excepthandler / check_functiondef(node, ctx,
    visitor)`` and append to ``visitor.findings`` via :meth:`report`.
    Inline ``graftlint: disable=`` suppressions are applied here so no
    rule needs to know about them.
    """

    def __init__(self, ctx: FileContext, rules: Sequence,
                 graph: Optional[ModuleGraph] = None):
        self.ctx = ctx
        self.rules = rules
        self.graph = graph
        self.findings: List[Finding] = []
        self.stack: List[FunctionInfo] = []
        self._qual: List[str] = []

    # -- rule-facing API --------------------------------------------------

    @property
    def function(self) -> Optional[FunctionInfo]:
        return self.stack[-1] if self.stack else None

    def report(self, rule: str, node: ast.AST, message: str) -> None:
        line = getattr(node, "lineno", 1)
        finding = Finding(
            rule=rule, path=self.ctx.path, line=line,
            col=getattr(node, "col_offset", 0) + 1, message=message,
            code=self.ctx.source_line(line),
        )
        if not suppressed(finding, self.ctx.suppressions):
            self.findings.append(finding)

    # -- traversal --------------------------------------------------------

    def _dispatch(self, hook: str, node: ast.AST) -> None:
        for rule in self.rules:
            fn = getattr(rule, hook, None)
            if fn is not None:
                fn(node, self.ctx, self)

    def _visit_function(self, node) -> None:
        name = getattr(node, "name", None)
        graph_node = None
        resolve = None
        if self.graph is not None:
            resolve = self.graph.resolve
            if name is not None:
                graph_node = self.graph.lookup(
                    ".".join(self._qual + [name]))
        frame = FunctionInfo(node, self.function, self.ctx.hot_module,
                             graph_node=graph_node, resolve=resolve)
        self.stack.append(frame)
        if name is not None:
            self._qual.append(name)
        self._dispatch("check_functiondef", node)
        decorators = set(map(id, getattr(node, "decorator_list", ())))
        try:
            for child in ast.iter_child_nodes(node):
                if id(child) in decorators:
                    continue  # decorators belong to the ENCLOSING frame
                self.visit(child)
        finally:
            self.stack.pop()
            if name is not None:
                self._qual.pop()

    visit_FunctionDef = _visit_function
    visit_AsyncFunctionDef = _visit_function
    visit_Lambda = _visit_function

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._qual.append(node.name)
        try:
            self.generic_visit(node)
        finally:
            self._qual.pop()

    def visit_Assign(self, node: ast.Assign) -> None:
        self._note_traced_assignment(node.targets, node.value)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._note_traced_assignment([node.target], node.value)
        self.generic_visit(node)

    def _note_traced_assignment(self, targets, value) -> None:
        """Track locals bound from jnp./jax./lax. expressions inside jit or
        launch frames, so the rules can tell traced/device values from host
        ones."""
        frame = self.function
        if frame is None or not (frame.in_jit or frame.in_device_region):
            return
        if host_static_value(value):
            return
        traced = False
        for sub in ast.walk(value):
            if isinstance(sub, ast.Call):
                fn = dotted_name(sub.func)
                if self.graph is not None:
                    fn = self.graph.resolve(fn)
                if (fn.split(".", 1)[0] in ("jnp", "jax", "lax")
                        and not _HOST_STRUCTURAL_RE.match(fn)):
                    traced = True
                    break
            elif isinstance(sub, ast.Name) and sub.id in frame.traced_names():
                traced = True
                break
        if traced:
            for t in targets:
                for name_node in ast.walk(t):
                    if isinstance(name_node, ast.Name):
                        frame.traced_locals.add(name_node.id)

    def _visit_loop(self, node) -> None:
        frame = self.function
        if frame is not None:
            frame.loop_depth += 1
        if isinstance(node, ast.While):
            self._dispatch("check_while", node)
        try:
            self.generic_visit(node)
        finally:
            if frame is not None:
                frame.loop_depth -= 1

    visit_For = _visit_loop
    visit_AsyncFor = _visit_loop
    visit_While = _visit_loop

    def visit_Call(self, node: ast.Call) -> None:
        self._dispatch("check_call", node)
        self.generic_visit(node)

    def visit_If(self, node: ast.If) -> None:
        self._dispatch("check_if", node)
        self.generic_visit(node)

    def visit_IfExp(self, node: ast.IfExp) -> None:
        self._dispatch("check_ifexp", node)
        self.generic_visit(node)

    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        self._dispatch("check_excepthandler", node)
        self.generic_visit(node)


def lint_source(path: str, text: str, rules: Sequence,
                interprocedural: bool = True) -> List[Finding]:
    """Run ``rules`` over one file's source; syntax errors become a single
    G00 finding instead of crashing the whole run (the linter gates a repo
    that must stay importable anyway — the test suite catches real syntax
    rot; the G00 row just keeps the lint report honest).

    ``interprocedural=False`` reverts to the PR-3 per-function engine
    (no call graph, no device-region propagation) — kept so the fixture
    tests can pin that the interprocedural layer catches what the old
    engine provably missed."""
    ctx = FileContext(path, text)
    try:
        tree = ast.parse(text)
    except SyntaxError as err:
        return [Finding("G00", ctx.path, err.lineno or 1,
                        (err.offset or 0) + 1,
                        f"syntax error: {err.msg}",
                        ctx.source_line(err.lineno or 1))]
    graph = ModuleGraph(tree, ctx.hot_module) if interprocedural else None
    visitor = LintVisitor(ctx, rules, graph=graph)
    for rule in rules:
        fn = getattr(rule, "check_module", None)
        if fn is not None:
            fn(tree, ctx, visitor)
    visitor.visit(tree)
    return visitor.findings
