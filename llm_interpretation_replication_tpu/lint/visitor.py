"""AST walking infrastructure for graftlint.

The linter is a single :class:`ast.NodeVisitor` pass per file that keeps a
stack of :class:`FunctionInfo` frames (so rules always know the enclosing
function, whether it is jit-compiled, and which of its parameters are
static) and dispatches each node to every rule that declares a matching
``check_<nodetype>`` method.  Rules stay declarative — all the JAX-specific
context resolution (what counts as a jit decorator, which arguments are
static, what a "device region" is) lives here, once.

Terminology the rules share:

- **jit region** — the body of a function decorated with ``jax.jit`` /
  ``pjit`` (directly or through ``functools.partial``), where Python
  control flow runs at TRACE time and any host sync is a bug.
- **device region** — a jit region, or a ``launch``-named closure inside a
  hot-path module: the engine's pipeline contract (runtime/engine.
  _run_pipelined) is that ``launch`` only dispatches device programs and
  ``consume`` is the sanctioned host-fetch point, so host syncs inside
  ``launch`` stall the very pipeline the PR-2 work built.
- **hot path** — runtime/engine.py + runtime/batching.py + models/ + ops/:
  the per-batch code where one stray ``.item()`` multiplies by every batch
  of a 10k-row sweep.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Sequence, Set, Tuple

from .report import Finding, parse_suppressions, suppressed

#: Path fragments marking the per-batch hot path (see module docstring).
HOT_PATH_MARKERS = (
    "runtime/engine.py",
    "runtime/batching.py",
    "/models/",
    "models/decoder.py",
    "/ops/",
)

#: Path fragments where G05 (broad except) applies: every layer that sits
#: between a device error and runtime/faults.py's OOM/transient
#: classification.  serve/ is in scope from day one — the scheduler's
#: micro-batch launches are exactly where a swallowed RESOURCE_EXHAUSTED
#: would skip the split/re-queue ladder.  obs/ is in scope too: its spans
#: wrap the engine's launch/consume callbacks, so a swallowed error there
#: would hide a device failure inside the instrumentation (its deliberate
#: best-effort catches — memory-stats probes, profiler start/stop — carry
#: disable annotations).  Analysis/stats/viz modules keep their defensive
#: catches — nothing there handles device errors.
FAULT_PATH_MARKERS = (
    "/runtime/", "/ops/", "/models/", "/sweeps/", "/parallel/", "/native/",
    "/serve/", "/obs/", "/scoring/",
    "runtime/", "ops/", "models/", "sweeps/", "parallel/", "native/",
    "serve/", "obs/", "scoring/",
)


def dotted_name(node: ast.AST) -> str:
    """'jax.random.normal' for a Name/Attribute chain; '' otherwise."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _jit_decorator_info(dec: ast.expr) -> Optional[Tuple[Set[str], Set[int]]]:
    """(static_argnames, static_argnums) when ``dec`` is a jit decorator,
    else None.  Recognizes ``jax.jit``, ``jit``, ``pjit``, ``jax.pjit``,
    and ``functools.partial(jax.jit, static_argnames=(...))``."""
    target = dec
    names: Set[str] = set()
    nums: Set[int] = set()
    if isinstance(dec, ast.Call):
        fn = dotted_name(dec.func)
        if fn.endswith("partial") and dec.args:
            target = dec.args[0]
            kws = dec.keywords
        else:
            target = dec.func
            kws = dec.keywords
        for kw in kws:
            if kw.arg == "static_argnames":
                names |= set(_const_strings(kw.value))
            elif kw.arg == "static_argnums":
                nums |= set(_const_ints(kw.value))
    name = dotted_name(target)
    if name in ("jax.jit", "jit", "pjit", "jax.pjit", "pjit.pjit"):
        return names, nums
    return None


def _const_strings(node: ast.expr) -> List[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [e.value for e in node.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)]
    return []


def _const_ints(node: ast.expr) -> List[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [e.value for e in node.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, int)]
    return []


class FunctionInfo:
    """One frame of the visitor's function stack."""

    def __init__(self, node, parent: Optional["FunctionInfo"],
                 hot_module: bool):
        self.node = node
        self.parent = parent
        self.name = getattr(node, "name", "<lambda>")
        args = node.args
        self.params: List[str] = [
            a.arg for a in (args.posonlyargs + args.args + args.kwonlyargs)]
        self.static_params: Set[str] = set()
        self.is_jit = False
        for dec in getattr(node, "decorator_list", ()):
            info = _jit_decorator_info(dec)
            if info is not None:
                self.is_jit = True
                names, nums = info
                self.static_params |= names
                for i in nums:
                    if 0 <= i < len(self.params):
                        self.static_params.add(self.params[i])
        # the engine pipeline contract: `launch` closures dispatch device
        # programs and must not fetch (see module docstring)
        self.is_launch = hot_module and self.name == "launch"
        self.in_jit = self.is_jit or (parent is not None and parent.in_jit)
        self.in_device_region = (
            self.is_jit or self.is_launch
            or (parent is not None and parent.in_device_region))
        #: locals assigned from jnp./jax./lax. expressions — treated as
        #: traced values by G02's control-flow rule
        self.traced_locals: Set[str] = set()
        self.loop_depth = 0

    def traced_names(self) -> Set[str]:
        """Names holding (potentially) traced arrays in this jit frame."""
        return (set(self.params) - self.static_params
                - {"self", "cls"}) | self.traced_locals


class FileContext:
    """Per-file state shared by every rule."""

    def __init__(self, path: str, text: str):
        self.path = path.replace("\\", "/")
        self.text = text
        self.lines = text.splitlines()
        self.suppressions = parse_suppressions(self.lines)
        self.hot_module = any(m in self.path for m in HOT_PATH_MARKERS)
        self.fault_module = any(m in self.path for m in FAULT_PATH_MARKERS)

    def source_line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""


class LintVisitor(ast.NodeVisitor):
    """Drives the rules over one parsed file.

    Rules implement any of ``check_call / check_if / check_while /
    check_ifexp / check_excepthandler / check_functiondef(node, ctx,
    visitor)`` and append to ``visitor.findings`` via :meth:`report`.
    Inline ``graftlint: disable=`` suppressions are applied here so no
    rule needs to know about them.
    """

    def __init__(self, ctx: FileContext, rules: Sequence):
        self.ctx = ctx
        self.rules = rules
        self.findings: List[Finding] = []
        self.stack: List[FunctionInfo] = []

    # -- rule-facing API --------------------------------------------------

    @property
    def function(self) -> Optional[FunctionInfo]:
        return self.stack[-1] if self.stack else None

    def report(self, rule: str, node: ast.AST, message: str) -> None:
        line = getattr(node, "lineno", 1)
        finding = Finding(
            rule=rule, path=self.ctx.path, line=line,
            col=getattr(node, "col_offset", 0) + 1, message=message,
            code=self.ctx.source_line(line),
        )
        if not suppressed(finding, self.ctx.suppressions):
            self.findings.append(finding)

    # -- traversal --------------------------------------------------------

    def _dispatch(self, hook: str, node: ast.AST) -> None:
        for rule in self.rules:
            fn = getattr(rule, hook, None)
            if fn is not None:
                fn(node, self.ctx, self)

    def _visit_function(self, node) -> None:
        frame = FunctionInfo(node, self.function, self.ctx.hot_module)
        self.stack.append(frame)
        self._dispatch("check_functiondef", node)
        decorators = set(map(id, getattr(node, "decorator_list", ())))
        try:
            for child in ast.iter_child_nodes(node):
                if id(child) in decorators:
                    continue  # decorators belong to the ENCLOSING frame
                self.visit(child)
        finally:
            self.stack.pop()

    visit_FunctionDef = _visit_function
    visit_AsyncFunctionDef = _visit_function
    visit_Lambda = _visit_function

    def visit_Assign(self, node: ast.Assign) -> None:
        self._note_traced_assignment(node.targets, node.value)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._note_traced_assignment([node.target], node.value)
        self.generic_visit(node)

    def _note_traced_assignment(self, targets, value) -> None:
        """Track locals bound from jnp./jax./lax. expressions inside jit or
        launch frames, so the rules can tell traced/device values from host
        ones."""
        frame = self.function
        if frame is None or not (frame.in_jit or frame.in_device_region):
            return
        # metadata access (`x.shape[0]`, `x.dtype`, `x.ndim`) is Python-
        # static under trace — a local bound from it is a host int, not a
        # traced value, even when `x` itself is traced
        for sub in ast.walk(value):
            if isinstance(sub, ast.Attribute) and sub.attr in (
                    "shape", "ndim", "dtype", "size"):
                return
        traced = False
        for sub in ast.walk(value):
            if isinstance(sub, ast.Call):
                fn = dotted_name(sub.func)
                if fn.split(".", 1)[0] in ("jnp", "jax", "lax"):
                    traced = True
                    break
            elif isinstance(sub, ast.Name) and sub.id in frame.traced_names():
                traced = True
                break
        if traced:
            for t in targets:
                for name_node in ast.walk(t):
                    if isinstance(name_node, ast.Name):
                        frame.traced_locals.add(name_node.id)

    def _visit_loop(self, node) -> None:
        frame = self.function
        if frame is not None:
            frame.loop_depth += 1
        if isinstance(node, ast.While):
            self._dispatch("check_while", node)
        try:
            self.generic_visit(node)
        finally:
            if frame is not None:
                frame.loop_depth -= 1

    visit_For = _visit_loop
    visit_AsyncFor = _visit_loop
    visit_While = _visit_loop

    def visit_Call(self, node: ast.Call) -> None:
        self._dispatch("check_call", node)
        self.generic_visit(node)

    def visit_If(self, node: ast.If) -> None:
        self._dispatch("check_if", node)
        self.generic_visit(node)

    def visit_IfExp(self, node: ast.IfExp) -> None:
        self._dispatch("check_ifexp", node)
        self.generic_visit(node)

    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        self._dispatch("check_excepthandler", node)
        self.generic_visit(node)


def lint_source(path: str, text: str, rules: Sequence) -> List[Finding]:
    """Run ``rules`` over one file's source; syntax errors become a single
    G00 finding instead of crashing the whole run (the linter gates a repo
    that must stay importable anyway — the test suite catches real syntax
    rot; the G00 row just keeps the lint report honest)."""
    ctx = FileContext(path, text)
    try:
        tree = ast.parse(text)
    except SyntaxError as err:
        return [Finding("G00", ctx.path, err.lineno or 1,
                        (err.offset or 0) + 1,
                        f"syntax error: {err.msg}",
                        ctx.source_line(err.lineno or 1))]
    visitor = LintVisitor(ctx, rules)
    for rule in rules:
        fn = getattr(rule, "check_module", None)
        if fn is not None:
            fn(tree, ctx, visitor)
    visitor.visit(tree)
    return visitor.findings
