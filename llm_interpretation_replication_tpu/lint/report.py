"""Findings, suppression comments, and output formatting for graftlint.

A :class:`Finding` is one rule violation anchored to a source line.  Its
identity for baseline matching is the :meth:`Finding.fingerprint` —
``(rule, path, normalized code line)`` — NOT the line number: grandfathered
findings must survive unrelated edits above them, and a baseline keyed on
line numbers would go stale on every refactor.  The line number is kept for
display and as a tiebreaker when the same code text appears twice.

Inline suppressions use the reference-linter idiom::

    except Exception as err:  # graftlint: disable=G05 sweep must outlive one bad row

The comment may sit on the flagged line or the line directly above it, and
carries a free-text reason after the rule list (comma-separated rule ids).
A suppression WITHOUT a reason still works — the linter is a gate, not a
bureaucracy — but the repo convention (README "Static analysis") is to
always say why.
"""

from __future__ import annotations

import dataclasses
import json
import re
from typing import Dict, List, Optional, Sequence, Tuple

_DISABLE_RE = re.compile(r"#\s*graftlint:\s*disable=([A-Z0-9,\s]+?)(?:\s+(.*))?$")


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str           # "G01".."G05"
    path: str           # repo-relative posix path
    line: int           # 1-indexed source line
    col: int
    message: str        # human explanation of this instance
    code: str           # stripped source line the finding anchors to

    @property
    def fingerprint(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, normalize_code(self.code))

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"

    def to_json(self) -> Dict:
        return {
            "rule": self.rule, "path": self.path, "line": self.line,
            "col": self.col, "message": self.message, "code": self.code,
        }


def normalize_code(code: str) -> str:
    """Whitespace-insensitive form of a source line (baseline matching)."""
    return " ".join(code.split())


def parse_suppressions(lines: Sequence[str]) -> Dict[int, List[str]]:
    """{1-indexed line: [rule ids]} for every ``graftlint: disable=`` comment.

    Both comment styles work, each scoped to exactly ONE code line — a
    standalone comment covers the line below it, a trailing comment covers
    its own line (and must NOT bleed onto the next, or a same-line
    suppression would silently exempt an unrelated following statement)::

        # graftlint: disable=G05 reason
        except Exception:          # <- suppressed

        except Exception:  # graftlint: disable=G05 reason   <- suppressed
    """
    out: Dict[int, List[str]] = {}
    for i, text in enumerate(lines, start=1):
        m = _DISABLE_RE.search(text)
        if not m:
            continue
        rules = [r.strip() for r in m.group(1).split(",") if r.strip()]
        standalone = text[: m.start()].strip() in ("", "#")
        target = i + 1 if standalone else i
        out.setdefault(target, []).extend(rules)
    return out


def suppressed(finding: Finding, suppressions: Dict[int, List[str]]) -> bool:
    return finding.rule in suppressions.get(finding.line, ())


def format_report(findings: Sequence[Finding],
                  stale: Sequence[Dict] = (),
                  baselined: int = 0,
                  fmt: str = "text",
                  rot: Sequence[Dict] = ()) -> str:
    """Render the lint result.  ``findings`` are the NEW (non-baselined)
    violations; ``stale`` are baseline entries that no longer match any
    finding in this run's target set (fixed code whose grandfather clause
    should be deleted); ``rot`` are entries whose fingerprint matches no
    line of their own file on disk (scope-independent baseline rot)."""
    if fmt == "json":
        return json.dumps({
            "findings": [f.to_json() for f in findings],
            "stale_baseline": list(stale),
            "rotten_baseline": list(rot),
            "baselined": baselined,
        }, indent=2)
    lines: List[str] = [f.format() for f in findings]
    for entry in stale:
        lines.append(
            f"# stale baseline entry ({entry.get('rule')} "
            f"{entry.get('path')}): no longer matches — delete it from the "
            f"baseline ({normalize_code(entry.get('code', ''))!r})")
    for entry in rot:
        lines.append(
            f"# rotten baseline entry ({entry.get('rule')} "
            f"{entry.get('path')}): fingerprint matches no line of that "
            f"file on disk — the exempted code is gone; delete the entry "
            f"({normalize_code(entry.get('code', ''))!r})")
    summary = (f"{len(findings)} new finding(s)"
               + (f", {baselined} baselined" if baselined else "")
               + (f", {len(stale)} stale baseline entr"
                  f"{'y' if len(stale) == 1 else 'ies'}" if stale else "")
               + (f", {len(rot)} rotten baseline entr"
                  f"{'y' if len(rot) == 1 else 'ies'}" if rot else ""))
    lines.append(summary if (findings or stale or rot or baselined)
                 else "clean: no findings")
    return "\n".join(lines)


def sort_findings(findings: List[Finding]) -> List[Finding]:
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule))
