"""graftlint rules G01-G08: the TPU-hostile patterns this repo bans.

Each rule is a small class plugging into :class:`..lint.visitor.LintVisitor`
hooks.  The catalogue (also printed by ``lint --explain``):

- **G01 host-sync** — implicit device→host syncs inside device regions:
  ``.item()``, ``float()/int()/bool()`` on arrays, ``np.asarray``/
  ``np.array``/``jax.device_get`` inside jit-compiled functions or the
  engine's ``launch`` pipeline closures.  One stray sync serializes the
  async dispatch queue the engine's pipelining depends on (the measured
  1→2 pipeline-depth gap was 67.6 → 91.5 prompts/s); inside a jit trace it
  is a ConcretizationError waiting for a shape change.  The sanctioned
  fetch points are the pipeline's ``consume`` callbacks — runtime/strict.py
  arms the same contract at runtime via ``jax.transfer_guard``.
- **G02 traced-control-flow** — Python ``if``/``while`` on traced values
  inside jit regions.  Works on today's shapes, then either crashes
  (ConcretizationTypeError) or — worse — silently retraces per value and
  recompiles per batch.  Static knobs belong in ``static_argnames``;
  value-dependent branches belong in ``lax.cond``/``jnp.where``.
- **G03 key-reuse** — the same PRNG key consumed by two ``jax.random``
  draws without a ``split``: the draws are then CORRELATED (identical for
  the same shape/dtype), which silently destroys initialization scaling
  and any sampled statistic downstream.  ``split``/``fold_in`` are
  derivations, not draws, and don't count as consumption.
- **G04 jit-boundary** — jit-boundary hygiene: mutable default arguments
  on jit'd functions (one shared default across every trace), jit over
  bound methods / ``self`` captures (cache keyed per instance — exactly
  the leak the ``GenerationPlan`` cache keys were built to avoid), and
  bare ``jax.jit`` over shape-like parameters (``*_len``/``*_size``/...)
  that must be static or every distinct value recompiles.
- **G05 broad-except** — ``except Exception``/bare ``except`` that
  SWALLOWS (no re-raise) in the fault-handling layers (runtime/, ops/,
  models/, sweeps/, parallel/, native/): a swallowed RESOURCE_EXHAUSTED
  never reaches runtime/faults.py's OOM classification, so the batch
  back-off ladder can't engage and the sweep records a silently degraded
  operating point.  Handlers that re-raise (``raise`` / ``raise err``)
  pass; intentional keep-alive catches take an inline
  ``# graftlint: disable=G05 <reason>``.
- **G06 telemetry-discipline** — metric names passed to
  ``record_counter``/``record_sample``/``record_hist`` must be
  statically enumerable: string literals (or module constants /
  forwarded chokepoint-helper params), with labels spelled in the
  ``name|k=v,k2=v2`` convention using LITERAL label keys.  A
  dynamically concatenated name mints an unbounded metric family the
  README counter table cannot document, bench-diff cannot align, and
  the Prometheus exporter cannot re-split into one labeled family.
  Fault *kinds* are held to the same discipline at the registry level:
  a literal first argument to ``record_fault`` must be a member of
  ``utils.telemetry.FAULT_KINDS`` — a typo'd kind forks an event
  stream no flight-recorder trigger or listener ever matches.
- **G07 cache-scale-awareness** — ``reshape``/``gather``/``concat``
  (and friends) applied directly to ``KVCache.k``/``.v`` outside the
  ops helpers and ``models/decoder.cache_kv_map``: with int8 KV the
  per-head ``k_scale``/``v_scale`` must ride every storage re-layout,
  or dequantization silently reads misaligned scales — the exact bug
  class the PR-5 int8 scale-plumbing audit chased by hand.
- **G08 span-hygiene** — tracer spans must be context-managed (``with
  obs.span(...)``; cross-thread timing uses ``add_span``) and every
  ``phase=`` tag must be a literal from the canonical phase table
  (``obs/tracer.KNOWN_PHASES``): a leaked span corrupts the per-thread
  SELF-time stack, and a typo'd phase forks a row outside the
  documented partition.

Rules G09-G11 (guarded-by, lock-order, blocking-under-lock) are NOT in
this module: they need the whole tree at once — thread roots in serve/
reach state in utils/ — so they live in :mod:`.threads` as a third
analysis layer over the same Finding/suppression/baseline machinery.
Their catalogue rows are in :data:`RULES` below so ``lint --explain``
covers them.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from ..obs.tracer import KNOWN_PHASES
from ..utils.telemetry import FAULT_KINDS
from .visitor import METADATA_ATTRS, FileContext, LintVisitor, dotted_name

#: rule id -> (title, one-line summary) — the CLI's --explain table.
RULES: Dict[str, Tuple[str, str]] = {
    "G00": ("syntax-error", "file failed to parse; nothing else was checked"),
    "G01": ("host-sync", "implicit device->host sync inside a device region "
                         "(.item(), float()/bool(), np.asarray in jit/launch)"),
    "G02": ("traced-control-flow", "Python if/while on a traced value inside "
                                   "a jit region (retrace/recompile per value)"),
    "G03": ("key-reuse", "PRNG key consumed twice without split "
                         "(correlated draws)"),
    "G04": ("jit-boundary", "jit-boundary hygiene: mutable defaults, "
                            "self/bound-method capture, unpinned shape params"),
    "G05": ("broad-except", "broad except swallows errors before "
                            "runtime/faults.py classification"),
    "G06": ("telemetry-discipline", "metric names must be literal (or "
                                    "forwarded params); labels ride the "
                                    "name|k=v convention with literal "
                                    "keys; record_fault literals must be "
                                    "registered FAULT_KINDS"),
    "G07": ("cache-scale-awareness", "reshape/gather/concat directly on "
                                     "KVCache.k/.v outside ops helpers — "
                                     "int8 scales must ride along "
                                     "(cache_kv_map)"),
    "G08": ("span-hygiene", "tracer spans must be context-managed and "
                            "phase= tags must come from the known phase "
                            "table"),
    # G09-G11 live in lint/threads.py (the whole-tree concurrency layer),
    # not in default_rules(): they need every module at once, not one file
    "G09": ("guarded-by", "shared attribute reached from >=2 thread roots "
                          "mutated outside its consistently-held lock "
                          "(incl. non-atomic read-modify-write)"),
    "G10": ("lock-order", "cycle in the global lock-acquisition ordering "
                          "graph (or non-reentrant self-reacquisition) — "
                          "a static deadlock"),
    "G11": ("blocking-under-lock", "blocking call (sleep, result/join "
                                   "without timeout=0, network) while "
                                   "holding a contended lock"),
}

#: numpy-namespace fetch calls (host materialization of a device value)
_FETCH_CALLS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array",
                "jax.device_get", "device_get"}
_CAST_BUILTINS = {"float", "int", "bool"}

def _names_outside_metadata(expr: ast.expr) -> Set[str]:
    """Names in ``expr`` NOT reached through metadata attribute access
    (``x.shape``/``.size``/... are Python-static under trace)."""
    names: Set[str] = set()

    def walk(n: ast.AST) -> None:
        if isinstance(n, ast.Attribute) and n.attr in METADATA_ATTRS:
            return  # the base only appears as metadata here
        if isinstance(n, ast.Name):
            names.add(n.id)
        for child in ast.iter_child_nodes(n):
            walk(child)

    walk(expr)
    return names


class HostSyncRule:
    """G01 — see module docstring."""

    rule = "G01"

    @staticmethod
    def _device_names(frame) -> set:
        """Names plausibly holding traced/device values, walked up to the
        device-region root: every jit frame contributes its non-static
        params + jax-derived locals (anything reaching a jit body is
        traced); ``launch`` closures contribute only jax-derived locals
        (their params are host batch metadata)."""
        names: set = set()
        f = frame
        while f is not None:
            if f.in_jit:
                names |= f.traced_names()
            else:
                names |= f.traced_locals
            if f.is_jit or f.is_launch:
                break
            f = f.parent
        return names

    def check_call(self, node: ast.Call, ctx: FileContext,
                   v: LintVisitor) -> None:
        frame = v.function
        fn = dotted_name(node.func)
        is_item = isinstance(node.func, ast.Attribute) and node.func.attr == "item"
        in_device = frame is not None and frame.in_device_region
        if is_item and (in_device or ctx.hot_module):
            where = (frame.region_desc() if in_device
                     else "a hot-path module")
            v.report(self.rule, node,
                     f".item() forces a per-element device sync inside "
                     f"{where}; fetch whole arrays at the sanctioned "
                     f"consume points instead")
            return
        if not in_device:
            return
        if fn in _FETCH_CALLS:
            v.report(self.rule, node,
                     f"{fn}() materializes a device value inside "
                     f"{frame.region_desc()}; move the fetch "
                     f"to the pipeline's consume callback")
        elif fn in _CAST_BUILTINS and node.args:
            # metadata access is host-static: `int(cache.k.size + ...)`
            # never touches the device even when `cache` is traced
            arg_names = _names_outside_metadata(node.args[0])
            hits = sorted(arg_names & self._device_names(frame))
            if hits:
                v.report(self.rule, node,
                         f"{fn}() on traced/device value(s) "
                         f"{', '.join(hits)} inside a device region blocks "
                         f"on the device (ConcretizationError under jit); "
                         f"keep scalars on device or fetch in consume")


class TracedControlFlowRule:
    """G02 — see module docstring."""

    rule = "G02"

    @staticmethod
    def _skip_test(test: ast.expr) -> bool:
        """Tests that are fine in a trace: identity-vs-None, isinstance,
        hasattr — they interrogate Python structure, not traced values."""
        if isinstance(test, ast.Compare) and all(
                isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops):
            return True
        if isinstance(test, ast.Call) and dotted_name(test.func) in (
                "isinstance", "hasattr", "callable", "len"):
            return True
        return False

    def _check(self, node, test: ast.expr, ctx: FileContext,
               v: LintVisitor, kind: str) -> None:
        frame = v.function
        if frame is None or not frame.in_jit:
            return
        if self._skip_test(test):
            return
        # the innermost jit frame's traced names (params minus statics,
        # plus locals derived from jnp/jax/lax expressions)
        jit_frame = frame
        while jit_frame is not None and not jit_frame.is_jit:
            jit_frame = jit_frame.parent
        traced = (jit_frame or frame).traced_names() | frame.traced_names()
        names = {n.id for sub in ast.walk(test)
                 for n in [sub] if isinstance(sub, ast.Name)}
        # skip sub-tests that are themselves identity checks (`x is None`)
        for sub in ast.walk(test):
            if isinstance(sub, ast.Compare) and all(
                    isinstance(op, (ast.Is, ast.IsNot)) for op in sub.ops):
                for n in ast.walk(sub):
                    if isinstance(n, ast.Name):
                        names.discard(n.id)
        hits = sorted(names & traced)
        if hits:
            v.report(self.rule, node,
                     f"Python {kind} on traced value(s) {', '.join(hits)} "
                     f"inside a jit region — concretizes the tracer (or "
                     f"retraces per value); use lax.cond/jnp.where, or "
                     f"declare the parameter in static_argnames")

    def check_if(self, node: ast.If, ctx, v) -> None:
        self._check(node, node.test, ctx, v, "if")

    def check_while(self, node: ast.While, ctx, v) -> None:
        self._check(node, node.test, ctx, v, "while")

    def check_ifexp(self, node: ast.IfExp, ctx, v) -> None:
        self._check(node, node.test, ctx, v, "conditional expression")


#: jax.random.* calls that DERIVE keys rather than consuming entropy.
_KEY_DERIVERS = {"split", "fold_in", "PRNGKey", "key", "key_data",
                 "wrap_key_data", "clone"}


class KeyReuseRule:
    """G03 — see module docstring.  Statement-order scan per scope."""

    rule = "G03"

    def check_module(self, tree: ast.Module, ctx: FileContext,
                     v: LintVisitor) -> None:
        self._scan_scope(tree.body, ctx, v)

    def check_functiondef(self, node, ctx: FileContext,
                          v: LintVisitor) -> None:
        if isinstance(node.body, list):  # lambdas carry a bare expression
            self._scan_scope(node.body, ctx, v)

    # -- implementation ---------------------------------------------------

    @staticmethod
    def _random_fn(call: ast.Call) -> Optional[str]:
        """'normal' for jax.random.normal(...) / random.normal(...)."""
        fn = dotted_name(call.func)
        if fn.startswith("jax.random.") or fn.startswith("jrandom."):
            return fn.rsplit(".", 1)[1]
        if fn.startswith("random.") and fn.count(".") == 1:
            # `from jax import random` idiom; the stdlib `random` module
            # takes no key argument, so key-var tracking disambiguates
            return fn.rsplit(".", 1)[1]
        return None

    def _scan_scope(self, body, ctx: FileContext, v: LintVisitor) -> None:
        # keys: name -> (consumed_once, assigned_loop_depth)
        keys: Dict[str, Tuple[bool, int]] = {}

        def handle_call(call: ast.Call, loop_depth: int) -> None:
            fn = self._random_fn(call)
            if fn is None or fn in _KEY_DERIVERS - {"split", "fold_in"}:
                return
            consumes = fn not in _KEY_DERIVERS
            for arg in call.args[:1]:  # the key is the first positional arg
                if not isinstance(arg, ast.Name) or arg.id not in keys:
                    continue
                consumed, assigned_depth = keys[arg.id]
                if not consumes:
                    continue
                if consumed:
                    v.report(self.rule, call,
                             f"PRNG key '{arg.id}' consumed again without "
                             f"split — draws from a reused key are "
                             f"correlated; split it first")
                elif loop_depth > assigned_depth:
                    v.report(self.rule, call,
                             f"PRNG key '{arg.id}' (assigned outside this "
                             f"loop) is consumed every iteration — each "
                             f"pass draws IDENTICAL values; split per "
                             f"iteration or fold_in the loop index")
                else:
                    keys[arg.id] = (True, assigned_depth)

        def note_assign(targets, value, loop_depth: int) -> None:
            is_key_expr = False
            if isinstance(value, ast.Call):
                fn = self._random_fn(value)
                is_key_expr = fn in ("PRNGKey", "split", "fold_in", "key",
                                     "clone", "wrap_key_data")
            elif isinstance(value, ast.Name) and value.id in keys:
                is_key_expr = True  # aliasing
            names: List[str] = []
            for t in targets:
                for n in ast.walk(t):
                    if isinstance(n, ast.Name):
                        names.append(n.id)
            for name in names:
                if is_key_expr:
                    keys[name] = (False, loop_depth)
                elif name in keys:
                    del keys[name]  # rebound to a non-key value

        def walk(stmts, loop_depth: int) -> None:
            for stmt in stmts:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                    continue  # nested scopes get their own scan
                # calls in this statement's HEADER only — compound bodies
                # are recursed below at their own loop depth, and walking
                # them here too would double-count every consumption
                if isinstance(stmt, (ast.For, ast.AsyncFor)):
                    headers = [stmt.iter]
                elif isinstance(stmt, (ast.While, ast.If)):
                    headers = [stmt.test]
                elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                    headers = [item.context_expr for item in stmt.items]
                elif isinstance(stmt, ast.Try):
                    headers = []
                else:
                    headers = [stmt]
                for header in headers:
                    for sub in ast.walk(header):
                        if isinstance(sub, ast.Call):
                            handle_call(sub, loop_depth)
                if isinstance(stmt, ast.Assign):
                    note_assign(stmt.targets, stmt.value, loop_depth)
                elif isinstance(stmt, ast.AugAssign):
                    note_assign([stmt.target], stmt.value, loop_depth)
                elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                    walk(stmt.body, loop_depth + 1)
                    walk(stmt.orelse, loop_depth)
                elif isinstance(stmt, ast.While):
                    walk(stmt.body, loop_depth + 1)
                    walk(stmt.orelse, loop_depth)
                elif isinstance(stmt, ast.If):
                    walk(stmt.body, loop_depth)
                    walk(stmt.orelse, loop_depth)
                elif isinstance(stmt, (ast.With, ast.AsyncWith, ast.Try)):
                    for attr in ("body", "orelse", "finalbody"):
                        walk(getattr(stmt, attr, []) or [], loop_depth)
                    for h in getattr(stmt, "handlers", []) or []:
                        walk(h.body, loop_depth)

        walk(body, 0)


#: parameter-name suffixes that are shape-like in this codebase (bucket
#: lengths, batch sizes, chunk/step counts) — feeding them traced means one
#: recompile per distinct value.
_SHAPE_SUFFIXES = ("_len", "_size", "_steps", "_chunk")


class JitBoundaryRule:
    """G04 — see module docstring."""

    rule = "G04"

    def check_functiondef(self, node, ctx: FileContext,
                          v: LintVisitor) -> None:
        frame = v.function
        if frame is None or not frame.is_jit:
            return
        # (a) mutable defaults: one instance shared by EVERY trace
        args = node.args
        for default in list(args.defaults) + [
                d for d in args.kw_defaults if d is not None]:
            if isinstance(default, (ast.List, ast.Dict, ast.Set)):
                v.report(self.rule, default,
                         "mutable default argument on a jit-compiled "
                         "function: one shared instance leaks state across "
                         "traces; default to None and normalize inside")
        # (b) methods: jit over `self` keys the compile cache per instance
        if frame.params[:1] == ["self"]:
            v.report(self.rule, node,
                     "jax.jit directly on a method: the cache is keyed on "
                     "the bound instance, so every engine object re-traces "
                     "and holds its programs alive (defeats plan-key "
                     "sharing); jit a free function or use a cached "
                     "closure")
        # (d) bare jit over shape-like params
        if not frame.static_params:
            shapeish = [p for p in frame.params
                        if p.endswith(_SHAPE_SUFFIXES)]
            if shapeish:
                v.report(self.rule, node,
                         f"jit without static_argnums/static_argnames over "
                         f"shape-like parameter(s) {', '.join(shapeish)}: "
                         f"tracing them defeats bucketing (a recompile per "
                         f"distinct value) — declare them static")

    def check_call(self, node: ast.Call, ctx: FileContext,
                   v: LintVisitor) -> None:
        # (c) jax.jit(self.method) / jax.jit(obj.method)
        fn = dotted_name(node.func)
        if fn not in ("jax.jit", "jit", "pjit", "jax.pjit"):
            return
        if node.args and isinstance(node.args[0], ast.Attribute):
            target = dotted_name(node.args[0])
            v.report(self.rule, node,
                     f"jax.jit({target}): jitting a bound method/attribute "
                     f"keys the compile cache on the instance — every new "
                     f"object recompiles and pins its executables; jit a "
                     f"module-level function instead")


class BroadExceptRule:
    """G05 — see module docstring."""

    rule = "G05"

    def check_excepthandler(self, node: ast.ExceptHandler, ctx: FileContext,
                            v: LintVisitor) -> None:
        if not ctx.fault_module:
            return
        def is_broad(t) -> bool:
            if t is None:
                return True
            if isinstance(t, ast.Name):
                return t.id in ("Exception", "BaseException")
            if isinstance(t, ast.Attribute):
                return t.attr in ("Exception", "BaseException")
            if isinstance(t, ast.Tuple):  # except (Exception, OSError):
                return any(is_broad(e) for e in t.elts)
            return False

        if not is_broad(node.type):
            return
        for sub in ast.walk(node):
            if isinstance(sub, ast.Raise):
                return  # re-raises: classification still sees the error
        label = ("bare except" if node.type is None
                 else f"except {dotted_name(node.type) or 'Exception'}")
        v.report(self.rule, node,
                 f"{label} swallows device errors before runtime/faults.py "
                 f"can classify them (RESOURCE_EXHAUSTED never reaches the "
                 f"batch back-off ladder); catch typed exceptions, route "
                 f"through faults.is_oom/oom_detail, or add "
                 f"'# graftlint: disable=G05 <reason>' if the swallow is "
                 f"deliberate")


#: the telemetry recording API (utils/telemetry.py) whose first argument
#: is a metric name — the G06 surface.
_TELEMETRY_RECORDERS = {"record_counter", "record_sample", "record_hist"}

#: label-section skeleton of the `name|k=v,k2=v2` convention after
#: replacing dynamic values with {}: literal keys, comma-separated.
_LABELS_SKELETON_RE = re.compile(
    r"[A-Za-z_][A-Za-z0-9_]*=(\{\}|[A-Za-z0-9_.-]*)"
    r"(,[A-Za-z_][A-Za-z0-9_]*=(\{\}|[A-Za-z0-9_.-]*))*$")


class TelemetryDisciplineRule:
    """G06 — see module docstring.

    The telemetry layer keys on PLAIN STRINGS, and the Prometheus
    exporter (obs/metrics.split_labeled_name) re-splits the
    ``name|k=v,k2=v2`` convention into one labeled family.  That only
    works when metric names are statically enumerable: a dynamically
    concatenated name (``"slot_" + kind``) mints an unbounded family the
    README counter table cannot document and bench-diff cannot align.
    Allowed spellings for the name argument of
    record_counter/record_sample/record_hist:

    - a string literal (labels, if any, after ``|`` with literal keys);
    - an f-string whose BASE (before ``|``) is literal and whose label
      section has literal keys — values may interpolate
      (``f"k_steps_saved|leg={leg}"``);
    - a forwarded parameter of the enclosing function — the chokepoint-
      helper idiom (scheduler._counter, slots._slot_counter); the
      helper's callers are checked instead (and `lint contracts`
      enumerates names through those chokepoints);
    - a module-level string constant (runtime/strict.RECOMPILE_COUNTER);
    - a forwarded parameter plus a precomputed label suffix
      (``name + self._label_suffix``).

    ``record_fault`` is the registry-side twin: a LITERAL kind (either
    IfExp arm counts) must be a member of
    :data:`..utils.telemetry.FAULT_KINDS` — the flight recorder's
    trigger set and every fault listener match on exact kinds, so a
    typo'd literal forks an event stream nothing ever reads.  Dynamic
    kinds (forwarded params, computed names) are the chokepoint idiom
    and stay out of scope here.
    """

    rule = "G06"

    def __init__(self):
        self._module_consts: Dict[str, str] = {}

    def check_module(self, tree: ast.Module, ctx: FileContext,
                     v: LintVisitor) -> None:
        self._module_consts = {}
        for node in tree.body:
            if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Constant) and isinstance(
                    node.value.value, str):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self._module_consts[t.id] = node.value.value

    # -- helpers -----------------------------------------------------------

    @staticmethod
    def _is_param(name: str, frame) -> bool:
        f = frame
        while f is not None:
            if name in f.params:
                return True
            f = f.parent
        return False

    def _check_literal(self, text: str, node, v: LintVisitor) -> None:
        if "|" not in text:
            return
        base, _, labels = text.partition("|")
        if not base or not _LABELS_SKELETON_RE.match(labels or ""):
            v.report(self.rule, node,
                     f"malformed labeled metric name {text!r}: the "
                     f"convention is 'name|k=v,k2=v2' with literal "
                     f"identifier keys (obs/metrics.split_labeled_name "
                     f"cannot re-split anything else into one Prometheus "
                     f"family)")

    def _check_fstring(self, node: ast.JoinedStr, frame,
                       v: LintVisitor) -> None:
        # build the skeleton: literal text stays, FormattedValue -> {}
        parts: List[str] = []
        dynamic_names: List[Optional[str]] = []
        for seg in node.values:
            if isinstance(seg, ast.Constant):
                parts.append(str(seg.value))
                dynamic_names.append(None)
            else:  # FormattedValue
                parts.append("{}")
                inner = seg.value if isinstance(
                    seg, ast.FormattedValue) else None
                dynamic_names.append(
                    inner.id if isinstance(inner, ast.Name) else "")
        skeleton = "".join(parts)
        base, sep, labels = skeleton.partition("|")
        if "{}" in base:
            # the one sanctioned dynamic base: a single forwarded param
            # (the chokepoint-helper idiom, e.g. f"{name}|leg={leg}")
            first_dyn = next((n for p, n in zip(parts, dynamic_names)
                              if p == "{}"), "")
            forwarding = (base == "{}" and first_dyn
                          and self._is_param(first_dyn, frame))
            if not forwarding:
                v.report(self.rule, node,
                         "dynamically-constructed metric name: the base "
                         "before '|' must be a string literal (or a "
                         "forwarded parameter of a chokepoint helper) — "
                         "dynamic names mint unbounded metric families "
                         "the counter table and exporter cannot track")
                return
        if sep and not _LABELS_SKELETON_RE.match(labels):
            v.report(self.rule, node,
                     "labeled metric name must spell labels as "
                     "'|k=v,k2=v2' with LITERAL identifier keys — "
                     "dynamic label keys break the one-family Prometheus "
                     "re-split")

    def _leftmost(self, node: ast.expr) -> ast.expr:
        while isinstance(node, ast.BinOp):
            node = node.left
        return node

    # -- the check ---------------------------------------------------------

    def check_call(self, node: ast.Call, ctx: FileContext,
                   v: LintVisitor) -> None:
        fn = dotted_name(node.func)
        tail = fn.rsplit(".", 1)[-1]
        if tail == "record_fault":
            if node.args:
                self._check_fault_kind(node.args[0], node, v)
            return
        if tail not in _TELEMETRY_RECORDERS:
            return
        if not node.args:
            return
        self._check_name_expr(node.args[0], v.function, node, v)

    def _check_fault_kind(self, arg: ast.expr, node: ast.Call,
                          v: LintVisitor) -> None:
        if isinstance(arg, ast.IfExp):
            self._check_fault_kind(arg.body, node, v)
            self._check_fault_kind(arg.orelse, node, v)
            return
        if not (isinstance(arg, ast.Constant)
                and isinstance(arg.value, str)):
            return  # dynamic kind: chokepoint territory, out of scope
        if arg.value not in FAULT_KINDS:
            v.report(self.rule, node,
                     f"unregistered fault kind {arg.value!r}: "
                     f"record_fault literals must be members of "
                     f"utils/telemetry.FAULT_KINDS — the flight "
                     f"recorder's triggers and fault listeners match on "
                     f"exact kinds, so a typo forks an event stream "
                     f"nothing ever reads")

    def _check_name_expr(self, arg: ast.expr, frame, node: ast.Call,
                         v: LintVisitor) -> None:
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            self._check_literal(arg.value, node, v)
            return
        if isinstance(arg, ast.JoinedStr):
            self._check_fstring(arg, frame, v)
            return
        if isinstance(arg, ast.IfExp):
            # `"hit" if ok else "miss"` — enumerable iff both arms are
            self._check_name_expr(arg.body, frame, node, v)
            self._check_name_expr(arg.orelse, frame, node, v)
            return
        if isinstance(arg, ast.Name):
            if self._is_param(arg.id, frame):
                return  # chokepoint forwarding: callers are the surface
            if arg.id in self._module_consts:
                self._check_literal(self._module_consts[arg.id], node, v)
                return
            v.report(self.rule, node,
                     f"metric name '{arg.id}' is not statically "
                     f"resolvable (not a literal, module constant, or "
                     f"forwarded parameter) — telemetry names must be "
                     f"enumerable for the counter table and the "
                     f"Prometheus exporter")
            return
        if isinstance(arg, ast.BinOp):
            left = self._leftmost(arg)
            if isinstance(left, ast.Name) and self._is_param(
                    left.id, frame):
                return  # name + precomputed label suffix (scheduler idiom)
            v.report(self.rule, node,
                     "dynamically-concatenated metric name: concatenation "
                     "mints metric families the README counter table and "
                     "bench-diff cannot track; use a literal base with "
                     "the 'name|k=v' labeled convention instead")
            return
        v.report(self.rule, node,
                 "metric name is not statically resolvable; pass a "
                 "string literal (labels via 'name|k=v' with literal "
                 "keys) or forward a chokepoint helper's parameter")


#: array-manipulation callables that re-layout cache storage — the exact
#: operations that must keep k_scale/v_scale aligned with the int8 codes.
_CACHE_MANIP_FNS = {
    "reshape", "concatenate", "stack", "take", "take_along_axis",
    "gather", "dynamic_slice", "dynamic_update_slice", "pad", "tile",
    "repeat", "moveaxis", "swapaxes", "transpose", "broadcast_to",
    "roll", "flip", "split", "where", "zeros_like", "empty_like",
}

#: modules allowed to touch KVCache.k/.v storage directly: the ops
#: helpers (quant/attention readers) and the decoder that OWNS the cache
#: layout (cache_kv_map and the append/fold sites live there).
_CACHE_EXEMPT_PATHS = ("/ops/", "ops/", "models/decoder.py")


class CacheScaleAwarenessRule:
    """G07 — see module docstring.

    The int8-KV audit (PR 5) chased exactly this bug class by hand: a
    reshape/gather/concat applied to ``cache.k``/``cache.v`` codes
    without the same transform on ``k_scale``/``v_scale`` silently
    dequantizes with misaligned scales.  Every cache-reshaping site must
    route through ``models/decoder.cache_kv_map`` (which maps codes AND
    scales) or live in the exempt helper modules.  Metadata access
    (``cache.k.shape``/``.size``/``.dtype``) is host-static and fine."""

    rule = "G07"

    @classmethod
    def _cache_kv_operands(cls, node: ast.expr) -> List[ast.Attribute]:
        """``.k``/``.v`` attribute accesses on cache-named bases in the
        subtree, skipping metadata accesses (``cache.k.shape`` never
        touches storage)."""
        hits: List[ast.Attribute] = []

        def walk(n: ast.AST) -> None:
            if isinstance(n, ast.Attribute):
                if n.attr in METADATA_ATTRS:
                    return  # metadata: don't descend into its base
                if n.attr in ("k", "v"):
                    base = dotted_name(n.value)
                    last = base.rsplit(".", 1)[-1].lower()
                    if "cache" in last or last == "kv":
                        hits.append(n)
            for child in ast.iter_child_nodes(n):
                walk(child)

        walk(node)
        return hits

    def check_call(self, node: ast.Call, ctx: FileContext,
                   v: LintVisitor) -> None:
        if any(m in ctx.path for m in _CACHE_EXEMPT_PATHS):
            return
        fn = dotted_name(node.func)
        head, _, tail = fn.partition(".")
        name = fn.rsplit(".", 1)[-1]
        if name not in _CACHE_MANIP_FNS:
            return
        if head not in ("jnp", "jax", "lax", "np", "numpy"):
            return
        hits = []
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            hits.extend(self._cache_kv_operands(arg))
        if hits:
            operand = dotted_name(hits[0].value) + "." + hits[0].attr
            v.report(self.rule, node,
                     f"{fn}() re-layouts {operand} storage directly — "
                     f"with int8 KV the per-head scales must ride every "
                     f"reshape/gather/concat; route through "
                     f"models/decoder.cache_kv_map (or an ops/ helper) "
                     f"so codes and k_scale/v_scale transform together")


#: spans are context-managed (`with obs.span(...)`); the sanctioned
#: exceptions are ExitStack.enter_context(...) and the tracer module's
#: own plumbing.
_SPAN_EXEMPT_PATHS = ("obs/tracer.py",)


class SpanHygieneRule:
    """G08 — see module docstring.

    Two invariants keep the phases block a TRUE partition of wall-clock:
    (a) spans close exactly once, on the thread that opened them — which
    in Python means the ``with`` protocol (an un-entered or leaked span
    corrupts the per-thread stack and every SELF-time total above it);
    (b) phase tags come from the canonical table
    (:data:`..obs.tracer.KNOWN_PHASES`) — a typo'd phase silently forks
    a new row that ``obs report``, the bench ``phases`` block, and
    bench-diff all treat as a different phase."""

    rule = "G08"

    def __init__(self):
        self._managed_ids: set = set()

    def check_module(self, tree: ast.Module, ctx: FileContext,
                     v: LintVisitor) -> None:
        """Pre-collect the span calls that ARE context-managed: withitem
        context expressions and enter_context(...) arguments."""
        self._managed_ids = set()
        for node in ast.walk(tree):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    self._managed_ids.add(id(item.context_expr))
            elif isinstance(node, ast.Call):
                fn = dotted_name(node.func)
                if fn.rsplit(".", 1)[-1] == "enter_context":
                    for arg in node.args:
                        self._managed_ids.add(id(arg))

    @staticmethod
    def _is_span_call(node: ast.Call) -> bool:
        if isinstance(node.func, ast.Attribute):
            return node.func.attr == "span"
        return isinstance(node.func, ast.Name) and node.func.id == "span"

    @staticmethod
    def _is_add_span_call(node: ast.Call) -> bool:
        fn = dotted_name(node.func)
        return fn.rsplit(".", 1)[-1] == "add_span"

    def check_call(self, node: ast.Call, ctx: FileContext,
                   v: LintVisitor) -> None:
        if any(m in ctx.path for m in _SPAN_EXEMPT_PATHS):
            return
        is_span = self._is_span_call(node)
        is_add = not is_span and self._is_add_span_call(node)
        if not (is_span or is_add):
            return
        if is_span and id(node) not in self._managed_ids:
            v.report(self.rule, node,
                     "tracer span must be context-managed ('with "
                     "obs.span(...)' or stack.enter_context(...)): a "
                     "span that never closes corrupts the per-thread "
                     "span stack and every phase SELF-time above it "
                     "(cross-thread timing belongs to add_span)")
        for kw in node.keywords:
            if kw.arg != "phase":
                continue
            val = kw.value
            if isinstance(val, ast.Constant) and val.value is None:
                continue
            if not (isinstance(val, ast.Constant)
                    and isinstance(val.value, str)):
                v.report(self.rule, node,
                         "span phase= must be a string literal from the "
                         "known phase table (obs/tracer.KNOWN_PHASES): a "
                         "computed phase name forks the phases block "
                         "outside the documented partition")
            elif val.value not in KNOWN_PHASES:
                v.report(self.rule, node,
                         f"unknown span phase {val.value!r}: phases come "
                         f"from obs/tracer.KNOWN_PHASES (README 'Span / "
                         f"phase names' table) — add it there first if "
                         f"this is a new pipeline stage")


def default_rules() -> List:
    return [HostSyncRule(), TracedControlFlowRule(), KeyReuseRule(),
            JitBoundaryRule(), BroadExceptRule(),
            TelemetryDisciplineRule(), CacheScaleAwarenessRule(),
            SpanHygieneRule()]
