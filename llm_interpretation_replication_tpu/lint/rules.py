"""graftlint rules G01-G05: the TPU-hostile patterns this repo bans.

Each rule is a small class plugging into :class:`..lint.visitor.LintVisitor`
hooks.  The catalogue (also printed by ``lint --explain``):

- **G01 host-sync** — implicit device→host syncs inside device regions:
  ``.item()``, ``float()/int()/bool()`` on arrays, ``np.asarray``/
  ``np.array``/``jax.device_get`` inside jit-compiled functions or the
  engine's ``launch`` pipeline closures.  One stray sync serializes the
  async dispatch queue the engine's pipelining depends on (the measured
  1→2 pipeline-depth gap was 67.6 → 91.5 prompts/s); inside a jit trace it
  is a ConcretizationError waiting for a shape change.  The sanctioned
  fetch points are the pipeline's ``consume`` callbacks — runtime/strict.py
  arms the same contract at runtime via ``jax.transfer_guard``.
- **G02 traced-control-flow** — Python ``if``/``while`` on traced values
  inside jit regions.  Works on today's shapes, then either crashes
  (ConcretizationTypeError) or — worse — silently retraces per value and
  recompiles per batch.  Static knobs belong in ``static_argnames``;
  value-dependent branches belong in ``lax.cond``/``jnp.where``.
- **G03 key-reuse** — the same PRNG key consumed by two ``jax.random``
  draws without a ``split``: the draws are then CORRELATED (identical for
  the same shape/dtype), which silently destroys initialization scaling
  and any sampled statistic downstream.  ``split``/``fold_in`` are
  derivations, not draws, and don't count as consumption.
- **G04 jit-boundary** — jit-boundary hygiene: mutable default arguments
  on jit'd functions (one shared default across every trace), jit over
  bound methods / ``self`` captures (cache keyed per instance — exactly
  the leak the ``GenerationPlan`` cache keys were built to avoid), and
  bare ``jax.jit`` over shape-like parameters (``*_len``/``*_size``/...)
  that must be static or every distinct value recompiles.
- **G05 broad-except** — ``except Exception``/bare ``except`` that
  SWALLOWS (no re-raise) in the fault-handling layers (runtime/, ops/,
  models/, sweeps/, parallel/, native/): a swallowed RESOURCE_EXHAUSTED
  never reaches runtime/faults.py's OOM classification, so the batch
  back-off ladder can't engage and the sweep records a silently degraded
  operating point.  Handlers that re-raise (``raise`` / ``raise err``)
  pass; intentional keep-alive catches take an inline
  ``# graftlint: disable=G05 <reason>``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .visitor import FileContext, LintVisitor, dotted_name

#: rule id -> (title, one-line summary) — the CLI's --explain table.
RULES: Dict[str, Tuple[str, str]] = {
    "G00": ("syntax-error", "file failed to parse; nothing else was checked"),
    "G01": ("host-sync", "implicit device->host sync inside a device region "
                         "(.item(), float()/bool(), np.asarray in jit/launch)"),
    "G02": ("traced-control-flow", "Python if/while on a traced value inside "
                                   "a jit region (retrace/recompile per value)"),
    "G03": ("key-reuse", "PRNG key consumed twice without split "
                         "(correlated draws)"),
    "G04": ("jit-boundary", "jit-boundary hygiene: mutable defaults, "
                            "self/bound-method capture, unpinned shape params"),
    "G05": ("broad-except", "broad except swallows errors before "
                            "runtime/faults.py classification"),
}

#: numpy-namespace fetch calls (host materialization of a device value)
_FETCH_CALLS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array",
                "jax.device_get", "device_get"}
_CAST_BUILTINS = {"float", "int", "bool"}


class HostSyncRule:
    """G01 — see module docstring."""

    rule = "G01"

    @staticmethod
    def _device_names(frame) -> set:
        """Names plausibly holding traced/device values, walked up to the
        device-region root: every jit frame contributes its non-static
        params + jax-derived locals (anything reaching a jit body is
        traced); ``launch`` closures contribute only jax-derived locals
        (their params are host batch metadata)."""
        names: set = set()
        f = frame
        while f is not None:
            if f.in_jit:
                names |= f.traced_names()
            else:
                names |= f.traced_locals
            if f.is_jit or f.is_launch:
                break
            f = f.parent
        return names

    def check_call(self, node: ast.Call, ctx: FileContext,
                   v: LintVisitor) -> None:
        frame = v.function
        fn = dotted_name(node.func)
        is_item = isinstance(node.func, ast.Attribute) and node.func.attr == "item"
        in_device = frame is not None and frame.in_device_region
        if is_item and (in_device or ctx.hot_module):
            where = ("a jit region" if frame is not None and frame.in_jit
                     else "a hot-path module")
            v.report(self.rule, node,
                     f".item() forces a per-element device sync inside "
                     f"{where}; fetch whole arrays at the sanctioned "
                     f"consume points instead")
            return
        if not in_device:
            return
        if fn in _FETCH_CALLS:
            v.report(self.rule, node,
                     f"{fn}() materializes a device value inside a device "
                     f"region (jit trace / launch closure); move the fetch "
                     f"to the pipeline's consume callback")
        elif fn in _CAST_BUILTINS and node.args:
            arg_names = {n.id for n in ast.walk(node.args[0])
                         if isinstance(n, ast.Name)}
            hits = sorted(arg_names & self._device_names(frame))
            if hits:
                v.report(self.rule, node,
                         f"{fn}() on traced/device value(s) "
                         f"{', '.join(hits)} inside a device region blocks "
                         f"on the device (ConcretizationError under jit); "
                         f"keep scalars on device or fetch in consume")


class TracedControlFlowRule:
    """G02 — see module docstring."""

    rule = "G02"

    @staticmethod
    def _skip_test(test: ast.expr) -> bool:
        """Tests that are fine in a trace: identity-vs-None, isinstance,
        hasattr — they interrogate Python structure, not traced values."""
        if isinstance(test, ast.Compare) and all(
                isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops):
            return True
        if isinstance(test, ast.Call) and dotted_name(test.func) in (
                "isinstance", "hasattr", "callable", "len"):
            return True
        return False

    def _check(self, node, test: ast.expr, ctx: FileContext,
               v: LintVisitor, kind: str) -> None:
        frame = v.function
        if frame is None or not frame.in_jit:
            return
        if self._skip_test(test):
            return
        # the innermost jit frame's traced names (params minus statics,
        # plus locals derived from jnp/jax/lax expressions)
        jit_frame = frame
        while jit_frame is not None and not jit_frame.is_jit:
            jit_frame = jit_frame.parent
        traced = (jit_frame or frame).traced_names() | frame.traced_names()
        names = {n.id for sub in ast.walk(test)
                 for n in [sub] if isinstance(sub, ast.Name)}
        # skip sub-tests that are themselves identity checks (`x is None`)
        for sub in ast.walk(test):
            if isinstance(sub, ast.Compare) and all(
                    isinstance(op, (ast.Is, ast.IsNot)) for op in sub.ops):
                for n in ast.walk(sub):
                    if isinstance(n, ast.Name):
                        names.discard(n.id)
        hits = sorted(names & traced)
        if hits:
            v.report(self.rule, node,
                     f"Python {kind} on traced value(s) {', '.join(hits)} "
                     f"inside a jit region — concretizes the tracer (or "
                     f"retraces per value); use lax.cond/jnp.where, or "
                     f"declare the parameter in static_argnames")

    def check_if(self, node: ast.If, ctx, v) -> None:
        self._check(node, node.test, ctx, v, "if")

    def check_while(self, node: ast.While, ctx, v) -> None:
        self._check(node, node.test, ctx, v, "while")

    def check_ifexp(self, node: ast.IfExp, ctx, v) -> None:
        self._check(node, node.test, ctx, v, "conditional expression")


#: jax.random.* calls that DERIVE keys rather than consuming entropy.
_KEY_DERIVERS = {"split", "fold_in", "PRNGKey", "key", "key_data",
                 "wrap_key_data", "clone"}


class KeyReuseRule:
    """G03 — see module docstring.  Statement-order scan per scope."""

    rule = "G03"

    def check_module(self, tree: ast.Module, ctx: FileContext,
                     v: LintVisitor) -> None:
        self._scan_scope(tree.body, ctx, v)

    def check_functiondef(self, node, ctx: FileContext,
                          v: LintVisitor) -> None:
        if isinstance(node.body, list):  # lambdas carry a bare expression
            self._scan_scope(node.body, ctx, v)

    # -- implementation ---------------------------------------------------

    @staticmethod
    def _random_fn(call: ast.Call) -> Optional[str]:
        """'normal' for jax.random.normal(...) / random.normal(...)."""
        fn = dotted_name(call.func)
        if fn.startswith("jax.random.") or fn.startswith("jrandom."):
            return fn.rsplit(".", 1)[1]
        if fn.startswith("random.") and fn.count(".") == 1:
            # `from jax import random` idiom; the stdlib `random` module
            # takes no key argument, so key-var tracking disambiguates
            return fn.rsplit(".", 1)[1]
        return None

    def _scan_scope(self, body, ctx: FileContext, v: LintVisitor) -> None:
        # keys: name -> (consumed_once, assigned_loop_depth)
        keys: Dict[str, Tuple[bool, int]] = {}

        def handle_call(call: ast.Call, loop_depth: int) -> None:
            fn = self._random_fn(call)
            if fn is None or fn in _KEY_DERIVERS - {"split", "fold_in"}:
                return
            consumes = fn not in _KEY_DERIVERS
            for arg in call.args[:1]:  # the key is the first positional arg
                if not isinstance(arg, ast.Name) or arg.id not in keys:
                    continue
                consumed, assigned_depth = keys[arg.id]
                if not consumes:
                    continue
                if consumed:
                    v.report(self.rule, call,
                             f"PRNG key '{arg.id}' consumed again without "
                             f"split — draws from a reused key are "
                             f"correlated; split it first")
                elif loop_depth > assigned_depth:
                    v.report(self.rule, call,
                             f"PRNG key '{arg.id}' (assigned outside this "
                             f"loop) is consumed every iteration — each "
                             f"pass draws IDENTICAL values; split per "
                             f"iteration or fold_in the loop index")
                else:
                    keys[arg.id] = (True, assigned_depth)

        def note_assign(targets, value, loop_depth: int) -> None:
            is_key_expr = False
            if isinstance(value, ast.Call):
                fn = self._random_fn(value)
                is_key_expr = fn in ("PRNGKey", "split", "fold_in", "key",
                                     "clone", "wrap_key_data")
            elif isinstance(value, ast.Name) and value.id in keys:
                is_key_expr = True  # aliasing
            names: List[str] = []
            for t in targets:
                for n in ast.walk(t):
                    if isinstance(n, ast.Name):
                        names.append(n.id)
            for name in names:
                if is_key_expr:
                    keys[name] = (False, loop_depth)
                elif name in keys:
                    del keys[name]  # rebound to a non-key value

        def walk(stmts, loop_depth: int) -> None:
            for stmt in stmts:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                    continue  # nested scopes get their own scan
                # calls in this statement's HEADER only — compound bodies
                # are recursed below at their own loop depth, and walking
                # them here too would double-count every consumption
                if isinstance(stmt, (ast.For, ast.AsyncFor)):
                    headers = [stmt.iter]
                elif isinstance(stmt, (ast.While, ast.If)):
                    headers = [stmt.test]
                elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                    headers = [item.context_expr for item in stmt.items]
                elif isinstance(stmt, ast.Try):
                    headers = []
                else:
                    headers = [stmt]
                for header in headers:
                    for sub in ast.walk(header):
                        if isinstance(sub, ast.Call):
                            handle_call(sub, loop_depth)
                if isinstance(stmt, ast.Assign):
                    note_assign(stmt.targets, stmt.value, loop_depth)
                elif isinstance(stmt, ast.AugAssign):
                    note_assign([stmt.target], stmt.value, loop_depth)
                elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                    walk(stmt.body, loop_depth + 1)
                    walk(stmt.orelse, loop_depth)
                elif isinstance(stmt, ast.While):
                    walk(stmt.body, loop_depth + 1)
                    walk(stmt.orelse, loop_depth)
                elif isinstance(stmt, ast.If):
                    walk(stmt.body, loop_depth)
                    walk(stmt.orelse, loop_depth)
                elif isinstance(stmt, (ast.With, ast.AsyncWith, ast.Try)):
                    for attr in ("body", "orelse", "finalbody"):
                        walk(getattr(stmt, attr, []) or [], loop_depth)
                    for h in getattr(stmt, "handlers", []) or []:
                        walk(h.body, loop_depth)

        walk(body, 0)


#: parameter-name suffixes that are shape-like in this codebase (bucket
#: lengths, batch sizes, chunk/step counts) — feeding them traced means one
#: recompile per distinct value.
_SHAPE_SUFFIXES = ("_len", "_size", "_steps", "_chunk")


class JitBoundaryRule:
    """G04 — see module docstring."""

    rule = "G04"

    def check_functiondef(self, node, ctx: FileContext,
                          v: LintVisitor) -> None:
        frame = v.function
        if frame is None or not frame.is_jit:
            return
        # (a) mutable defaults: one instance shared by EVERY trace
        args = node.args
        for default in list(args.defaults) + [
                d for d in args.kw_defaults if d is not None]:
            if isinstance(default, (ast.List, ast.Dict, ast.Set)):
                v.report(self.rule, default,
                         "mutable default argument on a jit-compiled "
                         "function: one shared instance leaks state across "
                         "traces; default to None and normalize inside")
        # (b) methods: jit over `self` keys the compile cache per instance
        if frame.params[:1] == ["self"]:
            v.report(self.rule, node,
                     "jax.jit directly on a method: the cache is keyed on "
                     "the bound instance, so every engine object re-traces "
                     "and holds its programs alive (defeats plan-key "
                     "sharing); jit a free function or use a cached "
                     "closure")
        # (d) bare jit over shape-like params
        if not frame.static_params:
            shapeish = [p for p in frame.params
                        if p.endswith(_SHAPE_SUFFIXES)]
            if shapeish:
                v.report(self.rule, node,
                         f"jit without static_argnums/static_argnames over "
                         f"shape-like parameter(s) {', '.join(shapeish)}: "
                         f"tracing them defeats bucketing (a recompile per "
                         f"distinct value) — declare them static")

    def check_call(self, node: ast.Call, ctx: FileContext,
                   v: LintVisitor) -> None:
        # (c) jax.jit(self.method) / jax.jit(obj.method)
        fn = dotted_name(node.func)
        if fn not in ("jax.jit", "jit", "pjit", "jax.pjit"):
            return
        if node.args and isinstance(node.args[0], ast.Attribute):
            target = dotted_name(node.args[0])
            v.report(self.rule, node,
                     f"jax.jit({target}): jitting a bound method/attribute "
                     f"keys the compile cache on the instance — every new "
                     f"object recompiles and pins its executables; jit a "
                     f"module-level function instead")


class BroadExceptRule:
    """G05 — see module docstring."""

    rule = "G05"

    def check_excepthandler(self, node: ast.ExceptHandler, ctx: FileContext,
                            v: LintVisitor) -> None:
        if not ctx.fault_module:
            return
        def is_broad(t) -> bool:
            if t is None:
                return True
            if isinstance(t, ast.Name):
                return t.id in ("Exception", "BaseException")
            if isinstance(t, ast.Attribute):
                return t.attr in ("Exception", "BaseException")
            if isinstance(t, ast.Tuple):  # except (Exception, OSError):
                return any(is_broad(e) for e in t.elts)
            return False

        if not is_broad(node.type):
            return
        for sub in ast.walk(node):
            if isinstance(sub, ast.Raise):
                return  # re-raises: classification still sees the error
        label = ("bare except" if node.type is None
                 else f"except {dotted_name(node.type) or 'Exception'}")
        v.report(self.rule, node,
                 f"{label} swallows device errors before runtime/faults.py "
                 f"can classify them (RESOURCE_EXHAUSTED never reaches the "
                 f"batch back-off ladder); catch typed exceptions, route "
                 f"through faults.is_oom/oom_detail, or add "
                 f"'# graftlint: disable=G05 <reason>' if the swallow is "
                 f"deliberate")


def default_rules() -> List:
    return [HostSyncRule(), TracedControlFlowRule(), KeyReuseRule(),
            JitBoundaryRule(), BroadExceptRule()]
