"""graftlint: JAX-aware static analysis gating this repo's hot paths.

The silent killers of a TPU serving stack are not crashes — they are
unintended device→host syncs (one ``.item()`` serializes the async
dispatch pipeline) and shape-driven recompiles (one traced ``if`` retraces
per batch).  PR 2 built the machinery that avoids them (prefix-KV reuse,
plan-keyed compile caching, double-buffered host pipeline); this package
makes reintroducing them a TEST FAILURE instead of a perf mystery.

Layout:

- :mod:`.visitor` — the AST pass: function stack, jit/device-region and
  static-argname resolution, suppression comments.
- :mod:`.rules` — rules G01 (host-sync), G02 (traced control flow),
  G03 (PRNG key reuse), G04 (jit-boundary hygiene), G05 (broad except
  before fault classification).
- :mod:`.report` — findings, fingerprints, formatting.
- :mod:`.baseline` — the grandfathered-findings ratchet
  (``lint_baseline.json``).
- :mod:`.cli` — the ``python -m llm_interpretation_replication_tpu lint``
  subcommand; ``tests/test_lint.py`` runs it inside tier-1.

The runtime complement lives in :mod:`..runtime.strict`: an env-gated
strict mode (``LLM_INTERP_STRICT=1``) that arms ``jax.transfer_guard``
around the scoring pipeline and counts recompiles, so the same contract
the linter enforces statically is enforced (and telemetered) on device.
"""

from .baseline import apply_baseline, load_baseline, save_baseline
from .cli import default_paths, lint_paths, main
from .report import Finding, format_report
from .rules import RULES, default_rules
from .visitor import lint_source

__all__ = [
    "Finding",
    "RULES",
    "apply_baseline",
    "default_paths",
    "default_rules",
    "format_report",
    "lint_paths",
    "lint_source",
    "load_baseline",
    "main",
    "save_baseline",
]
