"""graftlint: JAX-aware static analysis gating this repo's hot paths.

The silent killers of a TPU serving stack are not crashes — they are
unintended device→host syncs (one ``.item()`` serializes the async
dispatch pipeline) and shape-driven recompiles (one traced ``if`` retraces
per batch).  PR 2 built the machinery that avoids them (prefix-KV reuse,
plan-keyed compile caching, double-buffered host pipeline); this package
makes reintroducing them a TEST FAILURE instead of a perf mystery.

Layout (three analysis layers since PR 18):

- :mod:`.visitor` — the AST passes: a module-level call graph that
  propagates device-region membership interprocedurally (bounded depth,
  import-alias aware), then the rule-dispatching function-stack walk.
- :mod:`.rules` — rules G01 (host-sync), G02 (traced control flow),
  G03 (PRNG key reuse), G04 (jit-boundary hygiene), G05 (broad except
  before fault classification), G06 (telemetry naming discipline),
  G07 (KV-cache scale awareness), G08 (tracer span hygiene).
- :mod:`.contracts` — layer 2, ``lint contracts``: cross-artifact drift
  checking (code vs README tables, pyproject marker registry, bench-diff
  block classification, the sweep-full child-override contract, the
  calibration-provenance citation gate on runtime/plan* coefficients).
- :mod:`.threads` — layer 3, the whole-tree concurrency analysis: infers
  the fleet's thread model (spawn sites, daemon loops, HTTP handlers,
  executor submissions, the implicit ``<api>`` caller) and propagates
  thread-root membership through the call graph, then checks G09
  (guarded-by: shared state mutated outside its consistent lock), G10
  (lock-order: cycles in the global acquisition-ordering graph), and
  G11 (blocking calls under a contended lock).  Findings ride the same
  fingerprint/suppression/baseline machinery as layers 1-2.
- :mod:`.report` — findings, fingerprints, formatting.
- :mod:`.baseline` — the grandfathered-findings ratchet
  (``lint_baseline.json``), including the scope-independent rot check.
- :mod:`.cli` — the ``python -m llm_interpretation_replication_tpu lint``
  subcommand (``--diff`` for changed-files CI runs);
  ``tests/test_lint.py`` runs it inside tier-1.

The runtime complement lives in :mod:`..runtime.strict`: an env-gated
strict mode (``LLM_INTERP_STRICT=1``) that arms ``jax.transfer_guard``
around the scoring pipeline and counts recompiles, so the same contract
the linter enforces statically is enforced (and telemetered) on device.
"""

from .baseline import (apply_baseline, load_baseline, rotten_entries,
                       save_baseline)
from .cli import changed_files, default_paths, lint_paths, main
from .contracts import check_contracts
from .report import Finding, format_report
from .rules import RULES, default_rules
from .threads import (ThreadModel, build_model, collect_thread_findings,
                      model_from_paths)
from .visitor import lint_source

__all__ = [
    "Finding",
    "RULES",
    "ThreadModel",
    "build_model",
    "collect_thread_findings",
    "model_from_paths",
    "apply_baseline",
    "changed_files",
    "check_contracts",
    "default_paths",
    "default_rules",
    "format_report",
    "lint_paths",
    "lint_source",
    "load_baseline",
    "main",
    "rotten_entries",
    "save_baseline",
]
