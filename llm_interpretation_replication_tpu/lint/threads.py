"""Static concurrency analysis — graftlint layer 3 (rules G09-G11).

PR 16 turned the EnginePool into a self-healing fleet: the hot path is
now crossed by the scheduler loop, supervisor monitor/rebuild workers,
per-replica wedge watchdogs, hedge legs, the metrics
``ThreadingHTTPServer``, ``HostPrefetcher`` tokenize threads, and the
flight-recorder dump hooks — synchronizing through locks in 13 modules.
This layer makes the three silent concurrency bug classes a lint
failure instead of a heisenbug:

- **thread-model inference** — enumerate thread entry points
  (``threading.Thread(target=...)``, ``Timer``, executor ``submit``,
  ``BaseHTTPRequestHandler`` ``do_*`` methods) and propagate
  thread-root membership through the interprocedural call graph the
  same way :mod:`.visitor` propagates device-region membership, so
  every function carries the set of threads that can execute it.
  Every PUBLIC function/method additionally carries the implicit
  ``<api>`` root (an arbitrary caller thread).
- **G09 guarded-by** — shared state (a ``self._x`` or module-global
  container reached from >= 2 distinct thread roots) must be mutated
  under the lock(s) the other access sites hold.  Guards are inferred
  from enclosing ``with self._lock:`` regions plus CALLER-held locks:
  a helper whose every resolved internal call site holds the pool lock
  is analyzed as entered with it held (the supervisor router-hook
  contract, without annotations).  Non-atomic read-modify-write and
  container mutation on never-locked shared state is flagged too.
- **G10 lock-order** — the global lock-acquisition ordering graph
  (``serve/``, ``obs/``, ``runtime/``, ``utils/``): an edge A->B for
  every site acquiring B (directly or transitively through resolved
  calls) while holding A.  Any cycle is a potential deadlock and
  fails.  ``Condition(existing_lock)`` aliases to the wrapped lock;
  RLock self-edges are reentrant and exempt.
- **G11 blocking-under-lock** — ``time.sleep``, ``block_until_ready``,
  ``Future.result``, thread ``join``, network calls (directly or
  transitively) while holding a CONTENDED lock (one acquired from >= 2
  thread roots).  ``cond.wait()`` while holding exactly that condition
  is the sanctioned idiom (it releases) and is exempt, as is
  ``result(timeout=0)`` on a completed future.

The analysis is a WHOLE-TREE pass (unlike the per-file G01-G08 walk):
:func:`collect_thread_findings` takes every file in the lint target set
at once, because thread roots in ``serve/`` reach shared state in
``utils/telemetry.py`` only through cross-module call edges.  A partial
target set (``lint --diff``) under-approximates — fewer findings, never
spurious ones.

Approximations (this is an ADVISORY-STATIC analyzer; the runtime twin
is ``runtime/strict.py``): method calls resolve through imports,
``self``, lexical scope, and a unique-method-name heuristic — an
ambiguous name contributes no edge (under-approximation); caller-held
inference trusts in-tree call sites; dynamic dispatch, lambdas, and
locks passed as arguments are invisible.  Findings ride the standard
fingerprint/suppression/baseline machinery — suppress a deliberate
pattern inline with ``# graftlint: disable=G09 <reason>``.
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, List, Mapping, Optional, Sequence, Set, Tuple

from .report import Finding, parse_suppressions, suppressed
from .visitor import dotted_name

#: the implicit thread root carried by every public function/method:
#: "some caller thread we do not control".  Counts as ONE root when
#: deciding shared-ness, so purely-internal single-thread state stays
#: quiet until a second in-tree thread actually reaches it.
API_ROOT = "<api>"

#: fixpoint bounds — mirrors visitor.INTERPROCEDURAL_DEPTH's philosophy:
#: enough hops for every real chain in this repo, bounded for O(n).
HELD_ROUNDS = 4
TRANS_DEPTH = 4

_LOCK_FACTORIES = {
    "threading.Lock": "lock", "threading.RLock": "rlock",
    "threading.Condition": "condition",
    "Lock": "lock", "RLock": "rlock", "Condition": "condition",
}
_THREAD_FACTORIES = {"threading.Thread", "Thread"}
_TIMER_FACTORIES = {"threading.Timer", "Timer"}
_HTTP_HANDLER_BASES = {"BaseHTTPRequestHandler", "SimpleHTTPRequestHandler"}

#: container constructors whose module-level result is mutable shared
#: state (the telemetry registries are exactly this shape)
_CONTAINER_FACTORIES = {
    "list", "dict", "set", "deque", "defaultdict", "OrderedDict",
    "Counter", "collections.deque", "collections.defaultdict",
    "collections.OrderedDict", "collections.Counter",
}

#: method names that mutate their receiver in place
_MUTATING_METHODS = frozenset({
    "append", "extend", "insert", "remove", "pop", "clear", "update",
    "add", "discard", "setdefault", "popitem", "appendleft", "popleft",
    "sort", "reverse",
})

#: self-synchronizing primitives: attributes/globals built from these
#: factories are exempt from G09 (an ``Event.set()``/``Queue.put()`` is
#: its own synchronization) and their methods never resolve through the
#: unique-method-name heuristic (``self._event.wait`` is threading's
#: wait, not some in-tree method that happens to share the name).
_SYNC_FACTORIES = {"threading.Event", "Event", "queue.Queue", "Queue",
                   "queue.SimpleQueue", "SimpleQueue", "threading.local",
                   "threading.Semaphore", "Semaphore",
                   "threading.BoundedSemaphore", "threading.Barrier"}

#: method names too generic for the unique-method-name heuristic: they
#: shadow stdlib synchronization/container methods, so "exactly one
#: in-tree class defines it" is evidence of a COLLISION, not identity.
_GENERIC_METHODS = frozenset({
    "wait", "set", "clear", "get", "put", "join", "start", "acquire",
    "release", "notify", "notify_all", "items", "keys", "values",
    "update", "read", "write", "flush", "send", "recv", "close",
})

#: blocking operations (G11).  Full dotted names match exactly;
#: attribute suffixes match the last component of a dotted callee.
_BLOCKING_FULL = {"time.sleep", "subprocess.run", "subprocess.check_output",
                  "subprocess.check_call"}
_BLOCKING_SUFFIX = {"result", "join", "wait", "wait_for",
                    "block_until_ready", "urlopen", "communicate"}
_BLOCKING_PREFIX = ("requests.",)
#: dotted prefixes never treated as blocking even on a suffix match
#: (``os.path.join`` is not a thread join)
_BENIGN_PREFIX = ("os.", "posixpath.", "ntpath.", "np.", "jnp.", "json.",
                  "shlex.", "itertools.")

#: API-root dunders: entry points an outside caller invokes directly
_API_DUNDERS = {"__init__", "__call__", "__enter__", "__exit__",
                "__iter__", "__next__", "__len__", "__del__"}


def _timeout_zero(call: ast.Call) -> bool:
    """True when the call passes a literal zero timeout (first
    positional or ``timeout=`` keyword) — explicitly non-blocking."""
    if call.args and isinstance(call.args[0], ast.Constant) \
            and call.args[0].value == 0:
        return True
    return any(kw.arg == "timeout" and isinstance(kw.value, ast.Constant)
               and kw.value.value == 0 for kw in call.keywords)


def _module_name(path: str) -> str:
    mod = path[:-3] if path.endswith(".py") else path
    mod = mod.replace("/", ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


def _short_lock(key: str) -> str:
    """Display form of a lock key: ``serve.pool:EnginePool._lock`` ->
    ``pool.EnginePool._lock`` (enough to find it, short enough to read)."""
    mod, _, rest = key.partition(":")
    return f"{mod.rsplit('.', 1)[-1]}.{rest}" if rest else key


class _Fn:
    """One function/method/nested def in the analyzed tree."""

    __slots__ = ("module", "qualname", "name", "cls", "node", "parent",
                 "local_defs", "global_decls", "local_stores", "is_api",
                 "roots", "entry_held", "spawn_target", "blocking",
                 "acquires")

    def __init__(self, module: str, qualname: str, cls: Optional[str],
                 node: ast.AST, parent: Optional["_Fn"]):
        self.module = module
        self.qualname = qualname
        self.name = qualname.rsplit(".", 1)[-1]
        self.cls = cls
        self.node = node
        self.parent = parent
        self.local_defs: Dict[str, "_Fn"] = {}
        self.global_decls: Set[str] = set()
        self.local_stores: Set[str] = set()
        self.is_api = False
        #: root id -> (depth, via-qualname) — minimal-hop provenance
        self.roots: Dict[str, Tuple[int, str]] = {}
        self.entry_held: FrozenSet[str] = frozenset()
        self.spawn_target = False   # used as Thread target / submit fn
        #: non-exempt direct blocking sites: (dotted, lineno)
        self.blocking: List[Tuple[str, int]] = []
        #: canonical locks this fn acquires directly (with-statements)
        self.acquires: Set[str] = set()

    @property
    def key(self) -> Tuple[str, str]:
        return (self.module, self.qualname)


class _Event:
    """One collected body event, with the LEXICAL held-lock set (the
    entry-held contribution is folded in later, after the caller-held
    fixpoint)."""

    __slots__ = ("kind", "fn", "node", "held", "target", "extra")

    def __init__(self, kind: str, fn: _Fn, node: ast.AST,
                 held: FrozenSet[str], target, extra=None):
        self.kind = kind      # "acq" | "call" | "access"
        self.fn = fn
        self.node = node
        self.held = held
        self.target = target  # acq: guard id; call: dotted; access: key
        self.extra = extra    # call: ast.Call; access: access kind


class _Module:
    __slots__ = ("path", "mod", "tree", "lines", "classes", "bases",
                 "functions", "imports", "globals_mut", "pkg",
                 "suppressions")

    def __init__(self, path: str, mod: str, tree: ast.Module,
                 lines: List[str]):
        self.path = path
        self.mod = mod
        self.tree = tree
        self.lines = lines
        self.suppressions = parse_suppressions(lines)
        #: class name -> {method name -> _Fn}
        self.classes: Dict[str, Dict[str, _Fn]] = {}
        #: class name -> base-name strings
        self.bases: Dict[str, List[str]] = {}
        #: qualname -> _Fn (every def, incl. methods and nested)
        self.functions: Dict[str, _Fn] = {}
        #: local name -> (target module dotted, member name | None)
        self.imports: Dict[str, Tuple[str, Optional[str]]] = {}
        #: module-level mutable-container globals
        self.globals_mut: Set[str] = set()
        self.pkg = mod.rsplit(".", 1)[0] if "." in mod else ""


class ThreadModel:
    """The whole-tree concurrency model: thread roots per function, the
    lock registry (with Condition aliasing), guard sets per access, the
    lock-order graph, and the G09/G10/G11 findings derived from them.

    ``tests/test_lint.py`` asserts :meth:`lock_cycles` is empty on the
    real tree — the deadlock-freedom pin the supervisor/pool/queue
    triangle is held to."""

    def __init__(self, file_texts: Mapping[str, str]):
        self.modules: Dict[str, _Module] = {}          # dotted -> module
        self._by_path: Dict[str, _Module] = {}
        self.lock_kinds: Dict[str, str] = {}           # key -> kind
        self.sync_keys: Set[str] = set()               # Event/Queue attrs
        self._lock_alias: Dict[str, str] = {}          # condition -> wrapped
        self._locks_by_attr: Dict[str, List[str]] = {}
        self._methods_by_name: Dict[str, List[_Fn]] = {}
        self.root_labels: Dict[str, str] = {API_ROOT: "an API caller thread"}
        self._events: List[_Event] = []
        self._spawns: List[Tuple[_Fn, ast.AST, str]] = []  # (target, site, root id)
        #: (held canonical, acquired canonical) -> (path, line, descr)
        self.lock_edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
        #: per-fn call events (the transitive queries walk these)
        self._calls_by_fn: Dict[Tuple[str, str], List[_Event]] = {}
        self._parse(file_texts)
        self._collect()
        for ev in self._events:
            if ev.kind == "call":
                self._calls_by_fn.setdefault(ev.fn.key, []).append(ev)
        self._propagate_roots()
        self._propagate_entry_held()
        self._fold_entry_held()
        self._collect_blocking()
        self._build_lock_edges()

    # -- pass A: parse + registries -------------------------------------

    def _parse(self, file_texts: Mapping[str, str]) -> None:
        for path in sorted(file_texts):
            text = file_texts[path]
            try:
                tree = ast.parse(text)
            except SyntaxError:
                continue    # the per-file walk already reported G00
            m = _Module(path, _module_name(path), tree, text.splitlines())
            self.modules[m.mod] = m
            self._by_path[path] = m
        for m in self.modules.values():
            self._collect_imports(m)
            self._collect_defs(m)
        for m in self.modules.values():
            self._collect_locks_and_globals(m)
        # canonicalize condition aliases transitively (Condition(lock))
        for key in list(self._lock_alias):
            seen = {key}
            tgt = self._lock_alias[key]
            while tgt in self._lock_alias and tgt not in seen:
                seen.add(tgt)
                tgt = self._lock_alias[tgt]
            self._lock_alias[key] = tgt
        for fns in self._methods_by_name.values():
            fns.sort(key=lambda f: (f.module, f.qualname))

    def _collect_imports(self, m: _Module) -> None:
        for node in m.tree.body:
            if isinstance(node, ast.Import):
                for a in node.names:
                    local = a.asname or a.name.split(".")[0]
                    target = a.name if a.asname else a.name.split(".")[0]
                    m.imports[local] = (target, None)
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:
                    parts = m.mod.split(".")
                    # a module's package is its dotted name minus the
                    # leaf (packages themselves keep the full name)
                    if not m.path.endswith("__init__.py"):
                        parts = parts[:-1]
                    parts = parts[: len(parts) - (node.level - 1)]
                    base = ".".join(parts + ([base] if base else []))
                for a in node.names:
                    local = a.asname or a.name
                    full = f"{base}.{a.name}" if base else a.name
                    if full in self.modules or a.name == "*":
                        m.imports[local] = (full, None)   # module alias
                    else:
                        m.imports[local] = (base, a.name)

    def _collect_defs(self, m: _Module) -> None:
        def walk(body, cls: Optional[str], parent: Optional[_Fn],
                 prefix: str) -> None:
            for node in body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qual = f"{prefix}{node.name}"
                    fn = _Fn(m.mod, qual, cls, node, parent)
                    m.functions[qual] = fn
                    if parent is not None:
                        parent.local_defs[node.name] = fn
                    elif cls is not None:
                        m.classes.setdefault(cls, {})[node.name] = fn
                        self._methods_by_name.setdefault(
                            node.name, []).append(fn)
                    for stmt in ast.walk(node):
                        if isinstance(stmt, ast.Global):
                            fn.global_decls.update(stmt.names)
                    self._scan_local_stores(fn)
                    fn.is_api = self._is_api(fn)
                    walk(node.body, cls, fn, f"{qual}.")
                elif isinstance(node, ast.ClassDef) and parent is None:
                    m.classes.setdefault(node.name, {})
                    m.bases[node.name] = [
                        b for b in (dotted_name(x) for x in node.bases) if b]
                    walk(node.body, node.name, None, f"{node.name}.")

        walk(m.tree.body, None, None, "")

    @staticmethod
    def _scan_local_stores(fn: _Fn) -> None:
        # manual stack walk: ast.walk would descend into NESTED defs and
        # pollute this fn's local-store set with theirs
        stack: List[ast.AST] = list(ast.iter_child_nodes(fn.node))
        while stack:
            sub = stack.pop()
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                continue
            if isinstance(sub, ast.Name) and isinstance(
                    sub.ctx, (ast.Store, ast.Del)):
                if sub.id not in fn.global_decls:
                    fn.local_stores.add(sub.id)
            stack.extend(ast.iter_child_nodes(sub))

    @staticmethod
    def _is_api(fn: _Fn) -> bool:
        if fn.parent is not None:       # nested defs are never API
            return False
        if fn.cls is None:
            return not fn.name.startswith("_")
        if fn.cls.startswith("_"):
            return False
        return (not fn.name.startswith("_")) or fn.name in _API_DUNDERS

    def _collect_locks_and_globals(self, m: _Module) -> None:
        # module-level: X = threading.Lock() / mutable containers
        for node in m.tree.body:
            targets: List[ast.expr] = []
            value = None
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, value = [node.target], node.value
            if value is None:
                continue
            for t in targets:
                if not isinstance(t, ast.Name):
                    continue
                self._maybe_register_lock(m, None, t.id, value)
                if self._is_container_value(value):
                    m.globals_mut.add(t.id)
        # any global declared+stored in a function is mutable state too
        for fn in m.functions.values():
            m.globals_mut.update(fn.global_decls)
        # instance locks: self._x = threading.Lock() in any method
        for fn in m.functions.values():
            if fn.cls is None:
                continue
            for sub in ast.walk(fn.node):
                targets, value = [], None
                if isinstance(sub, ast.Assign):
                    targets, value = sub.targets, sub.value
                elif isinstance(sub, ast.AnnAssign) and sub.value is not None:
                    targets, value = [sub.target], sub.value
                if value is None:
                    continue
                for t in targets:
                    if (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        self._maybe_register_lock(m, fn.cls, t.attr, value)

    @staticmethod
    def _is_container_value(value: ast.expr) -> bool:
        if isinstance(value, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                              ast.DictComp, ast.SetComp)):
            return True
        if isinstance(value, ast.Call):
            name = dotted_name(value.func)
            return name in _CONTAINER_FACTORIES
        return False

    def _maybe_register_lock(self, m: _Module, cls: Optional[str],
                             attr: str, value: ast.expr) -> None:
        if not isinstance(value, ast.Call):
            return
        factory = dotted_name(value.func)
        key = f"{m.mod}:{cls}.{attr}" if cls else f"{m.mod}:{attr}"
        if factory in _SYNC_FACTORIES:
            self.sync_keys.add(key)
            return
        kind = _LOCK_FACTORIES.get(factory or "")
        if kind is None:
            return
        self.lock_kinds[key] = kind
        self._locks_by_attr.setdefault(attr, []).append(key)
        if kind == "condition" and value.args:
            wrapped = dotted_name(value.args[0])
            if wrapped and wrapped.startswith("self.") and cls:
                self._lock_alias[key] = f"{m.mod}:{cls}.{wrapped[5:]}"
            elif wrapped and "." not in wrapped:
                self._lock_alias[key] = f"{m.mod}:{wrapped}"

    # -- lock / guard resolution ----------------------------------------

    def canon(self, key: str) -> str:
        return self._lock_alias.get(key, key)

    def canon_kind(self, key: str) -> Optional[str]:
        return self.lock_kinds.get(self.canon(key))

    def _resolve_lockref(self, dotted: Optional[str],
                         fn: _Fn) -> Optional[str]:
        """Dotted lock expression -> guard id.  Canonical lock keys
        participate in G10/G11; an unresolvable expression still gets a
        TEXTUAL guard id (``?module:expr``) so G09 guard-consistency
        sees it, but it contributes no ordering edges."""
        if not dotted:
            return None
        m = self.modules[fn.module]
        parts = dotted.split(".")
        if parts[0] == "self" and len(parts) == 2 and fn.cls:
            key = f"{fn.module}:{fn.cls}.{parts[1]}"
            if key in self.lock_kinds or key in self._lock_alias:
                return self.canon(key)
        if len(parts) == 1:
            key = f"{fn.module}:{parts[0]}"
            if key in self.lock_kinds:
                return self.canon(key)
            imp = m.imports.get(parts[0])
            if imp and imp[1]:
                key = f"{imp[0]}:{imp[1]}"
                if key in self.lock_kinds:
                    return self.canon(key)
            return None                 # a plain name is rarely a lock
        # <anything>._attr: unique-attribute heuristic across the tree
        cands = {self.canon(k) for k in self._locks_by_attr.get(parts[-1], ())}
        if len(cands) == 1:
            return next(iter(cands))
        if self._looks_locky(parts[-1]) or cands:
            return f"?{fn.module}:{dotted}"
        return None

    @staticmethod
    def _looks_locky(attr: str) -> bool:
        low = attr.lower()
        return any(s in low for s in ("lock", "cond", "wake", "mutex"))

    # -- pass B: body events --------------------------------------------

    def _collect(self) -> None:
        for m in self.modules.values():
            for fn in m.functions.values():
                body = getattr(fn.node, "body", [])
                self._walk_stmts(body, fn, frozenset())
        # HTTP handler do_* methods are thread roots (ThreadingHTTPServer
        # runs each request on its own thread)
        for m in self.modules.values():
            for cls, bases in m.bases.items():
                if not any(b.rsplit(".", 1)[-1] in _HTTP_HANDLER_BASES
                           for b in bases):
                    continue
                for name, fn in m.classes.get(cls, {}).items():
                    if name.startswith("do_"):
                        rid = f"{m.mod}:{fn.qualname}"
                        fn.roots.setdefault(rid, (0, fn.qualname))
                        fn.spawn_target = True
                        self.root_labels.setdefault(
                            rid, f"HTTP handler thread {fn.qualname}")

    def _walk_stmts(self, body: Sequence[ast.stmt], fn: _Fn,
                    held: FrozenSet[str]) -> None:
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue    # nested defs run later, NOT under this lock
            if isinstance(node, ast.With):
                inner = held
                for item in node.items:
                    guard = self._resolve_lockref(
                        dotted_name(item.context_expr), fn)
                    self._walk_exprs([item.context_expr], fn, held)
                    if guard is not None:
                        self._events.append(
                            _Event("acq", fn, item.context_expr, inner,
                                   guard))
                        if not guard.startswith("?"):
                            fn.acquires.add(guard)
                        inner = inner | {guard}
                self._walk_stmts(node.body, fn, inner)
                continue
            for field in ("value", "test", "iter", "exc", "msg"):
                sub = getattr(node, field, None)
                if isinstance(sub, ast.expr):
                    self._walk_exprs([sub], fn, held)
            if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                self._handle_store(node, fn, held)
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    if isinstance(t, ast.Subscript):
                        key = self._state_key(t.value, fn)
                        if key:
                            self._events.append(
                                _Event("access", fn, node, held, key, "del"))
                        self._walk_exprs([t.slice], fn, held)
            for sub_body in ("body", "orelse", "finalbody"):
                sub = getattr(node, sub_body, None)
                if isinstance(sub, list) and not isinstance(node, ast.With):
                    self._walk_stmts([s for s in sub
                                      if isinstance(s, ast.stmt)], fn, held)
            for handler in getattr(node, "handlers", []) or []:
                self._walk_stmts(handler.body, fn, held)

    def _handle_store(self, node: ast.stmt, fn: _Fn,
                      held: FrozenSet[str]) -> None:
        if isinstance(node, ast.AugAssign):
            targets = [node.target]
            kinds = ["aug"]
        elif isinstance(node, ast.AnnAssign):
            targets = [node.target] if node.value is not None else []
            kinds = ["store"]
        else:
            targets = list(node.targets)
            kinds = ["store"] * len(targets)
        value = getattr(node, "value", None)
        for t, kind in zip(targets, kinds):
            key = None
            if isinstance(t, (ast.Attribute, ast.Name)):
                key = self._state_key(t, fn)
            elif isinstance(t, ast.Subscript):
                key = self._state_key(t.value, fn)
                kind = "subscript"
                self._walk_exprs([t.slice], fn, held)
            if key is None:
                continue
            # a rebind whose RHS reads the same slot is a read-modify-
            # write in disguise (self.n = self.n + 1)
            if kind == "store" and value is not None:
                target_txt = dotted_name(t) if not isinstance(
                    t, ast.Subscript) else None
                if target_txt and any(
                        dotted_name(s) == target_txt
                        for s in ast.walk(value)
                        if isinstance(s, (ast.Attribute, ast.Name))):
                    kind = "aug"
            self._events.append(_Event("access", fn, node, held, key, kind))

    def _state_key(self, node: ast.expr, fn: _Fn) -> Optional[str]:
        """`self.X` -> ``module:Class.X``; a declared-global or
        module-container bare name -> ``module:X``; else None."""
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self" and fn.cls):
            return f"{fn.module}:{fn.cls}.{node.attr}"
        if isinstance(node, ast.Name):
            name = node.id
            if name in fn.local_stores and name not in fn.global_decls:
                return None
            m = self.modules[fn.module]
            if name in m.globals_mut or name in fn.global_decls:
                return f"{fn.module}:{name}"
        return None

    def _walk_exprs(self, exprs: Sequence[ast.expr], fn: _Fn,
                    held: FrozenSet[str]) -> None:
        stack = list(exprs)
        while stack:
            node = stack.pop()
            if isinstance(node, ast.Lambda):
                continue    # runs later; its body is not under this lock
            if isinstance(node, ast.Call):
                self._handle_call(node, fn, held)
            elif (isinstance(node, ast.Attribute)
                  and isinstance(node.ctx, ast.Load)):
                key = self._state_key(node, fn)
                if key:
                    self._events.append(
                        _Event("access", fn, node, held, key, "read"))
            elif (isinstance(node, ast.Name)
                  and isinstance(node.ctx, ast.Load)):
                key = self._state_key(node, fn)
                if key:
                    self._events.append(
                        _Event("access", fn, node, held, key, "read"))
            stack.extend(ast.iter_child_nodes(node))

    def _handle_call(self, node: ast.Call, fn: _Fn,
                     held: FrozenSet[str]) -> None:
        callee = dotted_name(node.func)
        if callee:
            self._events.append(_Event("call", fn, node, held, callee, node))
            base = callee.rsplit(".", 1)[0] if "." in callee else None
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr in _MUTATING_METHODS and base):
                key = self._state_key(node.func.value, fn)
                if key:
                    self._events.append(
                        _Event("access", fn, node, held, key, "mutcall"))
            self._maybe_spawn(node, callee, fn)

    def _maybe_spawn(self, node: ast.Call, callee: str, fn: _Fn) -> None:
        target_expr: Optional[ast.expr] = None
        if callee in _THREAD_FACTORIES:
            for kw in node.keywords:
                if kw.arg == "target":
                    target_expr = kw.value
        elif callee in _TIMER_FACTORIES and len(node.args) >= 2:
            target_expr = node.args[1]
        elif callee.endswith(".submit") and node.args:
            cand = node.args[0]
            if isinstance(cand, (ast.Name, ast.Attribute)):
                target_expr = cand
        if target_expr is None:
            return
        target = self._resolve_callee(dotted_name(target_expr), fn)
        if target is None:
            return
        rid = f"{target.module}:{target.qualname}"
        label = None
        for kw in node.keywords:
            if kw.arg == "name" and isinstance(kw.value, ast.Constant) \
                    and isinstance(kw.value.value, str):
                label = f"thread '{kw.value.value}'"
        target.roots.setdefault(rid, (0, target.qualname))
        target.spawn_target = True
        self.root_labels.setdefault(
            rid, label or f"thread target {target.qualname}")
        self._spawns.append((target, node, rid))

    # -- call resolution ------------------------------------------------

    def _resolve_callee(self, dotted: Optional[str],
                        fn: _Fn) -> Optional[_Fn]:
        if not dotted:
            return None
        m = self.modules[fn.module]
        parts = dotted.split(".")
        if len(parts) == 1:
            name = parts[0]
            scope: Optional[_Fn] = fn
            while scope is not None:    # lexical nesting, innermost first
                if name in scope.local_defs:
                    return scope.local_defs[name]
                scope = scope.parent
            if name in m.functions and m.functions[name].cls is None:
                return m.functions[name]
            if name in m.classes and "__init__" in m.classes[name]:
                return m.classes[name]["__init__"]
            imp = m.imports.get(name)
            if imp and imp[1] and imp[0] in self.modules:
                return self._module_member(self.modules[imp[0]], imp[1])
            return None
        if parts[0] == "self" and len(parts) == 2 and fn.cls:
            return m.classes.get(fn.cls, {}).get(parts[1])
        if len(parts) == 2:
            imp = m.imports.get(parts[0])
            if imp and imp[1] is None and imp[0] in self.modules:
                return self._module_member(self.modules[imp[0]], parts[1])
        # <expr>.method: unique-method-name heuristic — exactly one
        # class in the analyzed tree defines it, or no edge at all.
        # Generic stdlib names never resolve this way (`._event.wait`
        # is threading's wait even if one in-tree class defines a
        # `wait`), and neither do methods of known lock/sync receivers.
        if parts[-1] in _GENERIC_METHODS:
            return None
        base = ".".join(parts[:-1])
        if self._resolve_lockref(base, fn) is not None:
            return None
        if parts[0] == "self" and len(parts) == 3 and fn.cls \
                and f"{fn.module}:{fn.cls}.{parts[1]}" in self.sync_keys:
            return None
        cands = self._methods_by_name.get(parts[-1], [])
        if len(cands) == 1:
            return cands[0]
        return None

    @staticmethod
    def _module_member(m: _Module, name: str) -> Optional[_Fn]:
        if name in m.functions and m.functions[name].cls is None:
            return m.functions[name]
        if name in m.classes and "__init__" in m.classes[name]:
            return m.classes[name]["__init__"]
        return None

    def _external_dotted(self, dotted: str, fn: _Fn) -> str:
        """Resolve the module half of a dotted callee through imports so
        ``sleep`` imported from ``time`` matches ``time.sleep``."""
        m = self.modules[fn.module]
        parts = dotted.split(".")
        imp = m.imports.get(parts[0])
        if imp and imp[0] not in self.modules:
            if imp[1]:      # from time import sleep
                return ".".join([imp[0], imp[1]] + parts[1:])
            return ".".join([imp[0]] + parts[1:])
        return dotted

    # -- fixpoints ------------------------------------------------------

    def _propagate_roots(self) -> None:
        for m in self.modules.values():
            for fn in m.functions.values():
                if fn.is_api:
                    fn.roots.setdefault(API_ROOT, (0, fn.qualname))
        edges: Dict[Tuple[str, str], Set[Tuple[str, str]]] = {}
        for ev in self._events:
            if ev.kind != "call":
                continue
            target = self._resolve_callee(ev.target, ev.fn)
            if target is not None and target.key != ev.fn.key:
                edges.setdefault(ev.fn.key, set()).add(target.key)
        work = [fn for m in self.modules.values()
                for fn in m.functions.values() if fn.roots]
        while work:
            fn = work.pop()
            for tkey in edges.get(fn.key, ()):
                callee = self.modules[tkey[0]].functions[tkey[1]]
                changed = False
                for rid, (depth, _via) in fn.roots.items():
                    nxt = (depth + 1, fn.qualname)
                    if rid not in callee.roots \
                            or callee.roots[rid][0] > depth + 1:
                        callee.roots[rid] = nxt
                        changed = True
                if changed:
                    work.append(callee)

    def _call_sites_by_target(self) -> Dict[Tuple[str, str], List[_Event]]:
        out: Dict[Tuple[str, str], List[_Event]] = {}
        for ev in self._events:
            if ev.kind != "call":
                continue
            target = self._resolve_callee(ev.target, ev.fn)
            if target is not None and target.key != ev.fn.key:
                out.setdefault(target.key, []).append(ev)
        return out

    def _propagate_entry_held(self) -> None:
        """entry_held(f) = ∩ over resolved in-tree call sites of the
        locks held there (lexical ∪ caller's entry_held).  Thread spawn
        targets start fresh on their own thread: forced empty."""
        sites = self._call_sites_by_target()
        for _ in range(HELD_ROUNDS):
            changed = False
            for m in self.modules.values():
                for fn in m.functions.values():
                    if fn.spawn_target or fn.key not in sites:
                        continue
                    acc: Optional[FrozenSet[str]] = None
                    for ev in sites[fn.key]:
                        h = ev.held | ev.fn.entry_held
                        acc = h if acc is None else (acc & h)
                    acc = acc or frozenset()
                    if acc != fn.entry_held:
                        fn.entry_held = acc
                        changed = True
            if not changed:
                break

    def _fold_entry_held(self) -> None:
        # entry-held locks fold into every event's held set, but NOT
        # into fn.acquires: the caller acquired them — crediting them to
        # the callee would fabricate reversed lock-order edges
        for ev in self._events:
            ev.held = ev.held | ev.fn.entry_held

    # -- blocking + lock edges ------------------------------------------

    def _blocking_reason(self, ev: _Event) -> Optional[str]:
        dotted = self._external_dotted(ev.target, ev.fn)
        if dotted.startswith(_BENIGN_PREFIX):
            return None
        leaf = dotted.rsplit(".", 1)[-1]
        hit = (dotted in _BLOCKING_FULL
               or dotted.startswith(_BLOCKING_PREFIX)
               or leaf in _BLOCKING_SUFFIX)
        if not hit:
            return None
        call: ast.Call = ev.extra
        if leaf in ("result", "wait", "join", "exception") \
                and _timeout_zero(call):
            return None     # result(timeout=0)/wait(0) never blocks
        if leaf in ("wait", "wait_for") and "." in ev.target:
            base = ev.target.rsplit(".", 1)[0]
            guard = self._resolve_lockref(base, ev.fn)
            if guard is not None and guard in ev.held:
                return None     # cond.wait() RELEASES the held condition
        return dotted

    def _collect_blocking(self) -> None:
        for ev in self._events:
            if ev.kind != "call":
                continue
            # an inline `# graftlint: disable=G11 reason` at the
            # blocking site declares it non-blocking for the MODEL too,
            # so transitive findings at its callers clear with it
            supp = self.modules[ev.fn.module].suppressions
            if "G11" in supp.get(ev.node.lineno, ()):
                continue
            reason = self._blocking_reason(ev)
            if reason and self._resolve_callee(ev.target, ev.fn) is None:
                ev.fn.blocking.append((reason, ev.node.lineno))

    def transitive_blocking(self, fn: _Fn, depth: int = TRANS_DEPTH,
                            _seen=None) -> Optional[Tuple[str, str]]:
        """First (blocking op, via-chain) reachable from ``fn``."""
        if _seen is None:
            _seen = set()
        if fn.key in _seen or depth < 0:
            return None
        _seen.add(fn.key)
        if fn.blocking:
            return (fn.blocking[0][0], fn.qualname)
        for ev in self._calls_by_fn.get(fn.key, ()):
            if _timeout_zero(ev.extra):
                continue    # an explicit timeout=0 hop never blocks
            target = self._resolve_callee(ev.target, ev.fn)
            if target is None or target.spawn_target:
                continue
            found = self.transitive_blocking(target, depth - 1, _seen)
            if found:
                return (found[0], f"{fn.qualname} -> {found[1]}")
        return None

    def _transitive_acquires(self, fn: _Fn, depth: int = TRANS_DEPTH,
                             _seen=None) -> Set[str]:
        if _seen is None:
            _seen = set()
        if fn.key in _seen or depth < 0:
            return set()
        _seen.add(fn.key)
        out = set(fn.acquires)
        for ev in self._calls_by_fn.get(fn.key, ()):
            target = self._resolve_callee(ev.target, ev.fn)
            if target is not None and not target.spawn_target:
                out |= self._transitive_acquires(target, depth - 1, _seen)
        return out

    def _build_lock_edges(self) -> None:
        def add_edge(held: str, acq: str, ev: _Event, descr: str) -> None:
            if held.startswith("?") or acq.startswith("?"):
                return
            if held == acq:
                return      # self-edges handled separately (relock)
            edge = (held, acq)
            old = self.lock_edges.get(edge)
            path = self.modules[ev.fn.module].path
            new = (path, ev.node.lineno, descr)
            if old is None or (new[0], new[1]) < (old[0], old[1]):
                self.lock_edges[edge] = new

        for ev in self._events:
            if ev.kind == "acq":
                for h in ev.held:
                    add_edge(h, ev.target, ev,
                             f"{ev.fn.qualname} acquires "
                             f"{_short_lock(ev.target)} while holding "
                             f"{_short_lock(h)}")
            elif ev.kind == "call" and ev.held:
                target = self._resolve_callee(ev.target, ev.fn)
                if target is None or target.spawn_target:
                    continue
                for acq in self._transitive_acquires(target):
                    for h in ev.held:
                        add_edge(h, acq, ev,
                                 f"{ev.fn.qualname} -> {target.qualname} "
                                 f"acquires {_short_lock(acq)} while "
                                 f"holding {_short_lock(h)}")

    # -- public queries --------------------------------------------------

    def roots_of(self, module: str, qualname: str) -> Set[str]:
        m = self.modules.get(module)
        fn = m.functions.get(qualname) if m else None
        return set(fn.roots) if fn else set()

    def lock_roots(self, key: str) -> Set[str]:
        """Thread roots that ACQUIRE ``key`` (the with-statement sites);
        a lock is contended when this has >= 2 members."""
        out: Set[str] = set()
        for ev in self._events:
            if ev.kind == "acq" and ev.target == key:
                out |= set(ev.fn.roots)
        return out

    def lock_cycles(self) -> List[List[str]]:
        """Cycles in the lock-order graph (Tarjan SCCs of size > 1,
        plus non-reentrant self-loops), each as the ordered key list."""
        graph: Dict[str, Set[str]] = {}
        for (a, b) in self.lock_edges:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        on_stack: Set[str] = set()
        stack: List[str] = []
        sccs: List[List[str]] = []
        counter = [0]

        def strongconnect(v: str) -> None:
            index[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            on_stack.add(v)
            for w in sorted(graph.get(v, ())):
                if w not in index:
                    strongconnect(w)
                    low[v] = min(low[v], low[w])
                elif w in on_stack:
                    low[v] = min(low[v], index[w])
            if low[v] == index[v]:
                comp: List[str] = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                if len(comp) > 1:
                    sccs.append(sorted(comp))

        for v in sorted(graph):
            if v not in index:
                strongconnect(v)
        return sccs

    # -- findings --------------------------------------------------------

    def findings(self) -> List[Finding]:
        out: List[Finding] = []
        out.extend(self._g09_findings())
        out.extend(self._g10_findings())
        out.extend(self._g11_findings())
        return out

    def _finding(self, rule: str, ev_module: str, lineno: int,
                 col: int, message: str) -> Finding:
        m = self.modules[ev_module]
        code = ""
        if 1 <= lineno <= len(m.lines):
            code = m.lines[lineno - 1].strip()
        return Finding(rule=rule, path=m.path, line=lineno, col=col,
                       message=message, code=code)

    def _g09_findings(self) -> List[Finding]:
        by_key: Dict[str, List[_Event]] = {}
        for ev in self._events:
            if ev.kind == "access":
                by_key.setdefault(ev.target, []).append(ev)
        out: List[Finding] = []
        for key in sorted(by_key):
            accesses = by_key[key]
            cls = key.split(":", 1)[1].rsplit(".", 2)
            owner_cls = cls[0] if len(cls) > 1 else None
            live = [ev for ev in accesses
                    if not (owner_cls and ev.fn.cls == owner_cls
                            and ev.fn.name in ("__init__", "__post_init__",
                                               "__new__"))]
            roots: Set[str] = set()
            for ev in live:
                roots |= set(ev.fn.roots)
            if len(roots) < 2:
                continue
            if self.canon(key) in self.lock_kinds \
                    or key in self._lock_alias or key in self.sync_keys:
                continue    # locks/Events/Queues are not unguarded state
            guard_pool: Set[str] = set()
            for ev in live:
                guard_pool |= ev.held
            writes = [ev for ev in live if ev.extra != "read"]
            label_bits = sorted(self.root_labels.get(r, r)
                                for r in roots)[:3]
            short = key.split(":", 1)[1]
            for ev in writes:
                if ev.held:
                    continue
                if guard_pool:
                    out.append(self._finding(
                        "G09", ev.fn.module, ev.node.lineno,
                        getattr(ev.node, "col_offset", 0),
                        f"shared state '{short}' (reached from "
                        f"{', '.join(label_bits)}) is mutated in "
                        f"{ev.fn.qualname} without the guard held at its "
                        f"other access sites "
                        f"({', '.join(sorted(_short_lock(g) for g in guard_pool))})"))
                elif ev.extra in ("aug", "mutcall", "subscript", "del"):
                    verb = ("non-atomic read-modify-write on"
                            if ev.extra == "aug" else
                            "unsynchronized container mutation of")
                    out.append(self._finding(
                        "G09", ev.fn.module, ev.node.lineno,
                        getattr(ev.node, "col_offset", 0),
                        f"{verb} shared state '{short}' in "
                        f"{ev.fn.qualname}: reached from "
                        f"{', '.join(label_bits)} and never guarded by "
                        f"any lock — add a lock or confine it to one "
                        f"thread"))
        return out

    def _g10_findings(self) -> List[Finding]:
        out: List[Finding] = []
        # non-reentrant self-acquisition (with L: ... with L:)
        for ev in self._events:
            if ev.kind != "acq" or ev.target.startswith("?"):
                continue
            if ev.target in ev.held \
                    and self.canon_kind(ev.target) != "rlock":
                out.append(self._finding(
                    "G10", ev.fn.module, ev.node.lineno,
                    getattr(ev.node, "col_offset", 0),
                    f"{ev.fn.qualname} re-acquires non-reentrant lock "
                    f"{_short_lock(ev.target)} already held on this "
                    f"path — guaranteed self-deadlock"))
        for comp in self.lock_cycles():
            cyc_edges = [(a, b) for (a, b) in self.lock_edges
                         if a in comp and b in comp]
            sites = sorted((self.lock_edges[e], e) for e in cyc_edges)
            (path, lineno, _), _ = sites[0]
            chain = "; ".join(
                f"{_short_lock(a)} -> {_short_lock(b)} at "
                f"{self.lock_edges[(a, b)][0]}:{self.lock_edges[(a, b)][1]}"
                f" ({self.lock_edges[(a, b)][2]})"
                for (a, b) in sorted(cyc_edges))
            m = self._by_path[path]
            out.append(self._finding(
                "G10", m.mod, lineno, 0,
                f"lock-order cycle across {{{', '.join(_short_lock(k) for k in comp)}}}"
                f" — potential deadlock: {chain}"))
        return out

    def _g11_findings(self) -> List[Finding]:
        out: List[Finding] = []
        contended_cache: Dict[str, bool] = {}

        def contended(lock: str) -> bool:
            if lock not in contended_cache:
                contended_cache[lock] = len(self.lock_roots(lock)) >= 2
            return contended_cache[lock]

        for ev in self._events:
            if ev.kind != "call" or not ev.held:
                continue
            locks = sorted(h for h in ev.held
                           if not h.startswith("?") and contended(h))
            if not locks:
                continue
            reason = self._blocking_reason(ev)
            via = None
            if reason is None and not _timeout_zero(ev.extra):
                target = self._resolve_callee(ev.target, ev.fn)
                if target is not None and not target.spawn_target:
                    found = self.transitive_blocking(target)
                    if found:
                        reason, via = found
            if reason is None:
                continue
            hop = f" (via {via})" if via else ""
            out.append(self._finding(
                "G11", ev.fn.module, ev.node.lineno,
                getattr(ev.node, "col_offset", 0),
                f"blocking call {reason} while holding contended lock"
                f"{'s' if len(locks) > 1 else ''} "
                f"{', '.join(_short_lock(h) for h in locks)} in "
                f"{ev.fn.qualname}{hop} — every other thread queuing on "
                f"the lock stalls behind it; move the blocking work "
                f"outside the critical section"))
        return out


def build_model(file_texts: Mapping[str, str]) -> ThreadModel:
    """Build the whole-tree concurrency model from ``{repo-relative
    posix path: source text}`` — the fixture-facing entry point."""
    return ThreadModel(file_texts)


def collect_thread_findings(
        file_texts: Mapping[str, str]) -> List[Finding]:
    """Run the layer over the given tree and return suppression-filtered
    findings (``# graftlint: disable=G09 reason`` works exactly like the
    per-file rules)."""
    model = build_model(file_texts)
    out: List[Finding] = []
    supp: Dict[str, Dict[int, List[str]]] = {}
    for f in model.findings():
        if f.path not in supp:
            supp[f.path] = parse_suppressions(
                file_texts[f.path].splitlines())
        if not suppressed(f, supp[f.path]):
            out.append(f)
    return out


def model_from_paths(paths: Sequence[str],
                     root: Optional[str] = None) -> ThreadModel:
    """Convenience for tests: read files/dirs like ``lint_paths`` does
    and build the model over them (repo-relative paths)."""
    import os

    from .cli import iter_python_files, repo_root

    root = os.path.abspath(root or repo_root())
    texts: Dict[str, str] = {}
    for fname in iter_python_files(paths):
        try:
            with open(fname, encoding="utf-8") as f:
                text = f.read()
        except OSError:
            continue
        rel = os.path.relpath(os.path.abspath(fname), root)
        texts[rel.replace(os.sep, "/")] = text
    return build_model(texts)
