"""``lint contracts``: cross-artifact drift checking (graftlint layer 2).

Layer 1 (the G-rules) checks code against code-local conventions.  This
layer parses the codebase AND the docs/config as ONE system and fails on
drift between artifacts that describe each other — the conventions that,
before this checker, were each guarded by a hand-written source-pin test
that rotted one PR at a time:

- **counter-table** — telemetry counters recorded in code
  (``record_counter`` and its chokepoint wrappers) vs the README
  "Telemetry counters" table.  A counter recorded but undocumented never
  shows up in anyone's dashboard runbook; a documented counter nothing
  records is a row readers will wait on forever (and, since the
  Prometheus exporter enumerates the telemetry registry generically,
  "documented but never recorded" is exactly "documented but never
  exported").
- **markers** — pytest markers used in ``tests/`` vs the
  ``[tool.pytest.ini_options] markers`` registry in pyproject.toml, both
  directions (an unregistered marker is a silent ``-m`` no-op under
  ``--strict-markers``; a registered-but-unused one is dead config).
- **record-blocks** — top-level blocks ``bench.py`` emits into its JSON
  record vs :mod:`..obs.benchdiff`'s declared classification
  (``ALIGNED_BLOCKS`` / ``CONTEXT_BLOCKS`` / ``INFORMATIONAL_BLOCKS``):
  every emitted block must be consciously classified, and every block
  benchdiff claims to align/contextualize must actually be read by it.
- **child-flags** — ``bench.FULL_STUDY_CHILD_OVERRIDES`` vs the actual
  ``child.x = ...`` assignments inside ``_full_study_secondary``: the
  in-process sweep-full companion inherits the parent namespace, so the
  set of re-pointed attributes IS the forwarding contract.
- **phase-table** — :data:`..obs.tracer.KNOWN_PHASES` vs the README
  "Span / phase names" table (G08 enforces code→table membership; this
  check keeps the two tables themselves in lockstep).
- **calibration** — every pinned cost-model coefficient in
  ``runtime/plan.py`` / ``runtime/plan_search.py`` must cite its
  provenance: ``# anchor: BENCH_rNN`` (solved from that checked-in
  bench record — the refit input of ROADMAP item 4's ``plan
  calibrate`` loop) or ``# prior: <rationale>`` (a documented guess
  and its recalibration story).  A new uncited literal fails the gate;
  an uncited number is one nobody can ever refit.

Everything here is static (regex + ``ast`` over sources): no package
import, no JAX init — cheap enough to run before pytest in the tier-1
gate.  ``--root`` points the checker at another tree (the teeth tests
seed drift into temp copies).
"""

from __future__ import annotations

import argparse
import ast
import json
import os
import re
import sys
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .cli import repo_root

PKG_NAME = "llm_interpretation_replication_tpu"

#: marks provided by pytest itself — never need registration.
_BUILTIN_MARKS = {"parametrize", "skip", "skipif", "xfail", "usefixtures",
                  "filterwarnings", "tryfirst", "trylast"}

#: registered marks that are legitimately unused by any test TODAY:
#: ``slow`` is the tier-1 gate's exclusion selector (``-m 'not slow'``
#: in ROADMAP's verify command) — the registration documents the gate
#: convention and must survive windows where nothing is marked slow.
_SELECTOR_MARKS = {"slow"}

# Only counter-kind names (record_counter + its chokepoint wrappers)
# are checked against the README counter table — sample rings and
# histograms are documented prose-side next to it.


class Drift:
    """One cross-artifact disagreement."""

    def __init__(self, kind: str, message: str, artifact: str):
        self.kind = kind          # check id, e.g. "counter-table"
        self.message = message
        self.artifact = artifact  # the artifact that needs the edit

    def format(self) -> str:
        return f"[{self.kind}] {self.message} (fix in: {self.artifact})"

    def to_json(self) -> Dict:
        return {"kind": self.kind, "message": self.message,
                "artifact": self.artifact}


# ---------------------------------------------------------------------------
# shared parsing helpers
# ---------------------------------------------------------------------------

def _read(path: str) -> Optional[str]:
    try:
        with open(path, encoding="utf-8") as f:
            return f.read()
    except OSError:
        return None


def _iter_package_files(root: str) -> List[str]:
    pkg = os.path.join(root, PKG_NAME)
    out = []
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = [d for d in dirnames
                       if d not in ("__pycache__", ".git")]
        for fname in sorted(filenames):
            if fname.endswith(".py"):
                out.append(os.path.join(dirpath, fname))
    bench = os.path.join(root, "bench.py")
    if os.path.exists(bench):
        out.append(bench)
    return out


def _dotted(node: ast.AST) -> str:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _table_rows(md: str, heading: str) -> List[str]:
    """Backticked names from the FIRST column of the markdown table under
    ``heading`` (rows until the next heading).  ``\\|`` inside backticks
    (the labeled-twin spellings) is unescaped after the column split."""
    lines = md.splitlines()
    names: List[str] = []
    in_section = False
    for line in lines:
        if line.startswith("#") and heading in line:
            in_section = True
            continue
        if in_section and line.startswith("#"):
            break
        if not in_section or not line.startswith("|"):
            continue
        cell = line.replace("\\|", "\x00").split("|")[1]
        for name in re.findall(r"`([^`]+)`", cell.replace("\x00", "\\|")):
            names.append(name)
    return names


# ---------------------------------------------------------------------------
# check 1: telemetry counters vs README counter table
# ---------------------------------------------------------------------------

def _first_arg_literal_base(arg: ast.expr) -> List[str]:
    """Statically-resolvable base name(s) of a metric-name expression:
    the literal (or literal f-string prefix / both IfExp arms), stripped
    of the ``|k=v`` label suffix.  Forwarded params resolve at the
    wrapper's call sites instead and return []."""
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return [arg.value.partition("|")[0]]
    if isinstance(arg, ast.JoinedStr):
        first = arg.values[0] if arg.values else None
        if isinstance(first, ast.Constant):
            base = str(first.value).partition("|")[0]
            # a fully-literal base ends before the first dynamic segment;
            # `f"k_steps_saved|leg={leg}"` resolves, `f"slot_{kind}"` not
            if "|" in str(first.value) or len(arg.values) == 1:
                return [base]
        return []
    if isinstance(arg, ast.IfExp):
        return (_first_arg_literal_base(arg.body)
                + _first_arg_literal_base(arg.orelse))
    if isinstance(arg, ast.BinOp):  # name + label_suffix: base is left
        return _first_arg_literal_base(arg.left)
    return []


def _collect_code_counters(root: str) -> Set[str]:
    """Counter names recorded anywhere in the package + bench.py,
    resolved through chokepoint wrappers (a function whose body forwards
    its own param to ``record_counter`` makes every literal at ITS call
    sites a counter name)."""
    files = _iter_package_files(root)
    trees: List[Tuple[str, ast.Module]] = []
    for path in files:
        text = _read(path)
        if text is None:
            continue
        try:
            trees.append((path, ast.parse(text)))
        except SyntaxError:
            continue
    counters: Set[str] = set()
    # wrapper name -> (call-site positional index of the forwarded name
    # param with any bound self/cls dropped, or -1 for keyword-only;
    # keyword name).  record_counter itself is the (0, "name") root.
    wrappers: Dict[str, Tuple[int, str]] = {"record_counter": (0, "name")}

    def _base_param_name(arg: ast.expr, params: Set[str]) -> Optional[str]:
        """Name of the param the metric-name expression FORWARDS as its
        base (the chokepoint idiom): a bare param, an f-string whose
        base segment is one (``f"{name}|leg={leg}"``), or ``name + sfx``.
        A param that only interpolates a LABEL VALUE
        (``f"k_steps_saved|leg={leg}"``) is not forwarding — the literal
        base resolves right here, and treating the function as a wrapper
        would register its call-site argument strings as counter names."""
        if isinstance(arg, ast.Name):
            return arg.id if arg.id in params else None
        if isinstance(arg, ast.JoinedStr) and arg.values:
            first = arg.values[0]
            if (isinstance(first, ast.FormattedValue)
                    and isinstance(first.value, ast.Name)
                    and first.value.id in params):
                return first.value.id
            return None
        if isinstance(arg, ast.BinOp):
            return _base_param_name(arg.left, params)
        return None

    def _name_arg(call: ast.Call, fn: str) -> Optional[ast.expr]:
        """The metric-name argument at a recorder/wrapper call site:
        the registered keyword if present, else the positional slot."""
        idx, kw = wrappers[fn]
        for k in call.keywords:
            if k.arg == kw:
                return k.value
        if 0 <= idx < len(call.args):
            return call.args[idx]
        return None

    # pass 1 (fixpoint): wrapper discovery — a function that forwards
    # its own param as the NAME argument of record_counter or of an
    # already-known wrapper is itself a wrapper.  The fixpoint makes the
    # idiom transitive: `_reject(..., counter=...)` forwarding to
    # `self._counter(counter)` forwarding to `record_counter(name)`
    # registers `_reject` call-site literals too.
    changed = True
    while changed:
        changed = False
        for path, tree in trees:
            for node in ast.walk(tree):
                if not isinstance(node,
                                  (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                if node.name in wrappers:
                    continue
                pos_params = node.args.posonlyargs + node.args.args
                params = {a.arg for a in (pos_params
                                          + node.args.kwonlyargs)}
                for sub in ast.walk(node):
                    if not isinstance(sub, ast.Call):
                        continue
                    fn = _dotted(sub.func).rsplit(".", 1)[-1]
                    if fn not in wrappers:
                        continue
                    arg = _name_arg(sub, fn)
                    if arg is None:
                        continue
                    pname = _base_param_name(arg, params)
                    if pname is None:
                        continue
                    ordered = [a.arg for a in pos_params]
                    if ordered and ordered[0] in ("self", "cls"):
                        ordered = ordered[1:]   # bound at call sites
                    idx = (ordered.index(pname) if pname in ordered
                           else -1)            # kwonly: keyword-only
                    wrappers[node.name] = (idx, pname)
                    changed = True
                    break
    # pass 2: collect literal (or module-const) names at every recorder
    # and wrapper call site
    for path, tree in trees:
        consts = {t.id: n.value.value for n in ast.walk(tree)
                  if isinstance(n, ast.Assign)
                  and isinstance(n.value, ast.Constant)
                  and isinstance(n.value.value, str)
                  for t in n.targets if isinstance(t, ast.Name)}
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = _dotted(node.func).rsplit(".", 1)[-1]
            if fn not in wrappers:
                continue
            arg = _name_arg(node, fn)
            if arg is None:
                continue
            if (isinstance(arg, ast.Name) and arg.id in consts):
                counters.add(consts[arg.id].partition("|")[0])
            else:
                counters.update(_first_arg_literal_base(arg))
    return {c for c in counters if c}


def check_counter_table(root: str) -> List[Drift]:
    md = _read(os.path.join(root, "README.md"))
    if md is None:
        return [Drift("counter-table", "README.md missing", "README.md")]
    doc_names: List[str] = []
    for name in _table_rows(md, "Telemetry counters"):
        base = name.partition("\\|")[0].partition("|")[0]
        for part in base.split(" / "):
            part = part.strip().strip("`")
            if part:
                doc_names.append(part)
    code = _collect_code_counters(root)
    drifts: List[Drift] = []

    def documented(counter: str) -> bool:
        for doc in doc_names:
            if doc.endswith("*"):
                if counter.startswith(doc[:-1]):
                    return True
            elif counter == doc:
                return True
        return False

    for counter in sorted(code):
        if not documented(counter):
            drifts.append(Drift(
                "counter-table",
                f"counter '{counter}' is recorded in code but missing "
                f"from the README 'Telemetry counters' table",
                "README.md"))
    for doc in doc_names:
        if doc.endswith("*"):
            hit = any(c.startswith(doc[:-1]) for c in code)
        else:
            hit = doc in code
        if not hit:
            drifts.append(Drift(
                "counter-table",
                f"README counter-table row '{doc}' matches no counter "
                f"recorded anywhere in the code (never recorded means "
                f"never exported)",
                "README.md"))
    return drifts


# ---------------------------------------------------------------------------
# check 2: pytest markers vs pyproject registry
# ---------------------------------------------------------------------------

def check_markers(root: str) -> List[Drift]:
    pyproject = _read(os.path.join(root, "pyproject.toml"))
    if pyproject is None:
        return [Drift("markers", "pyproject.toml missing",
                      "pyproject.toml")]
    m = re.search(r"markers\s*=\s*\[(.*?)\]", pyproject, re.DOTALL)
    registered: Set[str] = set()
    if m:
        for entry in re.findall(r'"([^":]+):', m.group(1)):
            registered.add(entry.strip())
    used: Set[str] = set()
    tests_dir = os.path.join(root, "tests")
    if os.path.isdir(tests_dir):
        for fname in sorted(os.listdir(tests_dir)):
            if not fname.endswith(".py"):
                continue
            text = _read(os.path.join(tests_dir, fname)) or ""
            used.update(re.findall(r"pytest\.mark\.(\w+)", text))
    used -= _BUILTIN_MARKS
    drifts: List[Drift] = []
    for name in sorted(used - registered):
        drifts.append(Drift(
            "markers",
            f"pytest marker '{name}' is used in tests/ but not "
            f"registered in pyproject [tool.pytest.ini_options] markers",
            "pyproject.toml"))
    for name in sorted(registered - used - _SELECTOR_MARKS):
        drifts.append(Drift(
            "markers",
            f"pytest marker '{name}' is registered in pyproject but "
            f"used by no test (dead registry entry)",
            "pyproject.toml"))
    return drifts


# ---------------------------------------------------------------------------
# check 3: bench record blocks vs benchdiff classification
# ---------------------------------------------------------------------------

def _bench_emitted_blocks(tree: ast.Module) -> Set[str]:
    """Top-level record blocks bench emits: ``record["k"] = ...``
    subscript assignments plus keys of dict literals returned by local
    helpers applied via ``record.update(helper(...))``."""
    helper_keys: Dict[str, Set[str]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            keys: Set[str] = set()
            for sub in ast.walk(node):
                if isinstance(sub, ast.Return) and isinstance(
                        sub.value, ast.Dict):
                    for k in sub.value.keys:
                        if isinstance(k, ast.Constant) and isinstance(
                                k.value, str):
                            keys.add(k.value)
            if keys:
                helper_keys[node.name] = keys
    emitted: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if (isinstance(t, ast.Subscript)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "record"
                        and isinstance(t.slice, ast.Constant)
                        and isinstance(t.slice.value, str)):
                    emitted.add(t.slice.value)
        elif isinstance(node, ast.Call):
            fn = _dotted(node.func)
            if fn == "record.update" and node.args:
                arg = node.args[0]
                if isinstance(arg, ast.Call):
                    name = _dotted(arg.func).rsplit(".", 1)[-1]
                    emitted.update(helper_keys.get(name, ()))
    return emitted


def _module_str_tuples(tree: ast.Module, names: Sequence[str]
                       ) -> Dict[str, Tuple[List[str], ast.Assign]]:
    out: Dict[str, Tuple[List[str], ast.Assign]] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and isinstance(
                node.value, (ast.Tuple, ast.List)):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id in names:
                    vals = [e.value for e in node.value.elts
                            if isinstance(e, ast.Constant)
                            and isinstance(e.value, str)]
                    out[t.id] = (vals, node)
    return out


def check_record_blocks(root: str) -> List[Drift]:
    bench_text = _read(os.path.join(root, "bench.py"))
    diff_path = os.path.join(root, PKG_NAME, "obs", "benchdiff.py")
    diff_text = _read(diff_path)
    if bench_text is None or diff_text is None:
        return [Drift("record-blocks", "bench.py or obs/benchdiff.py "
                      "missing", "bench.py")]
    try:
        bench_tree = ast.parse(bench_text)
        diff_tree = ast.parse(diff_text)
    except SyntaxError as err:
        return [Drift("record-blocks", f"unparseable source: {err}",
                      "bench.py")]
    emitted = _bench_emitted_blocks(bench_tree)
    declared = _module_str_tuples(
        diff_tree, ("ALIGNED_BLOCKS", "CONTEXT_BLOCKS",
                    "INFORMATIONAL_BLOCKS"))
    drifts: List[Drift] = []
    missing_decls = [n for n in ("ALIGNED_BLOCKS", "CONTEXT_BLOCKS",
                                 "INFORMATIONAL_BLOCKS")
                     if n not in declared]
    if missing_decls:
        return [Drift("record-blocks",
                      f"obs/benchdiff.py no longer declares "
                      f"{', '.join(missing_decls)} — the block contract "
                      f"has no benchdiff side to check against",
                      "obs/benchdiff.py")]
    classified: Set[str] = set()
    decl_nodes = []
    for vals, node in declared.values():
        classified.update(vals)
        decl_nodes.append(node)
    for key in sorted(emitted - classified):
        drifts.append(Drift(
            "record-blocks",
            f"bench.py emits record block '{key}' that obs/benchdiff.py "
            f"classifies in none of ALIGNED_BLOCKS / CONTEXT_BLOCKS / "
            f"INFORMATIONAL_BLOCKS — bench-diff would silently ignore "
            f"it round over round",
            "obs/benchdiff.py"))
    # ALIGNED/CONTEXT entries must actually be READ by benchdiff: the
    # string must occur outside the declaration tuples themselves AND
    # outside docstrings (a docstring mentioning "secondary" is not code
    # reading the block)
    skip_ids = {id(e) for node in decl_nodes
                for e in ast.walk(node)}
    for node in ast.walk(diff_tree):
        if isinstance(node, (ast.Module, ast.FunctionDef,
                             ast.AsyncFunctionDef, ast.ClassDef)):
            body = node.body
            if (body and isinstance(body[0], ast.Expr)
                    and isinstance(body[0].value, ast.Constant)
                    and isinstance(body[0].value.value, str)):
                skip_ids.add(id(body[0].value))
    read_strings: Set[str] = set()
    for node in ast.walk(diff_tree):
        if (isinstance(node, ast.Constant) and isinstance(node.value, str)
                and id(node) not in skip_ids):
            read_strings.add(node.value)
    for decl_name in ("ALIGNED_BLOCKS", "CONTEXT_BLOCKS"):
        for key in declared[decl_name][0]:
            if key not in read_strings:
                drifts.append(Drift(
                    "record-blocks",
                    f"obs/benchdiff.py declares '{key}' in {decl_name} "
                    f"but never reads it — the block stopped being "
                    f"aligned/flattened",
                    "obs/benchdiff.py"))
    return drifts


# ---------------------------------------------------------------------------
# check 4: full-study child-override contract
# ---------------------------------------------------------------------------

def check_child_flags(root: str) -> List[Drift]:
    bench_text = _read(os.path.join(root, "bench.py"))
    if bench_text is None:
        return [Drift("child-flags", "bench.py missing", "bench.py")]
    try:
        tree = ast.parse(bench_text)
    except SyntaxError as err:
        return [Drift("child-flags", f"unparseable bench.py: {err}",
                      "bench.py")]
    declared = _module_str_tuples(tree, ("FULL_STUDY_CHILD_OVERRIDES",))
    if "FULL_STUDY_CHILD_OVERRIDES" not in declared:
        return [Drift("child-flags",
                      "bench.py no longer declares "
                      "FULL_STUDY_CHILD_OVERRIDES — the child-namespace "
                      "contract has no declared side",
                      "bench.py")]
    declared_names = set(declared["FULL_STUDY_CHILD_OVERRIDES"][0])
    fn = next((n for n in tree.body
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
               and n.name == "_full_study_secondary"), None)
    if fn is None:
        return [Drift("child-flags",
                      "bench.py has no _full_study_secondary — update "
                      "the contract checker alongside the refactor",
                      "bench.py")]
    assigned: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "child"):
                    assigned.add(t.attr)
    drifts: List[Drift] = []
    for name in sorted(assigned - declared_names):
        drifts.append(Drift(
            "child-flags",
            f"_full_study_secondary re-points child.{name} without "
            f"declaring it in FULL_STUDY_CHILD_OVERRIDES — undeclared "
            f"overrides are how parent settings silently stop reaching "
            f"the companion run",
            "bench.py"))
    for name in sorted(declared_names - assigned):
        drifts.append(Drift(
            "child-flags",
            f"FULL_STUDY_CHILD_OVERRIDES declares '{name}' but "
            f"_full_study_secondary never assigns child.{name} — the "
            f"declared forwardable flag is dropped by the child block",
            "bench.py"))
    return drifts


# ---------------------------------------------------------------------------
# check 5: tracer phase table vs README phase table
# ---------------------------------------------------------------------------

def check_phase_table(root: str) -> List[Drift]:
    tracer_text = _read(os.path.join(root, PKG_NAME, "obs", "tracer.py"))
    md = _read(os.path.join(root, "README.md"))
    if tracer_text is None or md is None:
        return [Drift("phase-table", "obs/tracer.py or README.md missing",
                      "obs/tracer.py")]
    try:
        tree = ast.parse(tracer_text)
    except SyntaxError as err:
        return [Drift("phase-table", f"unparseable tracer.py: {err}",
                      "obs/tracer.py")]
    known: Set[str] = set()
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "KNOWN_PHASES":
                    for sub in ast.walk(node.value):
                        if (isinstance(sub, ast.Constant)
                                and isinstance(sub.value, str)):
                            known.add(sub.value)
    if not known:
        return [Drift("phase-table",
                      "obs/tracer.py no longer declares KNOWN_PHASES",
                      "obs/tracer.py")]
    doc: Set[str] = set()
    for name in _table_rows(md, "Span / phase names"):
        for part in name.split(" / "):
            part = part.strip().strip("`")
            if part:
                doc.add(part)
    drifts: List[Drift] = []
    for name in sorted(known - doc):
        drifts.append(Drift(
            "phase-table",
            f"phase '{name}' is in obs/tracer.KNOWN_PHASES but missing "
            f"from the README 'Span / phase names' table",
            "README.md"))
    for name in sorted(doc - known):
        drifts.append(Drift(
            "phase-table",
            f"README phase-table row '{name}' is not in "
            f"obs/tracer.KNOWN_PHASES (G08 would reject a span using it)",
            "obs/tracer.py"))
    return drifts


# ---------------------------------------------------------------------------
# check 6: calibration-coefficient provenance (ROADMAP item 4)
# ---------------------------------------------------------------------------

#: the files holding the plan search's pinned cost-model literals.
CALIBRATED_FILES = ("runtime/plan.py", "runtime/plan_search.py")

#: a provenance citation: ``# anchor: BENCH_rNN`` ties the literal to a
#: checked-in bench record the `plan calibrate` loop (ROADMAP item 4)
#: can refit it from; ``# prior: <rationale>`` documents an unmeasured
#: guess AND its recalibration story.  ``#:`` (sphinx-style) counts too.
_CITE_RE = re.compile(r"#:?\s*(anchor:\s*BENCH_r\d+\b|prior:\s*\S)")


def _is_numeric_literal(node: ast.expr) -> bool:
    """A scalar numeric expression made only of constants: ``169.5``,
    ``6_921_420_800``, ``1 << 28``, ``-0.5``.  Tuples/menus (enumerated
    search axes, not calibrated coefficients) don't count."""
    if isinstance(node, ast.Constant):
        return (isinstance(node.value, (int, float))
                and not isinstance(node.value, bool))
    if isinstance(node, ast.UnaryOp):
        return _is_numeric_literal(node.operand)
    if isinstance(node, ast.BinOp):
        return (_is_numeric_literal(node.left)
                and _is_numeric_literal(node.right))
    return False


def check_calibration(root: str) -> List[Drift]:
    """Every pinned cost-model literal must carry its provenance.

    Plan search ranks candidate plans with module-level numeric
    coefficients; a literal without a citation is a number nobody can
    recalibrate — the `plan calibrate` loop needs to know which bench
    record each one was solved from (``anchor:``) or that it is a
    documented guess awaiting its first measurement (``prior:``).  The
    citation rides the assignment line or the comment block directly
    above it."""
    drifts: List[Drift] = []
    for rel in CALIBRATED_FILES:
        path = os.path.join(root, PKG_NAME, rel.replace("/", os.sep))
        text = _read(path)
        if text is None:
            drifts.append(Drift("calibration", f"{rel} missing",
                                PKG_NAME + "/" + rel))
            continue
        try:
            tree = ast.parse(text)
        except SyntaxError as err:
            drifts.append(Drift("calibration",
                                f"unparseable {rel}: {err}",
                                PKG_NAME + "/" + rel))
            continue
        lines = text.splitlines()
        for node in tree.body:
            if not (isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                continue
            name = node.targets[0].id
            if not re.fullmatch(r"[A-Z][A-Z0-9_]*", name):
                continue
            if not _is_numeric_literal(node.value):
                continue
            # trailing comment on the assignment line itself ...
            cited = bool(_CITE_RE.search(lines[node.lineno - 1]))
            # ... or anywhere in the contiguous comment block above it
            i = node.lineno - 2
            while not cited and i >= 0 and lines[i].lstrip().startswith("#"):
                cited = bool(_CITE_RE.search(lines[i]))
                i -= 1
            if not cited:
                drifts.append(Drift(
                    "calibration",
                    f"pinned coefficient {name} ({rel}:{node.lineno}) "
                    f"carries no provenance citation — add "
                    f"'# anchor: BENCH_rNN' (solved from that record) or "
                    f"'# prior: <rationale>' (documented guess + its "
                    f"recalibration story)",
                    PKG_NAME + "/" + rel))
    return drifts


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

CHECKS = (
    ("counter-table", check_counter_table),
    ("markers", check_markers),
    ("record-blocks", check_record_blocks),
    ("child-flags", check_child_flags),
    ("phase-table", check_phase_table),
    ("calibration", check_calibration),
)

#: repo-relative path predicates per check — the ``--diff`` scope: a
#: check runs when ANY file it reads changed.  Predicates take a
#: repo-relative posix path.
CHECK_TRIGGERS = {
    "counter-table": lambda p: (p == "README.md" or p == "bench.py"
                                or (p.startswith(PKG_NAME + "/")
                                    and p.endswith(".py"))),
    "markers": lambda p: (p == "pyproject.toml"
                          or (p.startswith("tests/")
                              and p.endswith(".py"))),
    "record-blocks": lambda p: p in ("bench.py",
                                     PKG_NAME + "/obs/benchdiff.py"),
    "child-flags": lambda p: p == "bench.py",
    "phase-table": lambda p: p in ("README.md",
                                   PKG_NAME + "/obs/tracer.py"),
    "calibration": lambda p: p in tuple(PKG_NAME + "/" + rel
                                        for rel in CALIBRATED_FILES),
}


def check_contracts(root: Optional[str] = None,
                    only: Optional[Sequence[str]] = None) -> List[Drift]:
    root = os.path.abspath(root or repo_root())
    drifts: List[Drift] = []
    for kind, check in CHECKS:
        if only is not None and kind not in only:
            continue
        drifts.extend(check(root))
    return drifts


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="llm_interpretation_replication_tpu lint contracts",
        description="cross-artifact contract checking: code vs README "
                    "tables, pyproject marker registry, bench-diff block "
                    "classification, and the sweep-full child contract")
    parser.add_argument("--root", default=None,
                        help="tree to check (default: this repo)")
    parser.add_argument("--format", choices=["text", "json"],
                        default="text")
    parser.add_argument("--only", default=None, metavar="KIND",
                        help="run one check: " + ", ".join(
                            k for k, _ in CHECKS))
    parser.add_argument("--diff", action="store_true",
                        help="run only the checks whose artifacts "
                             "changed vs git HEAD (cheap CI mode; git "
                             "unavailable falls back to all checks)")
    args = parser.parse_args(argv)
    root = os.path.abspath(args.root or repo_root())
    if args.only:
        table = dict(CHECKS)
        if args.only not in table:
            print(f"unknown check {args.only!r}; known: "
                  f"{', '.join(k for k, _ in CHECKS)}")
            return 2
        drifts = table[args.only](root)
    elif args.diff:
        from .cli import changed_files

        changed = changed_files(root)
        if changed is None:
            drifts = check_contracts(root)
        else:
            triggered = [kind for kind, _ in CHECKS
                         if any(CHECK_TRIGGERS[kind](p) for p in changed)]
            drifts = check_contracts(root, only=triggered)
            if args.format == "text":
                skipped = [k for k, _ in CHECKS if k not in triggered]
                if skipped:
                    print(f"# --diff: skipped {', '.join(skipped)} "
                          f"(no relevant artifact changed)")
    else:
        drifts = check_contracts(root)
    if args.format == "json":
        print(json.dumps({"drift": [d.to_json() for d in drifts]},
                         indent=2))
    else:
        for d in drifts:
            print(d.format())
        print(f"{len(drifts)} contract drift(s)" if drifts
              else "contracts clean: code, docs, and config agree")
    return 1 if drifts else 0


if __name__ == "__main__":
    raise SystemExit(main())
