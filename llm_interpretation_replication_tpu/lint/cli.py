"""``python -m llm_interpretation_replication_tpu lint`` — the repo gate.

Collects the default target set (the package itself plus the repo-root
``bench.py``), runs every rule, subtracts the checked-in baseline, and
exits non-zero on any new finding.  ``tests/test_lint.py`` runs exactly
this entry point inside tier-1, which is what makes the pass a permanent
CI gate rather than a one-shot audit.

Usage::

    python -m llm_interpretation_replication_tpu lint
    python -m llm_interpretation_replication_tpu lint --format json
    python -m llm_interpretation_replication_tpu lint path/to/file.py
    python -m llm_interpretation_replication_tpu lint --diff       # changed files only
    python -m llm_interpretation_replication_tpu lint --explain G02
    python -m llm_interpretation_replication_tpu lint --write-baseline  # refresh
    python -m llm_interpretation_replication_tpu lint contracts    # cross-artifact layer
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional, Sequence, Set

from .baseline import (apply_baseline, load_baseline, rotten_entries,
                       save_baseline)
from .report import Finding, format_report, sort_findings
from .rules import RULES, default_rules
from .visitor import lint_source

#: directories never linted (vendored/caches); tests are exempt because
#: fixtures deliberately contain violations.
EXCLUDE_PARTS = ("/.git/", "/__pycache__/", "/.jax_cache/", "/tests/")


def repo_root() -> str:
    """The directory holding the package (and bench.py / the baseline)."""
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(pkg)


def default_paths() -> List[str]:
    root = repo_root()
    pkg = os.path.join(root, "llm_interpretation_replication_tpu")
    paths = [pkg]
    bench = os.path.join(root, "bench.py")
    if os.path.exists(bench):
        paths.append(bench)
    return paths


def default_baseline_path() -> str:
    return os.path.join(repo_root(), "lint_baseline.json")


def changed_files(root: Optional[str] = None) -> Optional[List[str]]:
    """Repo-relative posix paths changed vs git HEAD (staged, unstaged,
    and untracked) — the ``--diff`` target set for cheap CI.  Returns
    ``None`` when git is unavailable or ``root`` is not a work tree, so
    callers can fall back to the full scan rather than silently passing
    an empty diff."""
    import subprocess

    root = os.path.abspath(root or repo_root())
    out: List[str] = []
    for cmd in (["git", "diff", "--name-only", "HEAD"],
                ["git", "ls-files", "--others", "--exclude-standard"]):
        try:
            proc = subprocess.run(cmd, cwd=root, capture_output=True,
                                  text=True, timeout=30)
        except (OSError, subprocess.TimeoutExpired):
            return None
        if proc.returncode != 0:
            return None
        out.extend(line.strip() for line in proc.stdout.splitlines()
                   if line.strip())
    return sorted(set(out))


def iter_python_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames
                               if d not in ("__pycache__", ".git")]
                for fname in sorted(filenames):
                    if fname.endswith(".py"):
                        full = os.path.join(dirpath, fname)
                        posix = full.replace(os.sep, "/")
                        if not any(part in posix for part in EXCLUDE_PARTS):
                            out.append(full)
        elif p.endswith(".py"):
            out.append(p)
    return out


def lint_paths(paths: Sequence[str], root: Optional[str] = None,
               rules=None, threads: bool = True) -> List[Finding]:
    """Lint files/directories; paths in findings are relative to ``root``
    (default: the repo root) so baselines are machine-independent.

    Runs two layers: the per-file rules (G01-G08) and, unless
    ``threads=False``, the whole-tree concurrency layer (G09-G11) over
    exactly the files scanned.  A partial scan both misses cross-module
    findings AND can invent ones the full tree refutes (a caller that
    holds the lock may live in an unscanned file), which is why
    ``main()``'s ``--diff`` mode passes ``threads=False`` and instead
    runs :func:`thread_findings` over the full target set, filtering
    the report to the changed files."""
    root = os.path.abspath(root or repo_root())
    rules = rules if rules is not None else default_rules()
    findings: List[Finding] = []
    texts = {}
    for fname in iter_python_files(paths):
        try:
            with open(fname, encoding="utf-8") as f:
                text = f.read()
        except OSError as err:
            print(f"# lint: cannot read {fname}: {err}", file=sys.stderr)
            continue
        rel = os.path.relpath(os.path.abspath(fname), root)
        rel_posix = rel.replace(os.sep, "/")
        texts[rel_posix] = text
        findings.extend(lint_source(rel_posix, text, rules))
    if threads and texts:
        from .threads import collect_thread_findings

        findings.extend(collect_thread_findings(texts))
    return sort_findings(findings)


def thread_findings(paths: Optional[Sequence[str]] = None,
                    root: Optional[str] = None) -> List[Finding]:
    """Concurrency findings (G09-G11) over the FULL target set (default:
    the repo gate's), independent of any ``--diff`` restriction — the
    thread model needs every module at once to resolve cross-module
    locks, thread roots, and entry-held callers."""
    root = os.path.abspath(root or repo_root())
    texts = {}
    for fname in iter_python_files(paths or default_paths()):
        try:
            with open(fname, encoding="utf-8") as f:
                text = f.read()
        except OSError:
            continue
        rel = os.path.relpath(os.path.abspath(fname), root)
        texts[rel.replace(os.sep, "/")] = text
    if not texts:
        return []
    from .threads import collect_thread_findings

    return sort_findings(collect_thread_findings(texts))


def main(argv: Optional[Sequence[str]] = None) -> int:
    if argv and argv[0] == "contracts":
        # layer 2: cross-artifact contract checking (`lint contracts`),
        # routed before argparse like the parent `lint` routing itself
        from .contracts import main as contracts_main

        return contracts_main(list(argv[1:]))
    parser = argparse.ArgumentParser(
        prog="llm_interpretation_replication_tpu lint",
        description="JAX-aware static analysis (per-file rules G01-G08 "
                    "with interprocedural device regions, plus the "
                    "whole-tree concurrency layer G09-G11: thread-model "
                    "inference, guarded-by checking, lock-order deadlock "
                    "detection) with a grandfathered-findings baseline; "
                    "`lint contracts` runs the cross-artifact layer")
    parser.add_argument("paths", nargs="*",
                        help="files/dirs to lint (default: the package + "
                             "bench.py)")
    parser.add_argument("--diff", action="store_true",
                        help="lint only files changed vs git HEAD "
                             "(staged+unstaged+untracked); the baseline "
                             "rot check still covers the whole file")
    parser.add_argument("--baseline", default=None,
                        help="baseline JSON (default: lint_baseline.json "
                             "at the repo root; missing file = empty)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="report every finding, grandfathered or not")
    parser.add_argument("--write-baseline", action="store_true",
                        help="write the current findings as the new "
                             "baseline (preserving rationales of entries "
                             "that still match) and exit 0")
    parser.add_argument("--format", choices=["text", "json"], default="text")
    parser.add_argument("--explain", metavar="RULE", default=None,
                        help="print a rule's description and exit")
    args = parser.parse_args(argv)

    if args.explain:
        rid = args.explain.upper()
        if rid == "ALL":
            for rule_id, (title, desc) in sorted(RULES.items()):
                print(f"{rule_id} [{title}] {desc}")
            return 0
        if rid not in RULES:
            print(f"unknown rule {args.explain!r}; known: "
                  f"{', '.join(sorted(RULES))}")
            return 2
        title, desc = RULES[rid]
        print(f"{rid} [{title}] {desc}")
        return 0

    if args.diff and args.write_baseline:
        # a baseline written from a changed-files subset would silently
        # drop every grandfathered entry for untouched files
        print("--write-baseline needs the full scan; drop --diff",
              file=sys.stderr)
        return 2

    paths = args.paths or default_paths()
    linted_rel: Optional[Set[str]] = None
    if args.diff:
        root = repo_root()
        changed = changed_files(root)
        if changed is None:
            print("# lint --diff: git unavailable; falling back to the "
                  "full scan", file=sys.stderr)
        else:
            changed_abs = {os.path.abspath(os.path.join(root, c))
                           for c in changed}
            paths = [f for f in iter_python_files(paths)
                     if os.path.abspath(f) in changed_abs]
            # stale accounting below is restricted to the files actually
            # linted — a --diff run must not flag every untouched file's
            # baseline entry as stale; rot (scope-independent) still runs
            linted_rel = {
                os.path.relpath(os.path.abspath(f), root).replace(
                    os.sep, "/")
                for f in paths}
    if linted_rel is None:
        findings = lint_paths(paths)
    else:
        # --diff: per-file rules over the changed files only, but the
        # thread model over the FULL target set (a subset scan would
        # both miss cross-module findings and invent ones the missing
        # callers refute) — reported for the changed files
        findings = sort_findings(
            lint_paths(paths, threads=False)
            + [f for f in thread_findings() if f.path in linted_rel])
    baseline_path = args.baseline or default_baseline_path()

    if args.write_baseline:
        old = load_baseline(baseline_path)
        rationales = {
            (e.get("rule", ""), e.get("path", ""),
             " ".join(e.get("code", "").split())): e.get("rationale", "")
            for e in old if e.get("rationale")}
        save_baseline(findings, baseline_path, rationales)
        print(f"wrote {len(findings)} finding(s) to {baseline_path}")
        return 0

    entries = [] if args.no_baseline else load_baseline(baseline_path)
    rot = rotten_entries(entries, repo_root())
    scoped = (entries if linted_rel is None
              else [e for e in entries if e.get("path") in linted_rel])
    new, stale, matched = apply_baseline(findings, scoped)
    # a rotten entry is usually also stale on a full run — report it once,
    # under the more specific diagnosis
    rot_ids = set(map(id, rot))
    stale = [e for e in stale if id(e) not in rot_ids]
    print(format_report(new, stale=stale, baselined=matched,
                        fmt=args.format, rot=rot))
    # stale/rotten entries fail the gate too: the baseline is a ratchet,
    # and a leftover entry for fixed code would silently re-shield the
    # next violation with the same fingerprint — delete it (or
    # --write-baseline)
    return 1 if (new or stale or rot) else 0


if __name__ == "__main__":
    raise SystemExit(main())
