"""Baseline (grandfather) file handling for graftlint.

The baseline is the ratchet that lets the linter gate CI from day one: the
findings that existed when the gate landed are recorded in
``lint_baseline.json`` WITH a written rationale each, and the tier-1 test
fails on anything not in that list.  The file only ever shrinks — fixing a
grandfathered finding turns its entry stale, and stale entries are reported
so they get deleted rather than quietly shielding a future regression of
the same shape.

Matching is by ``(rule, path, normalized code line)`` — the same
fingerprint :class:`..lint.report.Finding` exposes — so entries survive
unrelated edits that shift line numbers, but NOT edits to the flagged line
itself (changing the line means re-justifying the exemption).  Entries
match at most once: two identical violations need two entries.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Sequence, Tuple

from .report import Finding, normalize_code

BASELINE_VERSION = 1


def load_baseline(path: str) -> List[Dict]:
    if not os.path.exists(path):
        return []
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if isinstance(data, dict):
        entries = data.get("findings", [])
    else:  # bare list form
        entries = data
    for e in entries:
        e.setdefault("rationale", "")
    return entries


def save_baseline(findings: Sequence[Finding], path: str,
                  rationales: Dict[Tuple[str, str, str], str] = None) -> None:
    """Write ``findings`` as a fresh baseline.  New entries get a TODO
    rationale — the repo convention (tests/test_lint.py enforces it) is
    that every checked-in entry carries a real one."""
    rationales = rationales or {}
    entries = [{
        "rule": f.rule,
        "path": f.path,
        "line": f.line,
        "code": normalize_code(f.code),
        "rationale": rationales.get(f.fingerprint,
                                    "TODO: justify this exemption"),
    } for f in findings]
    payload = {"version": BASELINE_VERSION, "findings": entries}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2)
        f.write("\n")


def rotten_entries(entries: Sequence[Dict], root: str) -> List[Dict]:
    """Baseline entries whose fingerprint no longer matches ANY line of
    the file they point at (or whose file is gone) — baseline rot.

    ``apply_baseline`` only surfaces stale entries for files the current
    run actually linted; a subset run (``--diff``, explicit paths) would
    let an entry for a deleted/rewritten file linger forever, silently
    re-shielding the next violation with the same fingerprint.  This
    check is scope-independent: the entry's own file is re-read from
    disk, so rot fails the gate on every run regardless of target set."""
    rotten: List[Dict] = []
    for e in entries:
        rel = e.get("path", "")
        code = normalize_code(e.get("code", ""))
        try:
            with open(os.path.join(root, rel), encoding="utf-8") as f:
                lines = f.read().splitlines()
        except OSError:
            rotten.append(e)
            continue
        if not code or not any(normalize_code(line) == code
                               for line in lines):
            rotten.append(e)
    return rotten


def apply_baseline(findings: Sequence[Finding], entries: Sequence[Dict]
                   ) -> Tuple[List[Finding], List[Dict], int]:
    """Split ``findings`` against the baseline.

    Returns ``(new_findings, stale_entries, matched_count)``; each entry
    absorbs at most one finding (multiset semantics)."""
    budget: Dict[Tuple[str, str, str], List[Dict]] = {}
    for e in entries:
        key = (e.get("rule", ""), e.get("path", ""),
               normalize_code(e.get("code", "")))
        budget.setdefault(key, []).append(e)
    new: List[Finding] = []
    matched = 0
    for f in findings:
        bucket = budget.get(f.fingerprint)
        if bucket:
            bucket.pop()
            matched += 1
        else:
            new.append(f)
    stale = [e for bucket in budget.values() for e in bucket]
    return new, stale, matched
