"""Rephrasing generation for the prompt-sensitivity study.

Behavioral replica of the reference's Claude-driven rephrasing pipeline
(perturb_prompts.py:727-870): per scenario, N sessions × "rephrase in 20
variations" at temperature 0.9, a numbered-list parser that handles
continuation lines, and the ``perturbations.json`` schema with prompt-identity
verification on reload (ibid.:739-777).

The LLM call is injected as a ``complete(prompt) -> str`` callable so any
backend (api_backends.anthropic client, a local TPU model, a test fake) can
drive generation.
"""

from __future__ import annotations

import json
import os
from typing import Callable, List, Optional, Sequence

REPHRASE_TEMPLATE = (
    'Here is a question:\n###"{main_prompt}"###\n'
    "Please rephrase this question in 20 variations that differ from the "
    "original question but preserve the substance of the question. Each "
    "rephrasing should be a complete question, not just a fragment of a "
    "question. Number each rephrasing from 1 to 20."
)


def parse_numbered_rephrasings(text: str) -> List[str]:
    """Parse a numbered list; unnumbered lines continue the previous entry."""
    out: List[str] = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.lower().startswith("here are"):
            continue
        if line[0].isdigit():
            parts = line.split(".", 1)
            if len(parts) > 1:
                rephrase = parts[1].strip()
            else:
                rephrase = line.lstrip("0123456789").strip(" .-\t")
            out.append(rephrase)
        elif out:
            out[-1] += " " + line
        else:
            out.append(line)
    return out


def generate_rephrasings(
    scenarios: Sequence[dict],
    complete: Callable[[str], str],
    sessions_per_scenario: int = 100,
    target_per_scenario: int = 2000,
    on_error: Optional[Callable[[int, Exception], None]] = None,
) -> List[dict]:
    """Run the generation loop; returns the perturbations.json records."""
    results = []
    for scenario in scenarios:
        main = scenario["original_main"]
        prompt = REPHRASE_TEMPLATE.format(main_prompt=main)
        rephrasings: List[str] = []
        for session in range(sessions_per_scenario):
            if len(rephrasings) >= target_per_scenario:
                break
            try:
                rephrasings.extend(parse_numbered_rephrasings(complete(prompt)))
            except Exception as err:  # sweep continues past broken sessions
                if on_error:
                    on_error(session, err)
        results.append(
            {
                "original_main": main,
                "response_format": scenario["response_format"],
                "target_tokens": list(scenario["target_tokens"]),
                "confidence_format": scenario["confidence_format"],
                "rephrasings": rephrasings[:target_per_scenario],
            }
        )
    return results


def save_perturbations(records: Sequence[dict], path: str) -> None:
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(list(records), f, indent=2, ensure_ascii=False)


def load_perturbations(path: str, expected_scenarios: Optional[Sequence[dict]] = None) -> List[dict]:
    """Load with the reference's identity verification: the saved
    original_main/response_format/target_tokens/confidence_format must match
    the current scenario definitions (perturb_prompts.py:757-772)."""
    with open(path, encoding="utf-8") as f:
        records = json.load(f)
    if expected_scenarios is not None:
        if len(records) != len(expected_scenarios):
            raise ValueError(
                f"perturbation file has {len(records)} scenarios, expected {len(expected_scenarios)}"
            )
        for rec, scen in zip(records, expected_scenarios):
            for key in ("original_main", "response_format", "confidence_format"):
                if rec[key] != scen[key]:
                    raise ValueError(f"scenario mismatch on {key!r}: reload would mix prompts")
            if list(rec["target_tokens"]) != list(scen["target_tokens"]):
                raise ValueError("scenario mismatch on target_tokens")
    return records
