from .irrelevant import (
    generate_perturbations,
    insert_statement,
    num_insertion_positions,
    position_description,
    split_sentences,
)
from .rephrase import (
    REPHRASE_TEMPLATE,
    generate_rephrasings,
    load_perturbations,
    parse_numbered_rephrasings,
    save_perturbations,
)
