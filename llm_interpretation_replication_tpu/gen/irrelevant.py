"""Irrelevant-statement perturbation generator.

Behavioral replica of /root/reference/analysis/perturb_with_irrelevant_statements.py:
split each scenario into sentences on ``(?<=\\.)\\s+``, insert each of the 199
facts at every position (beginning + after each sentence), and emit the
``perturbations_irrelevant.json`` schema (SURVEY.md §2.8): per scenario
``{scenario_name, original_main, response_format, target_tokens,
confidence_format, perturbations_with_irrelevant: [{perturbation_id,
irrelevant_statement, position_index, position_description, perturbed_text}]}``
— 400/400/600/1000/1000 = 3,400 perturbations for the reference scenarios.
"""

from __future__ import annotations

import json
import re
from typing import List, Optional, Sequence


def split_sentences(text: str) -> List[str]:
    """Period-boundary sentence split; every returned sentence ends with '.'"""
    parts = re.split(r"(?<=\.)\s+", text)
    out = []
    for s in parts:
        s = s.strip()
        if not s:
            continue
        if not s.endswith("."):
            s += "."
        out.append(s)
    return out


def num_insertion_positions(text: str) -> int:
    """Beginning + after each sentence."""
    return len([s for s in re.split(r"(?<=\.)\s+", text) if s.strip()]) + 1


def insert_statement(text: str, statement: str, position_index: int) -> str:
    sentences = split_sentences(text)
    if not statement.endswith("."):
        statement += "."
    if position_index <= len(sentences):
        sentences.insert(position_index, statement)
    else:
        sentences.append(statement)
    return " ".join(sentences)


def position_description(position_index: int, num_positions: int) -> str:
    if position_index == 0:
        return "beginning"
    if position_index == num_positions - 1:
        return "end"
    return f"after_sentence_{position_index}"


def generate_perturbations(
    scenarios: Sequence[dict],
    statements: Sequence[str],
    max_per_scenario: Optional[int] = None,
) -> List[dict]:
    """All (position × statement) insertions per scenario, ids starting at 1 —
    ordering and naming match data/perturbations_irrelevant.json exactly."""
    out = []
    for scenario in scenarios:
        main = scenario.get("main") or scenario["original_main"]
        n_positions = num_insertion_positions(main)
        perturbations = []
        pid = 1
        for pos in range(n_positions):
            for statement in statements:
                perturbations.append(
                    {
                        "perturbation_id": pid,
                        "irrelevant_statement": statement,
                        "position_index": pos,
                        "position_description": position_description(pos, n_positions),
                        "perturbed_text": insert_statement(main, statement, pos),
                    }
                )
                pid += 1
                if max_per_scenario and pid > max_per_scenario:
                    break
            if max_per_scenario and pid > max_per_scenario:
                break
        out.append(
            {
                "scenario_name": scenario.get("name") or scenario.get("scenario_name", ""),
                "original_main": main,
                "response_format": scenario["response_format"],
                "target_tokens": scenario["target_tokens"],
                "confidence_format": scenario["confidence_format"],
                "perturbations_with_irrelevant": perturbations,
            }
        )
    return out


def save_perturbations(perturbed: Sequence[dict], path: str) -> None:
    with open(path, "w") as f:
        json.dump(list(perturbed), f, indent=2)


def readable_dump(perturbed: Sequence[dict], generated_at: str = "") -> str:
    """Human-readable companion of the JSON
    (perturb_with_irrelevant_statements.py:204-232's exact layout).

    ``generated_at`` fills the reference's ``Generated:`` timestamp line —
    injectable so tests (and reproducible builds) don't depend on the clock.
    """
    lines = [
        "PERTURBATIONS WITH IRRELEVANT STATEMENTS",
        "=" * 80,
        f"Generated: {generated_at}",
        f"Total scenarios: {len(perturbed)}",
        f"Total perturbations: "
        f"{sum(len(p['perturbations_with_irrelevant']) for p in perturbed)}",
    ]
    for p in perturbed:
        lines.append(f"  {p['scenario_name']}: "
                     f"{len(p['perturbations_with_irrelevant'])} perturbations")
    lines += ["=" * 80, ""]
    for scenario in perturbed:
        lines += [
            "",
            f"SCENARIO: {scenario['scenario_name']}",
            "-" * 60,
            f"ORIGINAL:\n{scenario['original_main']}",
            "",
            f"RESPONSE FORMAT: {scenario['response_format']}",
            f"TARGET TOKENS: {scenario['target_tokens']}",
            "-" * 60,
        ]
        for pert in scenario["perturbations_with_irrelevant"]:
            lines += [
                "",
                f"Perturbation #{pert['perturbation_id']}:",
                f"Irrelevant Statement: {pert['irrelevant_statement']}",
                f"Position: {pert['position_description']} "
                f"(index: {pert['position_index']})",
                f"Perturbed Text:\n{pert['perturbed_text']}",
                "-" * 40,
            ]
        lines += ["", "=" * 80]
    return "\n".join(lines) + "\n"


def save_readable(perturbed: Sequence[dict], path: str,
                  generated_at: str = "") -> None:
    import os

    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        f.write(readable_dump(perturbed, generated_at))


def load_perturbations(path: str) -> List[dict]:
    with open(path) as f:
        return json.load(f)
