"""Typed run configuration + experiment-asset registry.

Replaces the reference's constants-at-top-of-file config style (SURVEY.md §5)
with one typed config carrying the ``device='tpu'|'cpu'`` switch BASELINE.json
specifies, and gives programmatic access to the experiment materials
(scenarios, question lists, model rosters) extracted from the reference into
``data_assets/``.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import List, Optional, Sequence

# Single source of truth for the sweep's length buckets; runtime/batching
# re-exports it.  Lives here (stdlib-only module) so importing config never
# pulls in the jax-heavy runtime package.  Two hot zones, each step 16:
# 64-256 covers the 10k-perturbation corpus (real rephrasing prompts
# tokenize to 60-203, mean ~107 — on that histogram the full step-16 menu
# with length-sorted batch formation pads x1.13 vs x1.23 for the coarser
# r04 menu, ~8% of all device FLOPs), and 400-448 covers the 100q few-shot
# shape (~430 tokens pads to 432 — measured +1.2% over the 448 bucket and
# +13% over 512 on v5e; see runtime/batching.py).  Every bucket is a
# multiple of 16 so VPU/MXU sublane tiling stays aligned; with grouped
# batching, near-empty buckets merge upward at batch time
# (batches_for_prompts min_bucket_rows) so a stray length never costs a
# compile; with length-sorted batching a bucket is only compiled when a
# whole batch's quantized max lands on it.
DEFAULT_BUCKETS = (64, 80, 96, 112, 128, 144, 160, 176, 192, 208, 224, 240,
                   256, 320, 384, 416, 432, 448,
                   512, 640, 768, 1024, 1536, 2048)
# Step 16 is the FINEST menu every attention path accepts: the Pallas
# grouped/flash kernels require S % 16 == 0 (ops/attention.py dispatch),
# so a step-8 hot zone would silently drop the bf16 flash escape hatch
# (the ONLY working bf16-7B path) to dense attention and OOM.  Step 8 was
# measured anyway on the int8/dense sweep (r5): padding x1.093 vs x1.129,
# but only ~+0.5-1% e2e (121.4 vs 120.5-120.9 p/s warm at batch 320) —
# saved padding converts sublinearly because shorter buckets also lower
# per-token device efficiency in the short-seq regime (PARITY.md MFU
# table: the MLP fusion epilogue amortizes over rows-per-tile).  Tested
# and rejected: the invariant is worth more than the half-percent.

_ASSETS = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "data_assets")


def _load(name: str):
    with open(os.path.join(_ASSETS, name)) as f:
        return json.load(f)


# ---------------------------------------------------------------------------
# Experiment assets (data contracts from the reference)
# ---------------------------------------------------------------------------

def legal_scenarios() -> List[dict]:
    """5 scenarios of the prompt-sensitivity study: original_main,
    response_format, target_tokens[2], confidence_format
    (perturb_prompts.py:728-734)."""
    return _load("legal_scenarios.json")


def irrelevant_scenarios() -> List[dict]:
    """5 scenarios (simpler target tokens) of the irrelevant-insertion study
    (perturb_with_irrelevant_statements.py:22-58)."""
    return _load("irrelevant_scenarios.json")


def irrelevant_statements() -> List[str]:
    """199 factual statements (data/irrelevant_statements.txt)."""
    path = os.path.join(_ASSETS, "irrelevant_statements.txt")
    with open(path) as f:
        return [line.strip() for line in f if line.strip()]


def power_pilot_results() -> dict:
    """Pilot MAE results the reference hardcodes for its power analysis
    (power_analysis.py:103-132): baseline_mae, sample_size, per-model
    mae/mae_std/mae_diff/CI."""
    return _load("power_pilot_results.json")


def decided_rate_calibration() -> dict:
    """Empirical position-0 decided-rate evidence behind the bench's
    synthetic-weight calibration targets (ROADMAP item 4): the reference
    workbooks' answer-start floor, the checked-in rounds' measured
    calibrated rates, and the [0.87, 0.92] target bracket the EOS-typical
    decode bracket reuses (bench.DECIDED_RATE_TARGETS)."""
    return _load("decided_rate_calibration.json")


def ordinary_meaning_questions() -> List[str]:
    """The 100 ordinary-meaning questions (survey 1 + survey 2 —
    run_base_vs_instruct_100q.py:120-231)."""
    q = _load("ordinary_meaning_questions.json")
    return q["survey1"] + q["survey2"]


def model_pairs_100q() -> List[dict]:
    """6 base/instruct pairs of the 100q sweep (run_base_vs_instruct_100q.py:88-115)."""
    return _load("model_pairs_100q.json")


def model_pairs_word_meaning() -> List[dict]:
    """base/instruct pairs of the word-meaning sweep (compare_base_vs_instruct.py:136-180)."""
    return _load("model_pairs_word_meaning.json")


def instruct_sweep_models() -> List[str]:
    """10-model instruct roster (compare_instruct_models.py:145-166)."""
    return _load("instruct_sweep_models.json")


def api_models() -> dict:
    """Frontier-API model roster + pricing (perturb_prompts.py:37-65)."""
    return _load("api_models.json")


def irrelevant_eval_models() -> dict:
    """Models of the irrelevant-perturbation evaluation
    (evaluate_irrelevant_perturbations.py:41-57)."""
    return _load("irrelevant_eval_models.json")


# ---------------------------------------------------------------------------
# Run configuration
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class RunConfig:
    """One typed config for local-model sweeps."""

    device: str = "tpu"                  # 'tpu' | 'cpu'
    dtype: str = "bfloat16"              # params/compute dtype on device
    quant: str = "none"                  # 'none' | 'int8' (w8a8, decoder-only;
                                         # the TPU answer to the reference's
                                         # bitsandbytes load_in_8bit)
    kv_dtype: str = "bf16"               # 'bf16' | 'int8' decode-time KV cache
                                         # storage (per-head scales, quantize-
                                         # on-append — runtime/engine kv_dtype)
    prefill_chunk: int = 0               # > 0: chunked prefill threshold/size
                                         # (models/decoder.chunked_prefill)
    pooled_confidence: bool = True       # confidence-leg decode through the
                                         # leg-parameterized cross-batch pool
                                         # (early-exit retirement + cache
                                         # streaming — runtime/engine
                                         # EngineConfig.pooled_confidence)
    phase2_pool_target: int = 0          # rows per pooled decode (binary +
                                         # confidence pools); 0 = batch_size
    slot_repack: bool = True             # decode-then-repack slot ring
                                         # (runtime/slots.py): retired pool
                                         # lanes refill mid-decode; False =
                                         # the legacy whole-flush schedule
    decode_k: int = 1                    # joint next-K-token decode block
                                         # size (verify-and-accept —
                                         # runtime/engine EngineConfig.
                                         # decode_k); 1 = sequential

    plan_search: bool = False            # auto-parallel plan search (runtime/
                                         # plan_search.py): pick batch/
                                         # kv-dtype/prefill-chunk/mesh from
                                         # the budget + cost model instead of
                                         # the flags; the engine's OOM
                                         # back-off ladder stays armed as the
                                         # safety net when prediction misses
    attention_impl: str = "xla"          # 'xla' | 'flash' | 'auto' (dense up
                                         # to 1k tokens, Pallas kernel beyond
                                         # — models/config.DecoderConfig)
    mesh_data: Optional[int] = None      # None = all remaining devices
    mesh_model: int = 1
    mesh_seq: int = 1
    batch_size: int = 32
    max_new_tokens: int = 50
    max_look_ahead: int = 10
    top_k: int = 5
    buckets: Sequence[int] = DEFAULT_BUCKETS
    checkpoint_dir: str = "checkpoints"  # local HF snapshots root
    output_dir: str = "results"
    seed: int = 42

    def resolve_dtype(self):
        import jax.numpy as jnp

        return {"bfloat16": jnp.bfloat16, "float32": jnp.float32, "float16": jnp.float16}[self.dtype]

    def make_mesh(self):
        from ..parallel import make_mesh

        return make_mesh(data=self.mesh_data, model=self.mesh_model, seq=self.mesh_seq)

    def snapshot_path(self, model_name: str) -> str:
        """Local snapshot dir for a HF model id (zero-egress: must exist)."""
        flat = model_name.replace("/", "--")
        candidates = [
            os.path.join(self.checkpoint_dir, model_name),
            os.path.join(self.checkpoint_dir, flat),
            os.path.join(self.checkpoint_dir, f"models--{flat}", "snapshots"),
        ]
        for c in candidates:
            if os.path.isdir(c):
                if c.endswith("snapshots"):
                    subs = sorted(os.listdir(c))
                    if subs:
                        return os.path.join(c, subs[0])
                    continue
                return c
        raise FileNotFoundError(
            f"no local snapshot for {model_name!r} under {self.checkpoint_dir}"
        )
