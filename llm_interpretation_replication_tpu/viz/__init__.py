from . import figures, latex
