"""Figure writers (reference L5 presentation layer).

Covers the reference's figure families (all Agg/matplotlib, saved as PNG):
- probability / confidence histograms (analyze_perturbation_results.py:623-722)
- QQ plots vs a fitted normal with 95% point bands (:499-622)
- clipped-normal model overlay (:340-498)
- combined per-scenario jitter-strip panels (:912-1094, the paper's Fig. 5/6)
- MAE heatmap and per-question error strips (evaluate_closed_source_models.py:
  1376-1586)
- violin plots for irrelevant-perturbation consistency
  (evaluate_irrelevant_perturbations.py:503-941)
- correlation heatmap + distribution histogram (model_comparison_graph.py:389-494)
"""

from __future__ import annotations

import os
from typing import Dict, Optional, Sequence

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt  # noqa: E402
import numpy as np  # noqa: E402
from scipy import stats as scipy_stats  # noqa: E402


def _save(fig, output_path: str) -> str:
    os.makedirs(os.path.dirname(os.path.abspath(output_path)), exist_ok=True)
    fig.savefig(output_path, dpi=150, bbox_inches="tight")
    plt.close(fig)
    return output_path


def probability_histogram(values, title: str, output_path: str, bins: int = 50,
                          xlabel: str = "Relative probability") -> Optional[str]:
    values = np.asarray(values, float)
    values = values[np.isfinite(values)]
    if values.size == 0:
        return None
    fig, ax = plt.subplots(figsize=(10, 6))
    ax.hist(values, bins=bins, range=(0, 1), edgecolor="black", alpha=0.75)
    ax.set_xlabel(xlabel)
    ax.set_ylabel("Count")
    ax.set_title(title)
    ax.set_xlim(0, 1)
    return _save(fig, output_path)


def qq_plot(values, title: str, output_path: str) -> Optional[str]:
    """QQ plot vs fitted normal + histogram-with-fit side panel."""
    values = np.asarray(values, float)
    values = values[np.isfinite(values)]
    if values.size < 2:
        return None
    mu, sigma = scipy_stats.norm.fit(values)
    n = values.size
    ordered = np.sort(values)
    positions = (np.arange(1, n + 1) - 0.5) / n
    theoretical = scipy_stats.norm.ppf(positions)
    fig, (ax1, ax2) = plt.subplots(1, 2, figsize=(16, 8))
    ax1.scatter(theoretical, ordered, s=12, alpha=0.6)
    if np.var(ordered) > 0:
        slope, intercept = np.polyfit(theoretical, ordered, 1)
    else:
        slope, intercept = 0.0, ordered[0]
    xs = np.array([theoretical.min(), theoretical.max()])
    ax1.plot(xs, slope * xs + intercept, "r--", label="best fit")
    # pointwise 95% band via order-statistic std approximation
    band = 1.96 * sigma * np.sqrt(positions * (1 - positions) / n) / np.maximum(
        scipy_stats.norm.pdf(theoretical), 1e-6
    )
    ax1.fill_between(theoretical, slope * theoretical + intercept - band,
                     slope * theoretical + intercept + band, alpha=0.15)
    ax1.set_xlabel("Theoretical quantiles")
    ax1.set_ylabel("Ordered values")
    ax1.set_title(f"QQ plot — {title}")
    ax1.legend()
    ax2.hist(values, bins=40, density=True, alpha=0.6, edgecolor="black")
    grid = np.linspace(values.min() - 0.05, values.max() + 0.05, 200)
    ax2.plot(grid, scipy_stats.norm.pdf(grid, mu, sigma), "r-",
             label=f"N({mu:.3f}, {sigma:.3f})")
    ax2.set_title("Histogram with fitted normal")
    ax2.legend()
    return _save(fig, output_path)


def truncated_model_plot(values, simulated, title: str, output_path: str,
                         ks_statistic: Optional[float] = None) -> Optional[str]:
    values = np.asarray(values, float)
    values = values[np.isfinite(values)]
    simulated = np.asarray(simulated, float)
    if values.size == 0 or simulated.size == 0:
        return None
    fig, (ax1, ax2) = plt.subplots(1, 2, figsize=(16, 6))
    bins = np.linspace(0, 1, 41)
    ax1.hist(values, bins=bins, density=True, alpha=0.55, label="observed",
             edgecolor="black")
    ax1.hist(simulated, bins=bins, density=True, alpha=0.4, label="clipped-normal model")
    ax1.set_title(title + (f" (KS={ks_statistic:.3f})" if ks_statistic is not None else ""))
    ax1.legend()
    # empirical CDFs
    for arr, label in ((values, "observed"), (simulated, "model")):
        xs = np.sort(arr)
        ax2.plot(xs, np.arange(1, xs.size + 1) / xs.size, label=label)
    ax2.set_title("Empirical CDFs")
    ax2.legend()
    return _save(fig, output_path)


def jitter_strip_panels(
    per_scenario_values: Dict[str, Sequence[float]],
    title: str,
    output_path: str,
    ylabel: str = "Relative probability",
    ylim=(0, 1),
    seed: int = 42,
) -> str:
    """One jittered strip per scenario with mean ± 95% CI markers (the
    Figure 5/6 style)."""
    rng = np.random.default_rng(seed)
    names = list(per_scenario_values)
    fig, ax = plt.subplots(figsize=(max(8, 2.2 * len(names)), 6))
    for i, name in enumerate(names):
        vals = np.asarray(per_scenario_values[name], float)
        vals = vals[np.isfinite(vals)]
        if vals.size == 0:
            continue
        x = i + rng.uniform(-0.18, 0.18, vals.size)
        ax.scatter(x, vals, s=6, alpha=0.25)
        mean = vals.mean()
        lo, hi = np.percentile(vals, [2.5, 97.5])
        ax.errorbar([i], [mean], yerr=[[mean - lo], [hi - mean]], fmt="o",
                    color="black", capsize=5, markersize=7, zorder=5)
    ax.set_xticks(range(len(names)))
    ax.set_xticklabels(names, rotation=20, ha="right")
    ax.set_ylabel(ylabel)
    if ylim:
        ax.set_ylim(*ylim)
    ax.set_title(title)
    return _save(fig, output_path)


def mae_heatmap(mae_matrix, row_labels: Sequence[str], col_labels: Sequence[str],
                title: str, output_path: str) -> str:
    mat = np.asarray(mae_matrix, float)
    fig, ax = plt.subplots(figsize=(max(8, 0.3 * len(col_labels)), max(4, 0.5 * len(row_labels))))
    im = ax.imshow(mat, aspect="auto", cmap="RdYlGn_r")
    ax.set_xticks(range(len(col_labels)))
    ax.set_xticklabels(col_labels, rotation=90, fontsize=6)
    ax.set_yticks(range(len(row_labels)))
    ax.set_yticklabels(row_labels)
    fig.colorbar(im, ax=ax, label="Absolute error")
    ax.set_title(title)
    return _save(fig, output_path)


def per_question_error_strip(errors_by_model: Dict[str, Sequence[float]],
                             title: str, output_path: str) -> str:
    names = list(errors_by_model)
    fig, ax = plt.subplots(figsize=(10, 6))
    rng = np.random.default_rng(42)
    for i, name in enumerate(names):
        vals = np.asarray(errors_by_model[name], float)
        vals = vals[np.isfinite(vals)]
        x = i + rng.uniform(-0.15, 0.15, vals.size)
        ax.scatter(x, vals, s=10, alpha=0.5)
        ax.plot([i - 0.25, i + 0.25], [vals.mean()] * 2, color="black", lw=2)
    ax.set_xticks(range(len(names)))
    ax.set_xticklabels(names, rotation=20, ha="right")
    ax.set_ylabel("Absolute error vs human mean")
    ax.set_title(title)
    return _save(fig, output_path)


def violin_by_group(values_by_group: Dict[str, Sequence[float]], title: str,
                    output_path: str, ylabel: str = "Confidence") -> Optional[str]:
    names = [k for k, v in values_by_group.items() if len(v)]
    data = [np.asarray(values_by_group[k], float) for k in names]
    data = [d[np.isfinite(d)] for d in data]
    if not data:
        return None
    fig, ax = plt.subplots(figsize=(max(8, 1.6 * len(names)), 6))
    ax.violinplot(data, showmeans=True, showextrema=True)
    ax.set_xticks(range(1, len(names) + 1))
    ax.set_xticklabels(names, rotation=20, ha="right")
    ax.set_ylabel(ylabel)
    ax.set_title(title)
    return _save(fig, output_path)


def stacked_violin_panels(
    values_by_panel: Dict[str, Dict[str, Sequence[float]]],
    output_path: str,
    group_order: Optional[Sequence[str]] = None,
    ylabel: str = "Confidence (0-100)",
    xlabel: str = "Prompt Number",
    ylim=(0, 100),
    refline: Optional[float] = 50.0,
    seed: int = 42,
) -> str:
    """Vertically stacked per-model violin+jitter panels — the irrelevant-
    insertion study's ``three_model_stacked_visualization.png``
    (evaluate_irrelevant_perturbations.py:803-941): one subplot per panel
    (model), scenarios as numbered x positions with a consistent color per
    scenario across panels, jittered points, black mean dot, and capped
    2.5/97.5-percentile error bars.
    """
    panels = list(values_by_panel)
    groups = list(group_order) if group_order is not None else sorted(
        {g for per_group in values_by_panel.values() for g in per_group}
    )
    colors = plt.rcParams["axes.prop_cycle"].by_key()["color"]
    fig, axes = plt.subplots(len(panels), 1,
                             figsize=(14, 5.6 * len(panels)), squeeze=False)
    for pi, panel in enumerate(panels):
        ax = axes[pi][0]
        per_group = values_by_panel[panel]
        pos = 0
        ticks, labels = [], []
        for gi, group in enumerate(groups):
            vals = np.asarray(per_group.get(group, []), float)
            vals = vals[np.isfinite(vals)]
            if vals.size == 0:
                continue
            pos += 1
            ticks.append(pos)
            labels.append(str(gi + 1))
            color = colors[gi % len(colors)]
            parts = ax.violinplot([vals], [pos], widths=0.3, showmeans=False,
                                  showmedians=False, showextrema=False)
            for pc in parts["bodies"]:
                pc.set_facecolor(color)
                pc.set_edgecolor("none")
                pc.set_alpha(0.3)
            rng = np.random.default_rng(seed + gi)
            ax.scatter(rng.normal(pos, 0.08, vals.size), vals, alpha=0.4,
                       s=30, color=color)
            mean = vals.mean()
            lo, hi = np.percentile(vals, [2.5, 97.5])
            ax.scatter([pos], [mean], color="black", s=80, zorder=5)
            ax.plot([pos, pos], [lo, hi], color="black", lw=2, zorder=4)
            for y in (lo, hi):
                ax.plot([pos - 0.1, pos + 0.1], [y, y], color="black", lw=2,
                        zorder=4)
        if pos == 0:
            ax.text(0.5, 0.5, f"No data available for {panel}",
                    transform=ax.transAxes, ha="center", va="center",
                    fontsize=14)
            ax.set_xlim(0, len(groups) + 1)
        else:
            ax.set_xticks(ticks)
            ax.set_xticklabels(labels, fontsize=14)
            if refline is not None:
                ax.axhline(y=refline, color="gray", linestyle="--", alpha=0.7)
        ax.tick_params(axis="y", labelsize=14)
        ax.set_ylabel(ylabel, fontsize=16)
        if ylim:
            ax.set_ylim(*ylim)
        ax.set_title(panel, fontsize=18, fontweight="bold", pad=10)
        if pi == len(panels) - 1:
            ax.set_xlabel(xlabel, fontsize=16)
    fig.tight_layout()
    return _save(fig, output_path)


def _mae_bars(ax, human_comparisons: Dict, capsize: int = 5) -> None:
    """Shared MAE-vs-baselines bar panel (evaluate_closed_source_models.py:
    1690-1780 and the standalone figure :1832-1901): per-model MAE with
    asymmetric bootstrap-CI error bars, then the Always-50 and N(μ,σ)
    baselines in grey/cyan."""
    models = human_comparisons.get("models", human_comparisons)
    baselines = human_comparisons.get("baselines", {})
    labels, values, lo_err, hi_err, colors = [], [], [], [], []

    def push(record, label, color):
        mae = record.get("mae")
        if mae is None or not np.isfinite(mae):
            return
        labels.append(label)
        values.append(mae)
        lo = record.get("mae_ci_lower", record.get("ci_lower"))
        hi = record.get("mae_ci_upper", record.get("ci_upper"))
        if lo is not None and hi is not None and np.isfinite(lo) and np.isfinite(hi):
            lo_err.append(max(mae - lo, 0.0))
            hi_err.append(max(hi - mae, 0.0))
        else:
            std = record.get("std", record.get("mae_std", 0.0)) or 0.0
            lo_err.append(std)
            hi_err.append(std)
        colors.append(color)

    palette = {"gpt": "#1f77b4", "gemini": "#2ca02c", "claude": "#d62728"}
    for name, record in models.items():
        push(record, str(name), palette.get(str(name).lower(), "#9467bd"))
    if "always_50" in baselines:
        push(baselines["always_50"], "Always 50%", "#808080")
    if "normal_human" in baselines:
        rec = baselines["normal_human"]
        mu, sd = rec.get("human_mean"), rec.get("human_std")
        if mu is None or sd is None:
            label = "N(human)"
        else:
            # confidences are 0-100, relative probabilities 0-1: pick digits
            fmt = ".0f" if mu > 1 else ".2f"
            label = f"N({mu:{fmt}},{sd:{fmt}})"
        push(rec, label, "#17becf")
    if not values:
        ax.axis("off")
        return
    x = np.arange(len(values))
    ax.bar(x, values, yerr=np.array([lo_err, hi_err]), capsize=capsize,
           alpha=0.7, color=colors)
    for i, mae in enumerate(values):
        ax.text(i, mae + hi_err[i] + 0.01, f"{mae:.3f}", ha="center")
    ax.set_xticks(x)
    ax.set_xticklabels(labels, rotation=45, ha="right")
    ax.set_ylabel("Mean Absolute Error")
    ax.set_title("MAE vs human assessments (lower is better)")
    ax.grid(axis="y", alpha=0.3)


def model_comparison_dashboard(
    df,
    correlations: Optional[Dict] = None,
    human_comparisons: Optional[Dict] = None,
    output_path: str = "model_comparison_plots.png",
) -> str:
    """The closed-source evaluation dashboard (evaluate_closed_source_models.py
    `create_visualizations`, :1587-1830): GPT-vs-Gemini scatter, per-model
    confidence histograms, binary-agreement heatmap, response-count bars, and
    — when human comparisons exist — the MAE bar chart, a correlation summary
    card, and confidence boxplots."""
    correlations = correlations or {}
    with_humans = bool(human_comparisons)
    nrows = 3 if with_humans else 2
    fig, axes = plt.subplots(nrows, 3, figsize=(18, 4.7 * nrows))

    ax = axes[0, 0]
    if {"gpt_relative_prob", "gemini_relative_prob"} <= set(df.columns):
        sub = df[["gpt_relative_prob", "gemini_relative_prob"]].dropna()
        ax.scatter(sub["gpt_relative_prob"], sub["gemini_relative_prob"], alpha=0.6)
        ax.plot([0, 1], [0, 1], "r--", alpha=0.5)
        ax.set_xlabel("GPT relative probability")
        ax.set_ylabel("Gemini relative probability")
        rho = correlations.get("gpt_relative_prob__gemini_relative_prob", {})
        ax.set_title(f"GPT vs Gemini (ρ={rho.get('pearson', float('nan')):.3f})")
    else:
        ax.axis("off")

    hist_specs = [
        (axes[0, 1], "gpt_weighted_confidence", "GPT weighted confidence"),
        (axes[0, 2], "gemini_weighted_confidence", "Gemini weighted confidence"),
        (axes[1, 0], "claude_confidence", "Claude confidence"),
    ]
    for ax, col, title in hist_specs:
        vals = df[col].dropna() if col in df.columns else []
        if len(vals):
            ax.hist(vals, bins=20, edgecolor="black", alpha=0.7)
            ax.axvline(np.mean(vals), color="red", linestyle="--",
                       label=f"mean: {np.mean(vals):.1f}")
            ax.legend()
            ax.set_xlabel("Confidence")
            ax.set_ylabel("Frequency")
        ax.set_title(title)

    ax = axes[1, 1]
    names = ["gpt", "gemini", "claude"]
    cols = [f"{n}_response" for n in names]
    if all(c in df.columns for c in cols):
        agree = np.eye(3)
        for i, a in enumerate(cols):
            for j, b in enumerate(cols):
                if i != j:
                    sub = df[[a, b]].dropna()
                    agree[i, j] = (sub[a] == sub[b]).mean() if len(sub) else np.nan
        ax.imshow(agree, cmap="coolwarm", vmin=0, vmax=1)
        ax.set_xticks(range(3)), ax.set_yticks(range(3))
        ax.set_xticklabels(names), ax.set_yticklabels(names)
        for i in range(3):
            for j in range(3):
                if np.isfinite(agree[i, j]):
                    ax.text(j, i, f"{agree[i, j]:.2f}", ha="center", va="center",
                            color="white" if agree[i, j] < 0.5 else "black")
        ax.set_title("Binary-response agreement")
    else:
        ax.axis("off")

    ax = axes[1, 2]
    counts = {n: df[f"{n}_response"].value_counts()
              for n in names if f"{n}_response" in df.columns}
    if counts:
        table = np.array([[c.get(v, 0) for v in ("Yes", "No")] for c in counts.values()])
        x = np.arange(len(counts))
        ax.bar(x - 0.18, table[:, 0], width=0.36, label="Yes")
        ax.bar(x + 0.18, table[:, 1], width=0.36, label="No")
        ax.set_xticks(x)
        ax.set_xticklabels(list(counts), rotation=45, ha="right")
        ax.set_ylabel("Count")
        ax.set_title("Response distribution by model")
        ax.legend()
    else:
        ax.axis("off")

    if with_humans:
        _mae_bars(axes[2, 0], human_comparisons)

        ax = axes[2, 1]
        ax.axis("off")
        models = human_comparisons.get("models", human_comparisons)
        lines = ["Model-human correlations:", ""]
        for name, record in models.items():
            corr = record.get("correlation")
            if corr is None:
                continue
            lines.append(f"{name}:")
            lines.append(f"  correlation: {corr:.3f}")
            if record.get("p_value") is not None:
                lines.append(f"  p-value: {record['p_value']:.4f}")
            if record.get("n_matched") is not None:
                lines.append(f"  n matched: {record['n_matched']}")
            lines.append("")
        ax.text(0.05, 0.5, "\n".join(lines), fontsize=11, va="center",
                family="monospace")
        ax.set_title("Model-human correlations")

        ax = axes[2, 2]
        box_cols = [("gpt", "gpt_weighted_confidence"),
                    ("gemini", "gemini_weighted_confidence"),
                    ("claude", "claude_confidence")]
        data, labels = [], []
        for name, col in box_cols:
            vals = df[col].dropna() if col in df.columns else []
            if len(vals):
                data.append(np.asarray(vals, float))
                labels.append(name)
        if data:
            bp = ax.boxplot(data, tick_labels=labels, patch_artist=True)
            for patch, color in zip(bp["boxes"], ["lightblue", "lightgreen", "lightcoral"]):
                patch.set_facecolor(color)
            ax.set_ylabel("Confidence")
            ax.set_title("Confidence distributions")
            ax.grid(axis="y", alpha=0.3)
        else:
            ax.axis("off")

    fig.tight_layout()
    return _save(fig, output_path)


def mae_comparison_bar(human_comparisons: Dict, output_path: str) -> str:
    """Standalone high-quality MAE comparison chart
    (evaluate_closed_source_models.py:1832-1901)."""
    fig, ax = plt.subplots(figsize=(10, 6))
    _mae_bars(ax, human_comparisons, capsize=10)
    baselines = human_comparisons.get("baselines", {})
    if "always_50" in baselines and baselines["always_50"].get("mae") is not None:
        ax.axhline(y=baselines["always_50"]["mae"], color="gray", linestyle="--",
                   alpha=0.3)
    return _save(fig, output_path)


def correlation_heatmap(corr_matrix, labels: Sequence[str], title: str,
                        output_path: str) -> str:
    mat = np.asarray(corr_matrix, float)
    fig, ax = plt.subplots(figsize=(1 + 0.7 * len(labels), 1 + 0.6 * len(labels)))
    im = ax.imshow(mat, vmin=-1, vmax=1, cmap="coolwarm")
    ax.set_xticks(range(len(labels)))
    ax.set_xticklabels(labels, rotation=90, fontsize=7)
    ax.set_yticks(range(len(labels)))
    ax.set_yticklabels(labels, fontsize=7)
    for i in range(len(labels)):
        for j in range(len(labels)):
            if np.isfinite(mat[i, j]):
                ax.text(j, i, f"{mat[i, j]:.2f}", ha="center", va="center", fontsize=6)
    fig.colorbar(im, ax=ax)
    ax.set_title(title)
    return _save(fig, output_path)


def correlation_distribution(correlations, title: str, output_path: str) -> str:
    vals = np.asarray(correlations, float)
    fig, ax = plt.subplots(figsize=(8, 5))
    ax.hist(vals, bins=20, edgecolor="black", alpha=0.75)
    ax.axvline(vals.mean(), color="red", linestyle="--",
               label=f"mean = {vals.mean():.3f}")
    ax.set_xlabel("Pairwise Pearson correlation")
    ax.set_ylabel("Count")
    ax.set_title(title)
    ax.legend()
    return _save(fig, output_path)
