"""Figure writers (reference L5 presentation layer).

Covers the reference's figure families (all Agg/matplotlib, saved as PNG):
- probability / confidence histograms (analyze_perturbation_results.py:623-722)
- QQ plots vs a fitted normal with 95% point bands (:499-622)
- clipped-normal model overlay (:340-498)
- combined per-scenario jitter-strip panels (:912-1094, the paper's Fig. 5/6)
- MAE heatmap and per-question error strips (evaluate_closed_source_models.py:
  1376-1586)
- violin plots for irrelevant-perturbation consistency
  (evaluate_irrelevant_perturbations.py:503-941)
- correlation heatmap + distribution histogram (model_comparison_graph.py:389-494)
"""

from __future__ import annotations

import os
from typing import Dict, Optional, Sequence

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt  # noqa: E402
import numpy as np  # noqa: E402
from scipy import stats as scipy_stats  # noqa: E402


def _save(fig, output_path: str) -> str:
    os.makedirs(os.path.dirname(os.path.abspath(output_path)), exist_ok=True)
    fig.savefig(output_path, dpi=150, bbox_inches="tight")
    plt.close(fig)
    return output_path


def probability_histogram(values, title: str, output_path: str, bins: int = 50,
                          xlabel: str = "Relative probability") -> Optional[str]:
    values = np.asarray(values, float)
    values = values[np.isfinite(values)]
    if values.size == 0:
        return None
    fig, ax = plt.subplots(figsize=(10, 6))
    ax.hist(values, bins=bins, range=(0, 1), edgecolor="black", alpha=0.75)
    ax.set_xlabel(xlabel)
    ax.set_ylabel("Count")
    ax.set_title(title)
    ax.set_xlim(0, 1)
    return _save(fig, output_path)


def qq_plot(values, title: str, output_path: str) -> Optional[str]:
    """QQ plot vs fitted normal + histogram-with-fit side panel."""
    values = np.asarray(values, float)
    values = values[np.isfinite(values)]
    if values.size < 2:
        return None
    mu, sigma = scipy_stats.norm.fit(values)
    n = values.size
    ordered = np.sort(values)
    positions = (np.arange(1, n + 1) - 0.5) / n
    theoretical = scipy_stats.norm.ppf(positions)
    fig, (ax1, ax2) = plt.subplots(1, 2, figsize=(16, 8))
    ax1.scatter(theoretical, ordered, s=12, alpha=0.6)
    if np.var(ordered) > 0:
        slope, intercept = np.polyfit(theoretical, ordered, 1)
    else:
        slope, intercept = 0.0, ordered[0]
    xs = np.array([theoretical.min(), theoretical.max()])
    ax1.plot(xs, slope * xs + intercept, "r--", label="best fit")
    # pointwise 95% band via order-statistic std approximation
    band = 1.96 * sigma * np.sqrt(positions * (1 - positions) / n) / np.maximum(
        scipy_stats.norm.pdf(theoretical), 1e-6
    )
    ax1.fill_between(theoretical, slope * theoretical + intercept - band,
                     slope * theoretical + intercept + band, alpha=0.15)
    ax1.set_xlabel("Theoretical quantiles")
    ax1.set_ylabel("Ordered values")
    ax1.set_title(f"QQ plot — {title}")
    ax1.legend()
    ax2.hist(values, bins=40, density=True, alpha=0.6, edgecolor="black")
    grid = np.linspace(values.min() - 0.05, values.max() + 0.05, 200)
    ax2.plot(grid, scipy_stats.norm.pdf(grid, mu, sigma), "r-",
             label=f"N({mu:.3f}, {sigma:.3f})")
    ax2.set_title("Histogram with fitted normal")
    ax2.legend()
    return _save(fig, output_path)


def truncated_model_plot(values, simulated, title: str, output_path: str,
                         ks_statistic: Optional[float] = None) -> Optional[str]:
    values = np.asarray(values, float)
    values = values[np.isfinite(values)]
    simulated = np.asarray(simulated, float)
    if values.size == 0 or simulated.size == 0:
        return None
    fig, (ax1, ax2) = plt.subplots(1, 2, figsize=(16, 6))
    bins = np.linspace(0, 1, 41)
    ax1.hist(values, bins=bins, density=True, alpha=0.55, label="observed",
             edgecolor="black")
    ax1.hist(simulated, bins=bins, density=True, alpha=0.4, label="clipped-normal model")
    ax1.set_title(title + (f" (KS={ks_statistic:.3f})" if ks_statistic is not None else ""))
    ax1.legend()
    # empirical CDFs
    for arr, label in ((values, "observed"), (simulated, "model")):
        xs = np.sort(arr)
        ax2.plot(xs, np.arange(1, xs.size + 1) / xs.size, label=label)
    ax2.set_title("Empirical CDFs")
    ax2.legend()
    return _save(fig, output_path)


def jitter_strip_panels(
    per_scenario_values: Dict[str, Sequence[float]],
    title: str,
    output_path: str,
    ylabel: str = "Relative probability",
    ylim=(0, 1),
    seed: int = 42,
) -> str:
    """One jittered strip per scenario with mean ± 95% CI markers (the
    Figure 5/6 style)."""
    rng = np.random.default_rng(seed)
    names = list(per_scenario_values)
    fig, ax = plt.subplots(figsize=(max(8, 2.2 * len(names)), 6))
    for i, name in enumerate(names):
        vals = np.asarray(per_scenario_values[name], float)
        vals = vals[np.isfinite(vals)]
        if vals.size == 0:
            continue
        x = i + rng.uniform(-0.18, 0.18, vals.size)
        ax.scatter(x, vals, s=6, alpha=0.25)
        mean = vals.mean()
        lo, hi = np.percentile(vals, [2.5, 97.5])
        ax.errorbar([i], [mean], yerr=[[mean - lo], [hi - mean]], fmt="o",
                    color="black", capsize=5, markersize=7, zorder=5)
    ax.set_xticks(range(len(names)))
    ax.set_xticklabels(names, rotation=20, ha="right")
    ax.set_ylabel(ylabel)
    if ylim:
        ax.set_ylim(*ylim)
    ax.set_title(title)
    return _save(fig, output_path)


def mae_heatmap(mae_matrix, row_labels: Sequence[str], col_labels: Sequence[str],
                title: str, output_path: str) -> str:
    mat = np.asarray(mae_matrix, float)
    fig, ax = plt.subplots(figsize=(max(8, 0.3 * len(col_labels)), max(4, 0.5 * len(row_labels))))
    im = ax.imshow(mat, aspect="auto", cmap="RdYlGn_r")
    ax.set_xticks(range(len(col_labels)))
    ax.set_xticklabels(col_labels, rotation=90, fontsize=6)
    ax.set_yticks(range(len(row_labels)))
    ax.set_yticklabels(row_labels)
    fig.colorbar(im, ax=ax, label="Absolute error")
    ax.set_title(title)
    return _save(fig, output_path)


def per_question_error_strip(errors_by_model: Dict[str, Sequence[float]],
                             title: str, output_path: str) -> str:
    names = list(errors_by_model)
    fig, ax = plt.subplots(figsize=(10, 6))
    rng = np.random.default_rng(42)
    for i, name in enumerate(names):
        vals = np.asarray(errors_by_model[name], float)
        vals = vals[np.isfinite(vals)]
        x = i + rng.uniform(-0.15, 0.15, vals.size)
        ax.scatter(x, vals, s=10, alpha=0.5)
        ax.plot([i - 0.25, i + 0.25], [vals.mean()] * 2, color="black", lw=2)
    ax.set_xticks(range(len(names)))
    ax.set_xticklabels(names, rotation=20, ha="right")
    ax.set_ylabel("Absolute error vs human mean")
    ax.set_title(title)
    return _save(fig, output_path)


def violin_by_group(values_by_group: Dict[str, Sequence[float]], title: str,
                    output_path: str, ylabel: str = "Confidence") -> Optional[str]:
    names = [k for k, v in values_by_group.items() if len(v)]
    data = [np.asarray(values_by_group[k], float) for k in names]
    data = [d[np.isfinite(d)] for d in data]
    if not data:
        return None
    fig, ax = plt.subplots(figsize=(max(8, 1.6 * len(names)), 6))
    ax.violinplot(data, showmeans=True, showextrema=True)
    ax.set_xticks(range(1, len(names) + 1))
    ax.set_xticklabels(names, rotation=20, ha="right")
    ax.set_ylabel(ylabel)
    ax.set_title(title)
    return _save(fig, output_path)


def correlation_heatmap(corr_matrix, labels: Sequence[str], title: str,
                        output_path: str) -> str:
    mat = np.asarray(corr_matrix, float)
    fig, ax = plt.subplots(figsize=(1 + 0.7 * len(labels), 1 + 0.6 * len(labels)))
    im = ax.imshow(mat, vmin=-1, vmax=1, cmap="coolwarm")
    ax.set_xticks(range(len(labels)))
    ax.set_xticklabels(labels, rotation=90, fontsize=7)
    ax.set_yticks(range(len(labels)))
    ax.set_yticklabels(labels, fontsize=7)
    for i in range(len(labels)):
        for j in range(len(labels)):
            if np.isfinite(mat[i, j]):
                ax.text(j, i, f"{mat[i, j]:.2f}", ha="center", va="center", fontsize=6)
    fig.colorbar(im, ax=ax)
    ax.set_title(title)
    return _save(fig, output_path)


def correlation_distribution(correlations, title: str, output_path: str) -> str:
    vals = np.asarray(correlations, float)
    fig, ax = plt.subplots(figsize=(8, 5))
    ax.hist(vals, bins=20, edgecolor="black", alpha=0.75)
    ax.axvline(vals.mean(), color="red", linestyle="--",
               label=f"mean = {vals.mean():.3f}")
    ax.set_xlabel("Pairwise Pearson correlation")
    ax.set_ylabel("Count")
    ax.set_title(title)
    ax.legend()
    return _save(fig, output_path)
