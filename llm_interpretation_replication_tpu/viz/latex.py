"""LaTeX table fragments (reference L5).

Covers: per-scenario summary tables + standalone document
(analyze_perturbation_results.py:723-911), compliance tables (:1453-1718),
and the MAE results tables of the ordinary-meaning study
(evaluate_closed_source_models.py:1136-1330).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np


def _esc(text: str) -> str:
    return (
        str(text)
        .replace("&", "\\&")
        .replace("%", "\\%")
        .replace("_", "\\_")
        .replace("#", "\\#")
    )


def summary_stats_table(values, label: str, caption: str) -> str:
    """Mean / std / percentiles / CI-width summary for one scenario's sweep."""
    vals = np.asarray(values, float)
    vals = vals[np.isfinite(vals)]
    if vals.size == 0:
        rows = [("N", "0")]
    else:
        p2_5, p97_5 = np.percentile(vals, [2.5, 97.5])
        rows = [
            ("N", f"{vals.size}"),
            ("Mean", f"{vals.mean():.3f}"),
            ("Std.\\ dev.", f"{vals.std():.3f}"),
            ("Median", f"{np.median(vals):.3f}"),
            ("2.5th percentile", f"{p2_5:.3f}"),
            ("97.5th percentile", f"{p97_5:.3f}"),
            ("95\\% interval width", f"{p97_5 - p2_5:.3f}"),
        ]
    body = "\n".join(f"{name} & {value} \\\\" for name, value in rows)
    return (
        "\\begin{table}[htbp]\n\\centering\n"
        f"\\caption{{{caption}}}\n\\label{{tab:{label}}}\n"
        "\\begin{tabular}{lr}\n\\hline\n"
        f"{body}\n\\hline\n\\end{{tabular}}\n\\end{{table}}"
    )


def standalone_document(tables: Sequence[str], title: str = "Perturbation analysis") -> str:
    body = "\n\n".join(tables)
    return (
        "\\documentclass{article}\n\\usepackage{booktabs}\n"
        f"\\title{{{_esc(title)}}}\n\\begin{{document}}\n\\maketitle\n"
        f"{body}\n\\end{{document}}\n"
    )


def compliance_table(compliance_df) -> str:
    """First-token / subsequent compliance rates per scenario."""
    lines = [
        "\\begin{tabular}{lrrrr}",
        "\\hline",
        "Prompt & N & First-token \\% & Non-compliant \\% & Subsequent \\% \\\\",
        "\\hline",
    ]
    for _, row in compliance_df.iterrows():
        sub = row.get("Conditional_Subsequent_Compliance_Rate")
        sub_str = f"{sub:.1f}" if sub is not None and np.isfinite(sub) else "--"
        lines.append(
            f"{int(row['Prompt'])} & {int(row['Total_Samples'])} & "
            f"{row['First_Token_Compliance_Rate']:.1f} & "
            f"{row['First_Token_Non_Compliance_Rate']:.1f} & {sub_str} \\\\"
        )
    lines += ["\\hline", "\\end{tabular}"]
    return "\n".join(lines)


def confidence_compliance_table(conf_df) -> str:
    lines = [
        "\\begin{tabular}{lrrrrr}",
        "\\hline",
        "Prompt & N & Compliant \\% & Float & Text & Out-of-range \\\\",
        "\\hline",
    ]
    for _, row in conf_df.iterrows():
        lines.append(
            f"{int(row['Prompt'])} & {int(row['Total_Confidence_Samples'])} & "
            f"{row['Confidence_Compliance_Rate']:.1f} & {int(row['Float_Errors'])} & "
            f"{int(row['Text_Errors'])} & {int(row['Out_Of_Range_Errors'])} \\\\"
        )
    lines += ["\\hline", "\\end{tabular}"]
    return "\n".join(lines)


def mae_results_tables(mae_records: Dict[str, Dict], diff_records: Optional[Dict] = None) -> str:
    """Tables 3/4 style: MAE with CIs per model; differences vs baselines.

    mae_records: name -> {mae, ci_lower, ci_upper}
    diff_records: name -> {baseline -> {diff, ci_lower, ci_upper, p_value}}
    """
    lines = [
        "% Table: MAE vs human mean",
        "\\begin{tabular}{lccc}",
        "\\hline",
        "Model & MAE & \\multicolumn{2}{c}{95\\% CI} \\\\",
        "\\hline",
    ]
    for name, rec in mae_records.items():
        lines.append(
            f"{_esc(name)} & {rec['mae']:.3f} & [{rec['ci_lower']:.3f} & "
            f"{rec['ci_upper']:.3f}] \\\\"
        )
    lines += ["\\hline", "\\end{tabular}"]
    if diff_records:
        lines += [
            "",
            "% Table: MAE differences vs baselines",
            "\\begin{tabular}{llcccc}",
            "\\hline",
            "Model & Baseline & $\\Delta$MAE & CI low & CI high & $p$ \\\\",
            "\\hline",
        ]
        for name, baselines in diff_records.items():
            for bname, rec in baselines.items():
                stars = significance_stars(rec.get("p_value"))
                lines.append(
                    f"{_esc(name)} & {_esc(bname)} & {rec['diff']:+.3f}{stars} & "
                    f"{rec['ci_lower']:.3f} & {rec['ci_upper']:.3f} & "
                    f"{rec['p_value']:.3f} \\\\"
                )
        lines += ["\\hline", "\\end{tabular}"]
    return "\n".join(lines)


def significance_stars(p: Optional[float]) -> str:
    if p is None or not np.isfinite(p):
        return ""
    if p < 0.01:
        return "***"
    if p < 0.05:
        return "**"
    if p < 0.10:
        return "*"
    return ""


def base_vs_instruct_table(family_records: Dict[str, Dict]) -> str:
    """Table-5 style: base→instruct MAE per family with Δ CI and p."""
    lines = [
        "\\begin{tabular}{lcccc}",
        "\\hline",
        "Family & Base MAE & Instruct MAE & $\\Delta$ [95\\% CI] & $p$ \\\\",
        "\\hline",
    ]
    for family, rec in family_records.items():
        if family.startswith("_"):
            continue
        if rec.get("excluded"):
            lines.append(f"{_esc(family)} & \\multicolumn{{4}}{{c}}{{excluded: {_esc(rec.get('reason', ''))}}} \\\\")
            continue
        stars = significance_stars(rec.get("p_value"))
        lines.append(
            f"{_esc(family)} & {rec['base_mae']:.3f} & {rec['instruct_mae']:.3f} & "
            f"{rec['observed_diff']:+.3f}{stars} [{rec['ci_lower']:+.3f}, "
            f"{rec['ci_upper']:+.3f}] & {rec['p_value']:.3f} \\\\"
        )
    lines += ["\\hline", "\\end{tabular}"]
    return "\n".join(lines)


# unicode -> LaTeX replacements for the irrelevant-statement sampler
# (data/generate_latex_statements.py:28-44)
_STATEMENT_REPLACEMENTS = (
    ("&", "\\&"), ("%", "\\%"), ("$", "\\$"), ("#", "\\#"), ("_", "\\_"),
    ("°", "$^\\circ$"), ("−", "$-$"), ("×", "$\\times$"),
    ("π", "$\\pi$"),
    ("⁻¹⁹", "$^{-19}$"), ("⁻³⁴", "$^{-34}$"),
    ("²³", "$^{23}$"), ("₂", "$_2$"),
    ("²", "$^2$"), ("³", "$^3$"), ("–", "--"),
)


def escape_statement(statement: str) -> str:
    for src, dst in _STATEMENT_REPLACEMENTS:
        statement = statement.replace(src, dst)
    return statement


def irrelevant_statements_sample(statements, k: int = 50, seed: int = 42) -> str:
    """Seeded random sample of irrelevant statements as a LaTeX enumerate
    (data/generate_latex_statements.py: random.seed(42) + random.sample(·, 50),
    same escaping rules) for the paper appendix."""
    import random

    rng = random.Random(seed)
    selected = rng.sample(list(statements), k)
    lines = ["\\begin{enumerate}"]
    lines += [f"    \\item {escape_statement(s)}" for s in selected]
    lines.append("\\end{enumerate}")
    return "\n".join(lines)


def power_analysis_table(report, alpha: float = 0.05,
                         sample_size: int = None) -> str:
    """LaTeX table for a `stats.power.power_report` result: per-model effect
    size, required N at 80%/90% power, achieved power at the current N, and
    the limiting-model recommendation."""
    import math

    def fmt_n(n):
        return "$\\infty$" if math.isinf(n) else str(int(n))

    lines = [
        "\\begin{table}[htbp]", "\\centering",
        "\\caption{Power analysis: required sample sizes "
        f"($\\alpha={alpha}$, current $N={sample_size}$)}}",
        "\\begin{tabular}{lrrrr}", "\\hline",
        "Model & Cohen's $d$ & $N$ (80\\% power) & $N$ (90\\% power) "
        "& Power at current $N$ \\\\", "\\hline",
    ]
    for name, analysis in report["models"].items():
        n80 = analysis["sample_sizes"]["power_80"]["raw"]
        n90 = analysis["sample_sizes"]["power_90"]["raw"]
        power_pct = f"{100 * analysis['achieved_power']:.1f}\\%"
        lines.append(
            f"{_esc(str(name))} & {analysis['effect_size']:.3f} & {fmt_n(n80)} & "
            f"{fmt_n(n90)} & {power_pct} \\\\"
        )
    rec = report["recommendation"]["power_80"]
    if math.isinf(rec["raw"]):
        footer = (
            f"No finite $N$ achieves 80\\% power for every model "
            f"(zero observed effect for: {_esc(str(rec['limiting_model']))})."
        )
    else:
        footer = (
            f"Recommended $N$ for 80\\% power across all models: {fmt_n(rec['raw'])} "
            f"({fmt_n(rec['with_margin'])} with 50\\% margin; "
            f"limiting model: {_esc(str(rec['limiting_model']))})."
        )
    lines += ["\\hline", "\\end{tabular}", "\\par\\smallskip " + footer,
              "\\end{table}", ""]
    return "\n".join(lines)
