"""Command-line entry points.

One typed CLI replaces the reference's 43 standalone scripts::

    python -m llm_interpretation_replication_tpu run-100q --checkpoint-dir ...
    python -m llm_interpretation_replication_tpu run-instruct-sweep ...
    python -m llm_interpretation_replication_tpu serve --model ... --input requests.jsonl
    python -m llm_interpretation_replication_tpu run-perturbation --model ... --perturbations data/perturbations.json
    python -m llm_interpretation_replication_tpu generate-irrelevant --output data/perturbations_irrelevant.json
    python -m llm_interpretation_replication_tpu analyze-perturbations --workbook results.xlsx --output-dir out/
    python -m llm_interpretation_replication_tpu similarity --perturbations data/perturbations.json --output-dir out/
    python -m llm_interpretation_replication_tpu analyze-100q --results results/base_vs_instruct_100q_results.csv

Local-model commands build a mesh from RunConfig (device/mesh flags) and load
HF snapshots from a local checkpoint dir (zero-egress: no hub downloads).
"""

from __future__ import annotations

import argparse
import json
import sys


def _write_json(obj, path):
    """Shared artifact writer: parent dir, utf-8, indent-2, strict JSON
    (non-finite floats become null — utils/strict_json)."""
    from .utils.strict_json import dump_strict

    dump_strict(obj, path)
    print(f"wrote {path}")


def _add_run_config_args(p: argparse.ArgumentParser):
    p.add_argument("--strict", action="store_true",
                   help="arm strict mode (runtime/strict.py): disallow "
                        "implicit device->host transfers outside the "
                        "engine's sanctioned fetch points and count XLA "
                        "recompiles into telemetry (recompile_events / "
                        "blocked_transfers) — same as LLM_INTERP_STRICT=1")
    p.add_argument("--trace", nargs="?", const="obs_trace.json",
                   default=None, metavar="PATH",
                   help="span tracing (obs/): record hot-path phase spans "
                        "(tokenize/prefill/extend/decode/fetch, serve "
                        "request spans), stream a JSONL span log to "
                        "PATH.spans.jsonl, and export a Perfetto-loadable "
                        "Chrome trace to PATH at exit; analyze saved "
                        "traces with the 'obs report' subcommand")
    p.add_argument("--profile", metavar="DIR", default=None,
                   help="windowed jax.profiler capture into DIR for the "
                        "command's run (obs/profiler.py; headless "
                        "analysis: utils/profiling.top_device_ops)")
    p.add_argument("--metrics", nargs="?", const="metrics.jsonl",
                   default=None, metavar="PATH",
                   help="streaming JSONL metrics log (obs/metrics.py): "
                        "one sample per sweep heartbeat — telemetry "
                        "counters (raw + since-start delta), sample-ring "
                        "percentiles with truncation visibility, and "
                        "progress gauges — to PATH (default "
                        "metrics.jsonl).  Off by default; the live HTTP "
                        "endpoint is the serve subcommand's "
                        "--metrics-port")
    p.add_argument("--device", choices=["tpu", "cpu"], default="tpu")
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument("--quant", choices=["none", "int8"], default="none",
                   help="w8a8 int8 projections — ~1.9x scoring throughput on "
                        "v5e, ~0.9997 logit correlation vs bf16")
    p.add_argument("--attention-impl", choices=["xla", "flash", "auto"],
                   default="xla",
                   help="'auto' keeps XLA dense at sweep lengths and "
                        "switches to the Pallas kernel past 1k tokens, "
                        "where dense's S^2 scores would exhaust HBM")
    p.add_argument("--kv-dtype", choices=["bf16", "int8"], default="bf16",
                   help="decode-time KV cache storage dtype: bf16 keeps "
                        "the bit-parity contracts; int8 (per-head scales, "
                        "quantize-on-append) nearly halves the cache HBM "
                        "the full-study sweep pins — tolerance in "
                        "PARITY.md")
    p.add_argument("--prefill-chunk", type=int, default=0, metavar="N",
                   help="> 0: prompts above N tokens prefill in N-token "
                        "chunks through the suffix-extension path, "
                        "bounding the long buckets' attention transients")
    p.add_argument("--pooled-confidence",
                   action=argparse.BooleanOptionalAction, default=True,
                   help="route confidence-leg decodes through the "
                        "leg-parameterized cross-batch pool (early-exit "
                        "row retirement + per-chunk completion-cache "
                        "streaming); --no-pooled-confidence keeps the "
                        "per-batch decode")
    p.add_argument("--phase2-pool-target", type=int, default=0, metavar="N",
                   help="rows per pooled phase-2 decode (binary undecided "
                        "pool AND confidence pool); 0 = batch size")
    p.add_argument("--slot-repack",
                   action=argparse.BooleanOptionalAction, default=True,
                   help="decode-then-repack slot-level continuous batching "
                        "(runtime/slots.py): retired pool lanes refill "
                        "from the pending queue mid-decode; "
                        "--no-slot-repack keeps the legacy whole-flush "
                        "schedule")
    p.add_argument("--decode-k", type=int, default=1, metavar="K",
                   help="joint next-K-token decode with verify-and-accept "
                        "(models/decoder.k_verify_block): a K-head "
                        "distilled on sample corpus prompts proposes K "
                        "tokens per pass and one joint program verifies "
                        "them against the single-step argmax path — "
                        "accepted blocks are bit-identical to the "
                        "sequential decode, rejections fall back to it "
                        "(PARITY.md 'K-decode'); 1 = sequential (default)")
    p.add_argument("--plan-search", action="store_true",
                   help="auto-parallel plan search (runtime/plan_search.py)"
                        ": enumerate mesh x batch x kv-dtype x "
                        "prefill-chunk candidates against the HBM budget "
                        "model and run the predicted-rows/s winner instead "
                        "of the batch/kv/mesh flags; the engine's OOM "
                        "back-off ladder stays armed as the safety net "
                        "('plan search' prints the same table standalone)")
    p.add_argument("--mesh-model", type=int, default=1)
    p.add_argument("--mesh-seq", type=int, default=1)
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--checkpoint-dir", default="checkpoints")
    p.add_argument("--output-dir", default="results")


def _run_config(args):
    from .config import RunConfig

    return RunConfig(
        device=args.device, dtype=args.dtype, quant=args.quant,
        kv_dtype=args.kv_dtype, prefill_chunk=args.prefill_chunk,
        pooled_confidence=getattr(args, "pooled_confidence", True),
        phase2_pool_target=getattr(args, "phase2_pool_target", 0),
        slot_repack=getattr(args, "slot_repack", True),
        decode_k=getattr(args, "decode_k", 1),
        plan_search=getattr(args, "plan_search", False),
        attention_impl=args.attention_impl,
        mesh_model=args.mesh_model,
        mesh_seq=args.mesh_seq, batch_size=args.batch_size,
        checkpoint_dir=args.checkpoint_dir, output_dir=args.output_dir,
    )


def _engine_factory(run_config):
    """model name -> ScoringEngine over local snapshots."""
    import jax

    from .parallel import initialize_distributed
    from .runtime import EngineConfig, ScoringEngine, load_model, load_tokenizer

    if run_config.device == "cpu":
        jax.config.update("jax_platforms", "cpu")
    else:
        # multi-host bootstrap: honors JAX_COORDINATOR_ADDRESS /
        # JAX_NUM_PROCESSES / JAX_PROCESS_ID; no-op on a single host
        initialize_distributed()
    mesh = run_config.make_mesh() if (run_config.mesh_model > 1 or run_config.mesh_seq > 1) else None

    def factory(model_name: str) -> ScoringEngine:
        path = run_config.snapshot_path(model_name)
        rc, factory_mesh, plan_note = run_config, mesh, None
        if rc.plan_search:
            rc, factory_mesh, plan_note = _searched_run_config(rc, path,
                                                              mesh)
        family, cfg, params = load_model(
            path, dtype=rc.resolve_dtype(), mesh=factory_mesh,
            quant=rc.quant,
            attention_impl=rc.attention_impl,
        )
        tokenizer = load_tokenizer(path)
        engine = ScoringEngine(
            family, cfg, params, tokenizer, mesh=factory_mesh,
            # NOTE: oom_backoff keeps its armed default here — when the
            # plan search chose this operating point, the PR-1 in-place
            # re-bucket ladder is the safety net for a prediction miss
            engine_config=EngineConfig(
                batch_size=rc.batch_size,
                kv_dtype=rc.kv_dtype,
                prefill_chunk=rc.prefill_chunk,
                pooled_confidence=rc.pooled_confidence,
                phase2_pool_target=rc.phase2_pool_target,
                slot_repack=getattr(rc, "slot_repack", True),
                decode_k=getattr(rc, "decode_k", 1),
            ),
        )
        engine.plan_decision = plan_note
        if getattr(rc, "decode_k", 1) > 1:
            # load-or-redistill (ROADMAP 2(c)): a K-head distilled in an
            # earlier process persists beside the snapshot keyed on
            # (weights fingerprint, decode_k) — a hit skips the
            # per-process ridge-probe distillation entirely; callers that
            # distill on a miss persist via loader_mod.save_k_head
            from .runtime import loader as loader_mod

            if loader_mod.attach_k_head(engine, path):
                print(f"# K-head loaded from snapshot "
                      f"({loader_mod.K_HEAD_FILENAME}, decode_k="
                      f"{rc.decode_k})", file=sys.stderr)
        return engine

    return factory


def _searched_run_config(rc, path, mesh):
    """Apply the auto-parallel plan search to one model's engine
    construction: read the snapshot's geometry (config.json only — no
    weights), search mesh x batch x kv-dtype x prefill-chunk over the
    visible devices, and rewrite the RunConfig fields (plus the mesh) to
    the chosen plan.  Returns (run_config, mesh, decision_note); falls
    back to the flags unchanged — with a stderr note — for geometries the
    budget model cannot price (T5-family encoders)."""
    import dataclasses

    import jax

    from .models.config import from_hf_config
    from .parallel import make_mesh
    from .runtime.loader import load_hf_config
    from .runtime.plan_search import (
        chosen_plan,
        format_candidate_table,
        search_plans,
    )

    try:
        _family, dcfg = from_hf_config(load_hf_config(path))
        ranked = search_plans(dcfg, rc.quant, len(jax.devices()),
                              workload="full")
    except (ValueError, AttributeError, TypeError, OSError) as err:
        print(f"# plan search skipped for {path}: {err}; running the "
              f"configured flags", file=sys.stderr)
        return rc, mesh, None
    best = chosen_plan(ranked)
    if best is None:
        print("# plan search: no candidate fits; running the configured "
              "flags", file=sys.stderr)
        return rc, mesh, None
    print(format_candidate_table(ranked, top=4), file=sys.stderr)
    rc = dataclasses.replace(
        rc, batch_size=best.batch, kv_dtype=best.kv_dtype,
        prefill_chunk=best.prefill_chunk,
        # unconditional: pool_target 0 IS the chosen plan's pool-at-batch
        # configuration, not "keep the flag"
        phase2_pool_target=best.pool_target,
        decode_k=getattr(best, "decode_k", 1),
        mesh_model=best.model)
    if best.data * best.model > 1:
        mesh = make_mesh(data=best.data, model=best.model)
    note = (f"plan search chose mesh dp{best.data}xtp{best.model} batch "
            f"{best.batch} kv {best.kv_dtype} chunk {best.prefill_chunk}"
            + (f" decode-k {best.decode_k}"
               if getattr(best, "decode_k", 1) > 1 else "")
            + f" ({best.reason})")
    print(f"# {note}", file=sys.stderr)
    return rc, mesh, note


def cmd_run_100q(args):
    import os

    from .sweeps import run_sweep

    rc = _run_config(args)
    df = run_sweep(
        _engine_factory(rc),
        checkpoint_path=os.path.join(rc.output_dir, "base_vs_instruct_100q_checkpoint.json"),
        results_csv=os.path.join(rc.output_dir, "base_vs_instruct_100q_results.csv"),
    )
    print(f"{len(df)} rows")


def cmd_run_instruct_sweep(args):
    import os

    from .config import ordinary_meaning_questions
    from .sweeps import run_instruct_sweep

    rc = _run_config(args)
    if args.questions_file:
        # survey-2 leg: the question list extracted from the Qualtrics
        # headers (extract-survey2-questions), the reference's
        # compare_instruct_models_survey2.py:298-355 prompts
        if not args.results_csv:
            raise SystemExit(
                "--questions-file requires --results-csv: without it the "
                "custom-question run would overwrite the default sweep's "
                "instruct_model_comparison_results.csv"
            )
        with open(args.questions_file, encoding="utf-8") as f:
            prompts = [line.strip() for line in f if line.strip()]
    else:
        prompts = ordinary_meaning_questions()
    results_csv = args.results_csv or os.path.join(
        rc.output_dir, "instruct_model_comparison_results.csv"
    )
    if args.results_csv:
        stem = os.path.splitext(os.path.basename(results_csv))[0]
        checkpoint = os.path.join(rc.output_dir, f"{stem}_checkpoint.json")
    else:
        checkpoint = os.path.join(rc.output_dir, "instruct_sweep_checkpoint.json")
    df = run_instruct_sweep(
        _engine_factory(rc),
        prompts=prompts,
        checkpoint_path=checkpoint,
        results_csv=results_csv,
    )
    print(f"{len(df)} rows over {len(prompts)} questions")


def cmd_run_closed_source(args):
    import os
    import time

    from .analysis.closed_source_eval import run_closed_source_evaluation
    from .analysis.questions import (
        load_human_survey_means,
        load_ordinary_meaning_questions,
    )
    from .api_backends.anthropic_client import AnthropicClient
    from .api_backends.gemini_client import GeminiClient
    from .api_backends.openai_client import OpenAIClient

    questions = load_ordinary_meaning_questions(
        instruct_csv=args.questions_csv, survey2_csv=args.survey2_csv,
    )
    human_means = load_human_survey_means(args.survey1_csv, args.survey2_csv)

    def client(env, cls):
        key = os.environ.get(env)
        return cls(key) if key else None

    run_closed_source_evaluation(
        questions,
        output_dir=args.output_dir,
        human_means=human_means,
        cache_file=os.path.join(args.output_dir, "api_cache.json"),
        confirm_fn=None if args.yes else (
            lambda prompt: input(prompt).strip().lower() == "yes"
        ),
        gpt_client=client("OPENAI_API_KEY", OpenAIClient),
        gemini_client=client("GEMINI_API_KEY", GeminiClient),
        claude_client=client("ANTHROPIC_API_KEY", AnthropicClient),
        sleep=time.sleep,           # real per-vendor pacing outside tests
    )


def cmd_run_perturbation(args):
    import os

    from .config import legal_scenarios
    from .gen.rephrase import load_perturbations
    from .sweeps import (
        run_model_perturbation_sweep,
        run_packed_perturbation_sweep,
    )

    rc = _run_config(args)
    scenarios = load_perturbations(args.perturbations, expected_scenarios=legal_scenarios())
    engine = _engine_factory(rc)(args.model)
    if getattr(engine.ecfg, "decode_k", 1) > 1 and engine.k_head is None:
        # K-head self-distillation on the sweep's own texts (both legs'
        # formats — the continuations the decode legs will replay); a
        # verify-and-accept head can only cost rejections, never rows.
        # Skipped entirely when the factory loaded a persisted head
        # (k_head.npz beside the snapshot); a fresh distillation persists
        # for the next process.
        sample = [f"{r} {s['response_format']}" for s in scenarios
                  for r in s["rephrasings"][:3]][:24]
        sample += [f"{r} {s['confidence_format']}" for s in scenarios
                   for r in s["rephrasings"][:2]][:12]
        engine.distill_k_head_on(sample)
        print(f"# K-head distilled for decode_k={engine.ecfg.decode_k} "
              f"on {min(len(sample), 32)} sample prompts", file=sys.stderr)
        if engine.k_head is not None:
            from .runtime import loader as loader_mod

            try:
                saved = loader_mod.save_k_head(
                    rc.snapshot_path(args.model), engine.k_head,
                    engine.ecfg.decode_k)
                print(f"# K-head persisted to {saved}", file=sys.stderr)
            except OSError as err:   # read-only snapshot dir: still runs
                print(f"# K-head not persisted ({err})", file=sys.stderr)
    if getattr(args, "packed", 0):
        # packed multi-question batching (scoring/packed.py): Q rephrasings
        # per prefill, anchor-gathered binary leg, measured-drift contract
        df, drift = run_packed_perturbation_sweep(
            engine, args.model, scenarios,
            output_xlsx=os.path.join(rc.output_dir,
                                     "perturbation_results_packed.xlsx"),
            packing=args.packed,
            drift_parity=getattr(args, "packed_parity", True),
            max_rephrasings=args.max_rephrasings,
            score_chunk=args.score_chunk,
        )
        print(f"{len(df)} rows (packed Q={args.packed})")
        if drift is not None:
            print(json.dumps({"packed_drift": drift}))
        return
    df = run_model_perturbation_sweep(
        engine, args.model, scenarios,
        output_xlsx=os.path.join(rc.output_dir, "perturbation_results.xlsx"),
        max_rephrasings=args.max_rephrasings,
        score_chunk=args.score_chunk,
        confidence_max_new_tokens=args.confidence_max_new_tokens,
    )
    print(f"{len(df)} rows")


def cmd_run_api_perturbation(args):
    import os

    from .api_backends.cost import CostTracker
    from .api_backends.openai_client import OpenAIClient
    from .config import legal_scenarios
    from .gen.rephrase import load_perturbations
    from .sweeps.api_perturbation import run_api_perturbation_sweep

    key = os.environ.get("OPENAI_API_KEY")
    if not key:
        raise SystemExit("OPENAI_API_KEY not set")
    scenarios = load_perturbations(args.perturbations,
                                   expected_scenarios=legal_scenarios())
    cost = CostTracker()
    run_api_perturbation_sweep(
        OpenAIClient(key), args.model, scenarios, args.output,
        max_rephrasings=args.max_rephrasings,
        skip_reasoning_logprobs=not args.reasoning_logprob_runs,
        cost_tracker=cost,
    )
    print(cost.summary())


def cmd_run_claude_perturbation(args):
    import os

    from .api_backends.anthropic_client import AnthropicClient
    from .config import legal_scenarios
    from .gen.rephrase import load_perturbations
    from .sweeps.api_perturbation import run_claude_perturbation_sweep

    key = os.environ.get("ANTHROPIC_API_KEY")
    if not key:
        raise SystemExit("ANTHROPIC_API_KEY not set")
    scenarios = load_perturbations(args.perturbations,
                                   expected_scenarios=legal_scenarios())
    run_claude_perturbation_sweep(
        AnthropicClient(key), args.model, scenarios, args.output,
        max_rephrasings=args.max_rephrasings,
    )


def cmd_generate_rephrasings(args):
    import os

    from .api_backends.anthropic_client import AnthropicClient
    from .config import legal_scenarios
    from .gen.rephrase import generate_rephrasings, save_perturbations

    key = os.environ.get("ANTHROPIC_API_KEY")
    if not key:
        raise SystemExit("ANTHROPIC_API_KEY not set")
    client = AnthropicClient(key)

    def complete(prompt):
        # reference: 100 sessions x temperature 0.9 rephrasing requests
        # (perturb_prompts.py:787-809)
        msg = client.create_message(
            args.model, [{"role": "user", "content": prompt}],
            temperature=0.9, max_tokens=4000,
        )
        return client.text_of(msg)

    records = generate_rephrasings(
        legal_scenarios(), complete,
        sessions_per_scenario=args.sessions,
        target_per_scenario=args.target,
        on_error=lambda s, e: print(f"session {s} failed: {e}"),
    )
    save_perturbations(records, args.output)
    print(f"wrote {args.output}: "
          + ", ".join(str(len(r["rephrasings"])) for r in records)
          + " rephrasings per scenario")


def cmd_run_gpt_perturbation(args):
    import os

    from .api_backends.openai_client import OpenAIClient
    from .config import legal_scenarios
    from .gen.rephrase import load_perturbations
    from .sweeps.api_perturbation import run_gpt_perturbation_sweep

    key = os.environ.get("OPENAI_API_KEY")
    if not key:
        raise SystemExit("OPENAI_API_KEY not set")
    scenarios = load_perturbations(args.perturbations,
                                   expected_scenarios=legal_scenarios())
    run_gpt_perturbation_sweep(
        OpenAIClient(key), args.model, scenarios, args.output,
        rate_limit_sleep=args.sleep,
        max_rephrasings=args.max_rephrasings,
    )


def cmd_run_gemini_perturbation(args):
    import os

    from .api_backends.gemini_client import GeminiClient
    from .config import legal_scenarios
    from .gen.rephrase import load_perturbations
    from .sweeps.api_perturbation import run_gemini_perturbation_sweep

    key = os.environ.get("GEMINI_API_KEY")
    if not key:
        raise SystemExit("GEMINI_API_KEY not set")
    scenarios = load_perturbations(args.perturbations,
                                   expected_scenarios=legal_scenarios())
    run_gemini_perturbation_sweep(
        GeminiClient(key, requests_per_second=args.rps), args.model, scenarios,
        args.output, max_workers=args.threads,
        max_rephrasings=args.max_rephrasings,
    )


def cmd_analyze_survey(args):
    from .survey.pipeline import run_consolidated_analysis

    run_consolidated_analysis(
        [args.survey1_csv, args.survey2_csv], args.llm_csv, args.output_dir,
        n_bootstrap=args.bootstrap, cross_prompt_bootstrap=args.cross_prompt_bootstrap,
    )


def cmd_analyze_combined(args):
    from .analysis.combined_confidence import run_combined_analysis
    from .utils.xlsx import read_xlsx

    frames = {}
    for spec in args.workbook:
        name, sep, path = spec.partition("=")
        if not sep or not name or not path:
            raise SystemExit(f"--workbook expects NAME=PATH, got {spec!r}")
        if name in frames:
            raise SystemExit(f"duplicate workbook name {name!r}")
        frames[name] = read_xlsx(path)
    out = run_combined_analysis(frames, args.output_dir)
    print(out["stats"].to_string(index=False))


def cmd_demographics(args):
    from .survey.demographics import (
        demographics_latex_table,
        load_demographics,
        summarize_age,
    )

    df = load_demographics(list(args.csv))
    columns = args.column or ["Sex", "Ethnicity simplified", "Employment status",
                              "Student status"]
    tex = demographics_latex_table(df, columns)
    age = summarize_age(df)
    age_block = (
        "\n% Age summary (reference generate_demographics_table.py:115-120)\n"
        f"% n={age['n']} mean={age['mean']:.1f} median={age['median']:.0f} "
        f"range {age['min']:.0f}-{age['max']:.0f}\n"
    )
    tex = tex + age_block
    if args.output:
        with open(args.output, "w") as f:
            f.write(tex)
        print(f"wrote {args.output}")
    else:
        print(tex)


def cmd_generate_irrelevant(args):
    from .config import irrelevant_scenarios, irrelevant_statements
    from .gen.irrelevant import (
        generate_perturbations,
        save_perturbations,
        save_readable,
    )

    perturbed = generate_perturbations(irrelevant_scenarios(), irrelevant_statements())
    save_perturbations(perturbed, args.output)
    total = sum(len(s["perturbations_with_irrelevant"]) for s in perturbed)
    print(f"{total} perturbations -> {args.output}")
    if args.readable_output:
        import datetime

        save_readable(
            perturbed, args.readable_output,
            generated_at=datetime.datetime.now().strftime("%Y-%m-%d %H:%M:%S"),
        )
        print(f"readable dump -> {args.readable_output}")


def cmd_run_irrelevant(args):
    """The irrelevant-insertion study end-to-end (Appendix C's data leg) —
    evaluate_irrelevant_perturbations.py:942-1297 as a subcommand: 3,400
    perturbations × {response, confidence} legs over GPT-4.1 / Claude Opus
    4.1 / Gemini 2.5 Pro at temperature 0.7, triple-set resume, and the full
    artifact set (raw/summary CSVs, three-sheet workbook, analysis.json,
    reports, stacked violins)."""
    import os

    from .analysis.irrelevant_eval import (
        analyze_results,
        build_vendor_evaluators,
        create_stacked_visualization,
        run_irrelevant_evaluation,
    )
    from .gen.irrelevant import load_perturbations

    out = args.output_dir
    analysis_json = os.path.join(out, "analysis.json")
    raw_csv = os.path.join(out, "raw_results.csv")

    if args.regenerate_plots:
        # reference :1009-1026: plots only, from the saved analysis
        if not os.path.exists(analysis_json):
            raise SystemExit(f"no analysis at {analysis_json}; run the evaluation first")
        with open(analysis_json) as f:
            analysis = json.load(f)
        fig = create_stacked_visualization(analysis, out)
        print(f"regenerated {fig}")
        return

    fresh_start = args.no_resume or args.clear_checkpoint
    if args.load_existing and not args.force_rerun and not fresh_start:
        # reference :1028-1078: loading saved results is the DEFAULT; a new
        # evaluation only starts when nothing is saved, --force-rerun asks,
        # or a fresh start (--no-resume/--clear-checkpoint) signals intent
        # to re-evaluate (silently ignoring those flags would be worse)
        if os.path.exists(raw_csv) and os.path.exists(analysis_json):
            import pandas as pd

            df = pd.read_csv(raw_csv)
            with open(analysis_json) as f:
                analysis = json.load(f)
            for model in args.models:
                sub = df[df["model"] == model]
                if len(sub):
                    print(f"{model.upper()}: {len(sub)} evaluations across "
                          f"{sub['scenario_name'].nunique()} scenarios")
            fig = create_stacked_visualization(analysis, out)
            print(f"loaded {len(df)} results from {raw_csv}; figure: {fig}")
            print("to force re-running evaluations, use: --force-rerun")
            return

    scenarios = load_perturbations(args.perturbations)

    def key_for(env):
        key = os.environ.get(env)
        if key is None:
            raise SystemExit(f"{env} not set")
        return key

    clients = {}
    if "gpt" in args.models:
        from .api_backends.openai_client import OpenAIClient

        clients["gpt_client"] = OpenAIClient(key_for("OPENAI_API_KEY"))
    if "claude" in args.models:
        from .api_backends.anthropic_client import AnthropicClient

        clients["claude_client"] = AnthropicClient(key_for("ANTHROPIC_API_KEY"))
    if "gemini" in args.models:
        from .api_backends.gemini_client import GeminiClient

        clients["gemini_client"] = GeminiClient(key_for("GEMINI_API_KEY"))

    # Destroy saved state only after inputs/keys validated above — a typo'd
    # path or missing key must fail fast WITHOUT erasing paid-for results.
    if fresh_start:
        for name in ("processed_triples.json", "progress.json",
                     "raw_results.csv", "analysis.json"):
            path = os.path.join(out, name)
            if os.path.exists(path):
                os.remove(path)
        print("cleared resume state")
    import time

    evaluators = build_vendor_evaluators(sleep=time.sleep, **clients)
    test_mode = args.test_mode and not args.full_mode
    if args.limit is not None and not args.full_mode:
        # an explicit cap implies a limited run — it must never silently
        # escalate into the full 3,400×3×2 paid sweep; only an explicit
        # --full-mode overrides it
        test_mode = True
    paths = run_irrelevant_evaluation(
        evaluators, scenarios, out,
        limit_total=(args.limit if args.limit is not None else 100)
        if test_mode else None,
    )
    print(json.dumps(paths, indent=2))


def cmd_analyze_perturbations(args):
    from .analysis import analyze_workbook
    from .config import legal_scenarios
    from .utils.xlsx import read_xlsx

    df = read_xlsx(args.workbook)
    out = analyze_workbook(df, legal_scenarios(), args.output_dir,
                           n_simulations=args.simulations)
    print(json.dumps({m: len(r["scenarios"]) for m, r in out.items()}, indent=2))


def cmd_similarity(args):
    from .analysis import similarity_report
    from .analysis.similarity_report import load_embedding_model
    from .config import legal_scenarios
    from .gen.rephrase import load_perturbations

    embedding_model = None
    if args.embeddings:
        # gated exactly like the reference: absent package / unloadable
        # model degrades to the lexical metrics with a warning
        embedding_model = load_embedding_model(args.embedding_model)
    records = load_perturbations(args.perturbations, expected_scenarios=legal_scenarios())
    summary = similarity_report(records, args.output_dir,
                                max_rephrasings=args.max_rephrasings,
                                embedding_model=embedding_model)
    print(summary.to_string(index=False))


def _mae_100q_families(results_csv, survey_csvs):
    """Shared Table-5 machinery: survey loading + exclusions + human means
    (0-1) + question matching + per-family paired bootstrap
    (analyze_base_vs_instruct_mae_100q.py:421-560)."""
    from .survey import (
        analyze_families,
        apply_exclusion_criteria,
        human_responses_by_question,
        load_and_clean_survey_data,
        match_survey_to_llm_questions,
    )

    df, cols = load_and_clean_survey_data(survey_csvs)
    df, excl = apply_exclusion_criteria(df, cols)
    model_df = _load_llm_csv(results_csv)
    matches, _ = match_survey_to_llm_questions(model_df, survey_csvs)
    human = human_responses_by_question(df, cols)
    human_avgs = {q: s["mean"] / 100.0 for q, s in human.items()}  # 0-100 → 0-1
    families = analyze_families(model_df, human_avgs, matches)
    meta = {
        "respondents": int(excl["final_count"]),
        "questions_with_humans": len(human_avgs),
        "matched_prompts": len(matches),
        "model_rows": len(model_df),
    }
    return families, meta


def cmd_analyze_100q(args):
    import pandas as pd

    from .stats.bootstrap import base_vs_instruct_analysis

    df = pd.read_csv(args.results)
    out = base_vs_instruct_analysis(df)
    print(json.dumps(out, indent=2, default=float))
    if args.output_json:
        _write_json(out, args.output_json)
    if args.latex:
        # Table 5 needs human survey means — delegate to the real machinery
        # (the old mapping printed NaN MAE columns from bootstrap-only keys)
        if not args.survey1_csv:
            raise SystemExit(
                "--latex emits paper Table 5 (MAE vs human means): pass "
                "--survey1-csv/--survey2-csv, or use the analyze-mae-100q "
                "subcommand"
            )
        from .viz.latex import base_vs_instruct_table

        surveys = [args.survey1_csv] + (
            [args.survey2_csv] if args.survey2_csv else []
        )
        families, _ = _mae_100q_families(args.results, surveys)
        print(base_vs_instruct_table(families))


def cmd_analyze_mae_100q(args):
    """Paper Table 5 end-to-end: per-family base→instruct MAE vs human means
    with paired bootstrap — analyze_base_vs_instruct_mae_100q.py's main."""
    from .viz.latex import base_vs_instruct_table

    surveys = [args.survey1_csv] + ([args.survey2_csv] if args.survey2_csv else [])
    families, meta = _mae_100q_families(args.results, surveys)
    print(f"Respondents after exclusions: {meta['respondents']}")
    print(f"Questions with human responses: {meta['questions_with_humans']}")
    print(f"Matched prompts: {meta['matched_prompts']}")
    for fam, rec in families.items():
        if fam.startswith("_"):
            continue
        if rec.get("excluded"):
            print(f"{fam}: excluded ({rec.get('reason', '')})")
            continue
        print(
            f"{fam}: base {rec['base_mae']:.3f} -> instruct "
            f"{rec['instruct_mae']:.3f}  diff {rec['observed_diff']:+.3f} "
            f"[{rec['ci_lower']:+.3f}, {rec['ci_upper']:+.3f}] "
            f"p={rec['p_value']:.4f} (n={rec['n']})"
        )
    overall = families.get("_overall")
    if overall:
        print(
            f"Overall: base {overall['base_mae']:.3f} -> instruct "
            f"{overall['instruct_mae']:.3f}  diff {overall['observed_diff']:+.3f} "
            f"[{overall['ci_lower']:+.3f}, {overall['ci_upper']:+.3f}] "
            f"p={overall['p_value']:.4f}"
        )
    if args.latex or args.output_tex:
        table = base_vs_instruct_table(families)
        if args.output_tex:
            with open(args.output_tex, "w", encoding="utf-8") as f:
                f.write(table + "\n")
            print(f"wrote {args.output_tex}")
        if args.latex:
            print(table)
    if args.output_json:
        _write_json({"families": families, "meta": meta}, args.output_json)


def cmd_serve(args):
    """Continuous-batching scoring service (serve/): one resident model,
    independent requests coalescing onto its warm compiled shapes.  The
    stdlib JSONL driver reads requests from --input (file or stdin) and
    answers every line in input order; --replay routes the perturbation
    sweep workload through the scheduler and asserts row-level parity
    with the offline score_prompts path; --pool-replicas N serves
    through an EnginePool of N shared-snapshot replicas (serve/pool.py:
    per-replica /healthz + labeled serve_* metrics, hot load/unload
    over the engine's verified teardown)."""
    from .serve.cli import main as serve_main

    rc = _run_config(args)
    engine = _engine_factory(rc)(args.model)
    raise SystemExit(serve_main(engine, args))


def cmd_lint(args):
    """graftlint: the repo's JAX-aware static-analysis gate (lint/).

    In practice UNREACHABLE — ``main()`` routes ``lint`` to
    :mod:`..lint.cli` before argparse runs, because REMAINDER cannot
    accept leading optionals like ``--explain``.  The subparser (and this
    equivalent forwarder) is registered anyway so the subcommand shows up
    in ``--help`` next to its siblings."""
    from .lint.cli import main as lint_main

    raise SystemExit(lint_main(args.lint_args))


def cmd_repair_batch(args):
    """Rewrite a corrupted batch-response JSONL (fix_batch_responses.py as a
    subcommand)."""
    from .api_backends.gemini_client import repair_batch_responses

    n = repair_batch_responses(args.requests, args.responses, args.output)
    print(f"repaired {n} rows -> {args.output}")


def cmd_extract_survey2(args):
    """Pull the part-2 questions out of the Qualtrics headers
    (analysis/extract_survey2_questions.py:9-82)."""
    from .analysis.questions import extract_survey2_questions

    import os

    questions, _ = extract_survey2_questions(args.survey_csv)
    if getattr(args, "ascii_quotes", False):
        table = str.maketrans({"“": '"', "”": '"',
                               "‘": "'", "’": "'"})
        questions = [q.translate(table) for q in questions]
    parent = os.path.dirname(os.path.abspath(args.output))
    os.makedirs(parent, exist_ok=True)
    with open(args.output, "w", encoding="utf-8") as f:
        f.write("\n".join(questions) + "\n")
    print(f"wrote {len(questions)} questions -> {args.output}")


def cmd_sample_statements(args):
    """Seeded LaTeX sample of the irrelevant statements for the appendix
    (data/generate_latex_statements.py)."""
    from .config import irrelevant_statements
    from .viz.latex import irrelevant_statements_sample

    tex = irrelevant_statements_sample(
        irrelevant_statements(), k=args.k, seed=args.seed
    )
    if args.output:
        with open(args.output, "w", encoding="utf-8") as f:
            f.write(tex + "\n")
        print(f"wrote {args.output}")
    else:
        print(tex)


def _load_llm_csv(path):
    """Model-results CSV with relative_prob guaranteed: recomputed from the
    raw probs with both-zero rows at 0.5 when yes/no columns exist
    (analyze_base_vs_instruct_mae_100q.py:212-222)."""
    import pandas as pd

    df = pd.read_csv(path)
    if {"yes_prob", "no_prob"}.issubset(df.columns):
        df["relative_prob"] = (
            df["yes_prob"] / (df["yes_prob"] + df["no_prob"])
        ).fillna(0.5)
    return df


def _load_clean_survey(survey_csvs):
    from .survey import apply_exclusion_criteria, load_and_clean_survey_data
    from .survey.pipeline import extract_question_text

    df, cols = load_and_clean_survey_data(survey_csvs)
    df, excl = apply_exclusion_criteria(df, cols)
    mapping = extract_question_text(survey_csvs)
    return df, cols, mapping, excl


def cmd_analyze_3way(args):
    """Base-vs-instruct-vs-human 3-way comparison
    (analyze_base_vs_instruct_vs_human.py as a subcommand)."""
    from .survey import three_way_report

    surveys = [args.survey1_csv] + ([args.survey2_csv] if args.survey2_csv else [])
    survey_df, cols, mapping, _ = _load_clean_survey(surveys)
    llm_df = _load_llm_csv(args.llm_csv)
    out = three_way_report(llm_df, survey_df, cols, mapping, args.output_dir,
                           make_figures=not args.no_figures)
    print(f"Loaded human data for {out['human_questions']} questions")
    print("Model correlations with human responses:")
    print(out["correlations"].to_string())
    print(f"Found {len(out['invalid_responses'])} invalid responses "
          f"(not containing Yes/No)")
    for _, row in out["distribution_stats"].iterrows():
        line = (f"{row['model']}: mean {row['mean']:.3f}, std {row['std']:.3f}, "
                f"range [{row['min']:.3f}, {row['max']:.3f}]")
        if row["warning"]:
            line += f"  WARNING: {row['warning']}"
        print(line)
    print(f"wrote {out['correlations_csv']}")
    if out.get("figure"):
        print(f"figure: {out['figure']}")


def cmd_analyze_agreement(args):
    """Point-estimate + question-bootstrap LLM/human agreement reports
    (analyze_llm_human_agreement.py + analyze_llm_agreement_simple_bootstrap.py
    as one subcommand; both reference JSON shapes)."""
    import os

    import pandas as pd

    from .survey.variants import (
        agreement_question_bootstrap,
        human_agreement_means,
        human_agreement_report,
        save_agreement_json,
    )

    os.makedirs(args.output_dir, exist_ok=True)
    instruct_df = _load_llm_csv(args.llm_csv)
    base_df = pd.read_csv(args.base_csv) if args.base_csv else None
    means = human_agreement_means([args.survey_csv], instruct_df)
    print(f"Loaded human average ratings for {len(means)} questions")

    point = human_agreement_report(instruct_df, base_df, means)
    point_path = os.path.join(args.output_dir, "llm_human_agreement_analysis.json")
    save_agreement_json({k: v for k, v in point.items() if k != "detailed"},
                        point_path)
    print(f"{'Model':<45} {'Type':<9} {'MAE':<8} {'RMSE':<8} {'Pearson r':<10}")
    for r in point["model_results"]:
        print(f"{r['model']:<45} {r['model_type']:<9} {r['mae']:<8.4f} "
              f"{r['rmse']:<8.4f} {r['pearson_r']:<10.4f}")

    boot = agreement_question_bootstrap(
        instruct_df, base_df, means,
        n_bootstrap=args.bootstrap, seed=args.seed,
    )
    boot_path = os.path.join(args.output_dir, "llm_human_agreement_bootstrap.json")
    save_agreement_json(boot, boot_path)
    for metric, rec in boot["overall_comparison"]["metrics"].items():
        print(f"{metric.upper()}: base {rec['base_mean']:.4f} vs instruct "
              f"{rec['instruct_mean']:.4f}, diff {rec['difference']:+.4f} "
              f"[{rec['difference_ci'][0]:+.4f}, {rec['difference_ci'][1]:+.4f}], "
              f"p = {rec['p_value']:.4f}")
    print(f"wrote {point_path}")
    print(f"wrote {boot_path}")


def cmd_analyze_family_differences(args):
    """Respondent-level agreement bootstrap + per-family MAE/MSE/MAPE
    differences (analyze_llm_human_agreement_bootstrap.py +
    analyze_model_family_differences.py)."""
    import os

    from .survey import (
        agreement_bootstrap,
        family_differences,
        family_differences_text,
    )
    from .survey.variants import save_agreement_json

    os.makedirs(args.output_dir, exist_ok=True)
    agreement_path = os.path.join(args.output_dir,
                                  "llm_human_agreement_bootstrap.json")
    if args.agreement_json:
        with open(args.agreement_json) as f:
            agreement = json.load(f)
    else:
        if not (args.llm_csv and args.survey1_csv):
            raise SystemExit(
                "pass --llm-csv and --survey1-csv, or --agreement-json"
            )
        surveys = [args.survey1_csv] + (
            [args.survey2_csv] if args.survey2_csv else []
        )
        survey_df, cols, mapping, _ = _load_clean_survey(surveys)
        llm_df = _load_llm_csv(args.llm_csv)
        agreement = agreement_bootstrap(
            llm_df, survey_df, cols, mapping,
            n_bootstrap=args.bootstrap,
        )
        save_agreement_json(agreement, agreement_path)
        print(f"wrote {agreement_path}")
    records = family_differences(agreement)
    text = family_differences_text(records)
    print(text)
    report_path = os.path.join(args.output_dir, "family_differences.txt")
    with open(report_path, "w", encoding="utf-8") as f:
        f.write(text)
    print(f"wrote {report_path}")


def cmd_ground_truth_figure(args):
    """Ground-truth distribution figures
    (visualize_ground_truth_distribution.py)."""
    from .survey import ground_truth_figures, ground_truth_values

    surveys = [args.survey1_csv] + ([args.survey2_csv] if args.survey2_csv else [])
    survey_df, cols, _, _ = _load_clean_survey(surveys)
    values = ground_truth_values(survey_df, cols)
    if not values.size:
        raise SystemExit("no human ground-truth values found")
    out = ground_truth_figures(values, args.output_dir)
    print(f"Loaded {out['n']} human ground truth values")
    print(f"Mean: {out['mean']:.3f} ({out['mean'] * 100:.1f}%)")
    print(f"Std:  {out['std']:.3f} ({out['std'] * 100:.1f}%)")
    print(f"figures: {out['two_panel']}, {out['simple']}")


def cmd_model_comparison(args):
    """Inter-model correlation engine as a runnable leg
    (model_comparison_graph.py:389-494): pairwise Pearson/Spearman, bootstrap
    summary, pairwise+aggregate kappa, heatmap/distribution/strip figures."""
    import pandas as pd

    from .analysis import model_comparison_report

    df = pd.read_csv(args.results)
    reference_model = args.reference_model
    if reference_model is None:
        # reference default: a Baichuan model anchors the strip plot when
        # present (model_comparison_graph.py:59-79)
        baichuan = [m for m in df["model"].unique() if "baichuan" in m.lower()]
        reference_model = baichuan[0] if baichuan else None
    report = model_comparison_report(
        df, args.output_dir, n_bootstrap=args.bootstrap,
        reference_model=reference_model, make_figures=not args.no_figures,
    )
    s = report["summary"]
    print(f"{len(report['pairwise'])} model pairs")
    print(f"mean correlation {s['mean']:.3f} "
          f"[{s['mean_ci'][0]:.3f}, {s['mean_ci'][1]:.3f}], "
          f"median {s['median']:.3f}, std {s['std']:.3f}")
    print(f"mean kappa {report['kappa']['mean_kappa']:.3f}")
    for key in ("heatmap", "distribution", "difference_strip"):
        if report.get(key):
            print(f"figure: {report[key]}")
    print(f"wrote {args.output_dir}/pairwise_correlations.csv, "
          f"correlation_summary.json")


def cmd_cross_kappa(args):
    """Cross-experiment Cohen's kappa (calculate_cohens_kappa.py): merge
    result frames from multiple experiments, binarize at the threshold, and
    bootstrap the aggregate agreement."""
    import pandas as pd

    frames = [pd.read_csv(path) for path in args.results]
    from .analysis import cross_experiment_kappa

    kappa = cross_experiment_kappa(
        frames, threshold=args.threshold, n_bootstrap=args.bootstrap,
    )
    out = {
        "n_frames": len(frames),
        "mean_kappa": kappa["mean_kappa"],
        "mean_kappa_ci": kappa["mean_kappa_ci"],
        "n_pairs": len(kappa["pairs"]),
    }
    print(json.dumps(out, indent=2, default=float))
    if args.output_json:
        _write_json({**out, "pairs": kappa["pairs"]}, args.output_json)


def cmd_power_analysis(args):
    """Sample-size / power report (power_analysis.py:10-278) from pilot MAEs;
    writes power_analysis_report.tex."""
    import os

    from .config import power_pilot_results
    from .stats import power_report

    if args.pilot_json:
        with open(args.pilot_json) as f:
            pilot = json.load(f)
    else:
        pilot = power_pilot_results()
    os.makedirs(args.output_dir, exist_ok=True)
    tex = os.path.join(args.output_dir, "power_analysis_report.tex")
    report = power_report(
        pilot["models"], baseline_mae=pilot["baseline_mae"],
        sample_size=pilot["sample_size"], alpha=args.alpha,
        n_simulations=args.simulations, output_tex=tex,
    )
    for name, analysis in report["models"].items():
        n80 = analysis["sample_sizes"]["power_80"]["raw"]
        print(f"{name}: effect d={analysis['effect_size']:.3f}, "
              f"power@N={pilot['sample_size']} "
              f"{analysis['achieved_power']:.2f}, N(80%)={n80}")
    rec = report["recommendation"]["power_80"]
    print(f"recommendation (80% power): N={rec['with_margin']} "
          f"(limiting model: {rec['limiting_model']})")
    print(f"wrote {tex}")


def cmd_verify_replication(args):
    """One-command replication verifier: recompute every headline table
    through this framework's pipeline and diff against the published numbers
    (BASELINE.md) with CI-overlap PASS/FAIL verdicts.  With --snapshots, the
    Table-5 sweep first runs for real through the TPU engine
    (run_base_vs_instruct_100q.py:514-599); otherwise the Table-5 rows
    report SKIP (the reference never published its raw CSV)."""
    from .analysis.replication import (
        format_report,
        run_snapshot_sweep,
        verify_replication,
    )

    results_100q = args.results_100q
    if args.snapshots:
        args.checkpoint_dir = args.snapshots
        rc = _run_config(args)
        results_100q = run_snapshot_sweep(rc, args.output_dir)
    result = verify_replication(
        reference_root=args.reference_root,
        results_100q_csv=results_100q,
        n_bootstrap=args.bootstrap,
        cross_prompt_bootstrap=args.cross_prompt_bootstrap,
    )
    print(format_report(result))
    if args.output_json:
        _write_json(result, args.output_json)
    if not result["ok"]:
        raise SystemExit(1)


def cmd_plan(args):
    """``plan search``: the auto-parallel strategy search (runtime/
    plan_search.py).  Like ``lint``/``obs``, in practice UNREACHABLE —
    ``main()`` routes ``plan`` pre-argparse; the subparser exists so the
    subcommand shows up in ``--help``."""
    from .runtime.plan_search import main as plan_main

    raise SystemExit(plan_main(args.plan_args))


def cmd_obs(args):
    """``obs report`` / ``obs bench-diff``: phase-attribution table over
    a saved span trace, and the bench-trajectory regression analyzer
    over BENCH_r*.json records.

    Like ``lint``, in practice UNREACHABLE — ``main()`` routes ``obs`` to
    :mod:`.obs.report` before argparse runs (REMAINDER cannot accept
    leading optionals like ``--trace``); the subparser exists so the
    subcommand shows up in ``--help``."""
    from .obs.report import main as obs_main

    raise SystemExit(obs_main(args.obs_args))


def main(argv=None):
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "lint":
        # routed before argparse: REMAINDER cannot swallow leading
        # optionals (`lint --explain all` would error against the parent
        # parser), and the linter needs none of the run-config machinery
        from .lint.cli import main as lint_main

        raise SystemExit(lint_main(argv[1:]))
    if argv and argv[0] == "obs":
        # same pre-argparse routing as lint: `obs report --trace PATH`
        # leads with an optional the parent parser would reject
        from .obs.report import main as obs_main

        raise SystemExit(obs_main(argv[1:]))
    if argv and argv[0] == "plan":
        # same pre-argparse routing as lint/obs: the plan-search CLI is
        # pure host arithmetic and must not pay (or trigger) the parent
        # parser's run-config machinery or a JAX backend init
        from .runtime.plan_search import main as plan_main

        raise SystemExit(plan_main(argv[1:]))
    parser = argparse.ArgumentParser(prog="llm_interpretation_replication_tpu")
    sub = parser.add_subparsers(dest="command", required=True)

    p = sub.add_parser("run-100q", help="base-vs-instruct 100-question sweep")
    _add_run_config_args(p)
    p.set_defaults(fn=cmd_run_100q)

    p = sub.add_parser(
        "verify-replication",
        help="recompute Tables 3-5 + appendix numbers and PASS/FAIL each "
             "against the published values (BASELINE.md) by CI overlap")
    _add_run_config_args(p)
    p.add_argument("--reference-root", default="/root/reference",
                   help="mounted reference repo with the recorded artifacts")
    p.add_argument("--snapshots", default=None, metavar="DIR",
                   help="local HF checkpoint dir: run the real Table-5 sweep "
                        "(run-100q) through the TPU engine first")
    p.add_argument("--results-100q", default=None,
                   help="existing base_vs_instruct_100q_results.csv from a "
                        "finished run-100q sweep (alternative to --snapshots)")
    p.add_argument("--bootstrap", type=int, default=10_000,
                   help="MAE bootstrap resamples (paper value)")
    p.add_argument("--cross-prompt-bootstrap", type=int, default=200,
                   help="cross-prompt bootstrap resamples (the full "
                        "pipeline's 1000 takes minutes; point estimates are "
                        "deterministic either way)")
    p.add_argument("--output-json", default=None)
    p.set_defaults(fn=cmd_verify_replication)

    p = sub.add_parser("run-instruct-sweep", help="instruct-model roster sweep")
    _add_run_config_args(p)
    p.add_argument("--questions-file", default=None,
                   help="newline-delimited question list (e.g. the output of "
                        "extract-survey2-questions) — drives the survey-2 "
                        "leg (compare_instruct_models_survey2.py:298-355); "
                        "default: the 50 ordinary-meaning questions")
    p.add_argument("--results-csv", default=None,
                   help="output CSV path (e.g. instruct_model_comparison_"
                        "results_survey2.csv); the checkpoint file is derived "
                        "from its basename so the 50q and survey-2 sweeps "
                        "can share an output dir")
    p.set_defaults(fn=cmd_run_instruct_sweep)

    p = sub.add_parser("run-closed-source",
                       help="frontier-API 100-question evaluation (keys via env)")
    p.add_argument("--questions-csv", required=True,
                   help="instruct_model_comparison_results.csv (first 50 questions)")
    p.add_argument("--survey2-csv", required=True,
                   help="survey part-2 export (remaining questions)")
    p.add_argument("--survey1-csv", required=True,
                   help="survey part-1 export (human means for the MAE tables)")
    p.add_argument("--output-dir", default="results/closed_source_evaluation")
    p.add_argument("--yes", action="store_true", help="skip the cost confirmation")
    p.set_defaults(fn=cmd_run_closed_source)

    p = sub.add_parser("run-perturbation", help="10k-perturbation local-model sweep")
    _add_run_config_args(p)
    p.add_argument("--model", required=True)
    p.add_argument("--perturbations", required=True)
    p.add_argument("--max-rephrasings", type=int, default=None)
    p.add_argument("--score-chunk", type=int, default=2000,
                   help="rows per cross-scenario scoring call: bounds crash "
                        "loss (a crash loses the in-flight chunk); raise on "
                        "reliable hardware to merge more tail batches")
    p.add_argument("--confidence-max-new-tokens", type=int, default=10,
                   metavar="N",
                   help="generation cap for the confidence leg (the API "
                        "legs' max_tokens=10 contract; the parse reads only "
                        "the first integer).  0 = the engine's full "
                        "max_new_tokens (50-token confidence completions "
                        "in the workbook)")
    p.add_argument("--packed", type=int, default=0, metavar="Q",
                   help="> 0: packed multi-question batching (Auto-Demo, "
                        "scoring/packed.py) — Q rephrasings + their "
                        "demonstration answers concatenate into one row, "
                        "prefill once, and the binary leg reads anchor-"
                        "gathered logits (no decode, no confidence leg; "
                        "measured-drift contract, PARITY.md).  Output "
                        "lands in perturbation_results_packed.xlsx")
    p.add_argument("--packed-parity",
                   action=argparse.BooleanOptionalAction, default=True,
                   help="with --packed: score the same rows isolated "
                        "first and print the drift block (per-question "
                        "|Δ relative_prob| distribution + flip rate); the "
                        "isolated answers double as the Auto-Demo "
                        "demonstrations")
    p.set_defaults(fn=cmd_run_perturbation)

    p = sub.add_parser("run-api-perturbation",
                       help="frontier-model 10k-perturbation sweep via the "
                            "OpenAI Batch API (key via env)")
    p.add_argument("--perturbations", required=True, help="perturbations.json")
    p.add_argument("--model", action="append", required=True,
                   help="repeat per model (<=3 run concurrently)")
    p.add_argument("--output", default="results/perturbation_results_api.xlsx")
    p.add_argument("--max-rephrasings", type=int, default=None)
    p.add_argument("--reasoning-logprob-runs", action="store_true",
                   help="approximate reasoning-model logprobs with 10 repeats "
                        "instead of skipping the binary leg")
    p.set_defaults(fn=cmd_run_api_perturbation)

    p = sub.add_parser("run-claude-perturbation",
                       help="confidence-only Claude Message-Batches sweep "
                            "(key via env)")
    p.add_argument("--perturbations", required=True, help="perturbations.json")
    p.add_argument("--model", default="claude-opus-4-1-20250805")
    p.add_argument("--output", default="results/claude_batch_perturbation_results.xlsx")
    p.add_argument("--max-rephrasings", type=int, default=None)
    p.set_defaults(fn=cmd_run_claude_perturbation)

    p = sub.add_parser("generate-rephrasings",
                       help="build perturbations.json via Claude rephrasing "
                            "sessions (key via env)")
    p.add_argument("--model", default="claude-sonnet-4-20250514")
    p.add_argument("--sessions", type=int, default=100)
    p.add_argument("--target", type=int, default=2000)
    p.add_argument("--output", default="data/perturbations.json")
    p.set_defaults(fn=cmd_generate_rephrasings)

    p = sub.add_parser("run-gpt-perturbation",
                       help="serial GPT sync perturbation sweep, no batch "
                            "service (perturb_prompts_gpt.py; key via env)")
    p.add_argument("--perturbations", required=True, help="perturbations.json")
    p.add_argument("--model", default="gpt-4-0125-preview")
    p.add_argument("--output", default="results/gpt4_perturbation_results.xlsx")
    p.add_argument("--sleep", type=float, default=0.5,
                   help="rate-limit sleep between rephrasings (reference: 0.5s)")
    p.add_argument("--max-rephrasings", type=int, default=None)
    p.set_defaults(fn=cmd_run_gpt_perturbation)

    p = sub.add_parser("run-gemini-perturbation",
                       help="threaded Gemini sync perturbation sweep (key via env)")
    p.add_argument("--perturbations", required=True, help="perturbations.json")
    p.add_argument("--model", default="gemini-2.5-pro")
    p.add_argument("--output", default="results/gemini_perturbation_results.xlsx")
    p.add_argument("--threads", type=int, default=20)
    p.add_argument("--rps", type=float, default=2.3,
                   help="token-bucket rate limit (reference: ~2.3 req/s)")
    p.add_argument("--max-rephrasings", type=int, default=None)
    p.set_defaults(fn=cmd_run_gemini_perturbation)

    p = sub.add_parser("analyze-survey",
                       help="consolidated human-vs-LLM survey analysis")
    p.add_argument("--survey1-csv", required=True)
    p.add_argument("--survey2-csv", required=True)
    p.add_argument("--llm-csv", required=True,
                   help="instruct_model_comparison_results_combined.csv")
    p.add_argument("--output-dir", default="results/survey_analysis")
    p.add_argument("--bootstrap", type=int, default=1000)
    p.add_argument("--cross-prompt-bootstrap", type=int, default=100)
    p.set_defaults(fn=cmd_analyze_survey)

    p = sub.add_parser("analyze-combined",
                       help="three-model confidence combiner over sweep workbooks")
    p.add_argument("--workbook", action="append", required=True,
                   metavar="NAME=PATH", help="repeat per model")
    p.add_argument("--output-dir", default="results/combined_analysis")
    p.set_defaults(fn=cmd_analyze_combined)

    p = sub.add_parser("demographics-table",
                       help="Prolific demographics LaTeX table")
    p.add_argument("--csv", action="append", required=True)
    p.add_argument("--column", action="append", default=None,
                   help="repeat per categorical column (default: Sex, "
                        "Ethnicity simplified, Employment status, Student "
                        "status; an Age summary comment is always appended)")
    p.add_argument("--output", default=None)
    p.set_defaults(fn=cmd_demographics)

    p = sub.add_parser("generate-irrelevant", help="build perturbations_irrelevant.json")
    p.add_argument("--output", default="data/perturbations_irrelevant.json")
    p.add_argument("--readable-output", default=None,
                   help="also write the human-readable dump "
                        "(perturbations_irrelevant_readable.txt)")
    p.set_defaults(fn=cmd_generate_irrelevant)

    p = sub.add_parser("run-irrelevant",
                       help="irrelevant-insertion study: 3,400 perturbations "
                            "over GPT/Claude/Gemini at temperature 0.7 "
                            "(keys via env)")
    p.add_argument("--perturbations", default="data/perturbations_irrelevant.json")
    p.add_argument("--output-dir", default="results/irrelevant_perturbations")
    p.add_argument("--test-mode", action="store_true", default=False,
                   help="limited run (see --limit)")
    p.add_argument("--full-mode", action="store_true",
                   help="run on all data (overrides test mode)")
    p.add_argument("--limit", type=int, default=None,
                   help="cap on total evaluations, split across models "
                        "(implies a limited run unless --full-mode; "
                        "test-mode default: 100)")
    p.add_argument("--models", nargs="+", choices=["gpt", "claude", "gemini"],
                   default=["gpt", "claude", "gemini"])
    p.add_argument("--resume", action="store_true",
                   help="resume from checkpoint (the default behavior; "
                        "accepted for reference-CLI parity)")
    p.add_argument("--no-resume", action="store_true",
                   help="start from scratch, discarding any checkpoint")
    p.add_argument("--clear-checkpoint", action="store_true",
                   help="clear existing checkpoint before starting")
    p.add_argument("--load-existing", action=argparse.BooleanOptionalAction,
                   default=True,
                   help="load saved results/analysis instead of evaluating")
    p.add_argument("--force-rerun", action="store_true",
                   help="run new evaluations even if results exist")
    p.add_argument("--regenerate-plots", action="store_true",
                   help="only rebuild plots from the saved analysis.json")
    p.set_defaults(fn=cmd_run_irrelevant)

    p = sub.add_parser("analyze-perturbations", help="statistics over a sweep workbook")
    p.add_argument("--workbook", required=True)
    p.add_argument("--output-dir", default="results/perturbation_analysis")
    p.add_argument("--simulations", type=int, default=100_000)
    p.set_defaults(fn=cmd_analyze_perturbations)

    p = sub.add_parser("similarity", help="rephrasing similarity validation")
    p.add_argument("--perturbations", required=True)
    p.add_argument("--output-dir", default="results/prompt_similarity")
    p.add_argument("--max-rephrasings", type=int, default=None)
    p.add_argument("--embeddings", action="store_true",
                   help="add the sentence-transformer embedding-cosine "
                        "column (calculate_prompt_similarity.py:98-207); "
                        "degrades with a warning when the package or model "
                        "is unavailable")
    p.add_argument("--embedding-model", default="all-MiniLM-L6-v2",
                   help="sentence-transformers model name (reference default)")
    p.set_defaults(fn=cmd_similarity)

    p = sub.add_parser("analyze-100q", help="instruct-base bootstrap over 100q results")
    p.add_argument("--results", required=True)
    p.add_argument("--latex", action="store_true",
                   help="also emit paper Table 5 (needs --survey1-csv)")
    p.add_argument("--survey1-csv", default=None)
    p.add_argument("--survey2-csv", default=None)
    p.add_argument("--output-json", default=None,
                   help="also write the analysis records here")
    p.set_defaults(fn=cmd_analyze_100q)

    p = sub.add_parser(
        "serve",
        help="continuous-batching scoring service (serve/): JSONL "
             "stdin/file driver over one resident model — or an "
             "EnginePool replica fleet with --pool-replicas — plus "
             "--replay for offline-parity verification and --load-rate "
             "for the open-loop load harness")
    _add_run_config_args(p)
    p.add_argument("--model", required=True,
                   help="model snapshot name under --checkpoint-dir")
    p.add_argument("--input", default="-",
                   help="JSONL request stream: one "
                        '{"prompt": ...}/{"prefix": ..., "suffix": ...} '
                        "object per line ('-' = stdin)")
    p.add_argument("--output", default="-",
                   help="JSONL results, input order ('-' = stdout)")
    p.add_argument("--max-batch", type=int, default=0, metavar="N",
                   help="rows per coalesced micro-batch (0 = the "
                        "engine's batch size — the warm compiled shape)")
    p.add_argument("--max-wait-ms", type=float, default=20.0, metavar="MS",
                   help="admission policy: hold the head request at most "
                        "this long for co-batchable traffic before "
                        "launching a partial micro-batch")
    p.add_argument("--queue-capacity", type=int, default=2048, metavar="N",
                   help="admission bound; a submit past it is a typed "
                        "QueueFull backpressure rejection")
    p.add_argument("--timeout-s", type=float, default=None, metavar="S",
                   help="default per-request deadline (expired requests "
                        "are rejected with a typed DeadlineExceeded, "
                        "never silently dropped)")
    p.add_argument("--no-slot-admission", action="store_true",
                   help="disable slot-level mid-decode admission "
                        "(SchedulerConfig.slot_admission, default ON "
                        "since replay bit-parity was pinned): eligible "
                        "requests launch only at coalescer boundaries "
                        "instead of refilling vacated decode slots — "
                        "the A/B escape hatch")
    p.add_argument("--metrics-port", type=int, default=0, metavar="PORT",
                   help="host /metrics (Prometheus text exposition over "
                        "the telemetry counters + serve sample-ring "
                        "percentiles) and /healthz (scheduler liveness + "
                        "queue depth) on this port while the driver "
                        "runs (obs/metrics.py; 0 = off)")
    p.add_argument("--replay", metavar="PERTURBATIONS", default=None,
                   help="replay mode: push the perturbation sweep "
                        "workload through the scheduler, assert "
                        "row-level parity vs the offline path, and "
                        "report scheduler-vs-offline throughput")
    p.add_argument("--max-rephrasings", type=int, default=None,
                   help="replay mode: cap rephrasings per scenario")
    p.add_argument("--load-rate", metavar="R[,R2,...]", default=None,
                   help="open-loop load harness (serve/load.py): drive "
                        "the scheduler at a seeded-Poisson offered rate "
                        "(requests/s) drawn from the --replay corpus (or "
                        "the --input lines as the prompt pool) and "
                        "report per-request latency anatomy (queue_wait/"
                        "coalesce/serve_engine/respond) from exact-count "
                        "histograms; a comma list of >= 3 rates walks "
                        "the rate sweep and reports the knee")
    p.add_argument("--load-duration", type=float, default=10.0,
                   metavar="S",
                   help="load mode: seconds of offered traffic per rate "
                        "point")
    p.add_argument("--load-seed", type=int, default=0, metavar="N",
                   help="load mode: seed for the Poisson schedule and "
                        "the prompt mix (same seed = identical traffic)")
    p.add_argument("--load-jsonl", metavar="PATH", default=None,
                   help="load mode: stream one per-request anatomy "
                        "record (scheduled time, generator lag, e2e + "
                        "per-phase ms) per line to PATH")
    p.add_argument("--pool-replicas", type=int, default=0, metavar="N",
                   help="serve through an EnginePool (serve/pool.py) of "
                        "N local replicas of the loaded snapshot — "
                        "siblings share the param tree (same device "
                        "buffers), each behind its own scheduler with "
                        "{replica, model} labeled serve_* metrics; "
                        "/healthz gains the per-replica health document "
                        "and --load-rate drives the pool through the "
                        "same open-loop harness (0/1 = single-engine "
                        "scheduler, today's path)")
    p.add_argument("--supervise", action="store_true",
                   help="arm fleet self-healing on the --pool-replicas "
                        "fleet (serve/supervisor.py): per-replica "
                        "watchdogs classify crash vs wedge, dead "
                        "replicas are quarantined and rebuilt from the "
                        "shared snapshot with exponential backoff, "
                        "in-flight requests fail over to a sibling "
                        "at-most-once, and repeat-killer requests are "
                        "rejected as poisonous instead of taking a "
                        "third replica down")
    p.set_defaults(fn=cmd_serve)

    p = sub.add_parser("lint",
                       help="JAX-aware static analysis (graftlint rules "
                            "G01-G08, interprocedural) gated by "
                            "lint_baseline.json; `lint contracts` runs "
                            "the cross-artifact drift checker")
    p.add_argument("lint_args", nargs=argparse.REMAINDER,
                   help="forwarded to the linter: paths, --diff, "
                        "--format text|json, --baseline PATH, "
                        "--no-baseline, --write-baseline, --explain "
                        "RULE|all, or the `contracts` subcommand "
                        "(--root, --only KIND, --diff)")
    p.set_defaults(fn=cmd_lint)

    p = sub.add_parser("plan",
                       help="auto-parallel plan search: 'plan search' "
                            "enumerates mesh x batch x kv-dtype x "
                            "prefill-chunk candidates against the HBM "
                            "budget model and ranks them by predicted "
                            "rows/s ('plan search --dryrun' proves the "
                            "choice vs the hand-picked MULTICHIP points "
                            "on the virtual 8-device mesh)")
    p.add_argument("plan_args", nargs=argparse.REMAINDER,
                   help="forwarded: search [--model ...] [--devices N] "
                        "[--workload full|binary] [--dryrun] "
                        "[--format table|json]")
    p.set_defaults(fn=cmd_plan)

    p = sub.add_parser("obs",
                       help="observability reports: 'obs report --trace "
                            "PATH' aggregates a saved span trace (JSONL "
                            "log or Chrome-trace JSON) per phase/leg; "
                            "'obs bench-diff BENCH_r04.json "
                            "BENCH_r05.json' aligns bench records into a "
                            "regression table (exit 1 past --threshold)")
    p.add_argument("obs_args", nargs=argparse.REMAINDER,
                   help="forwarded: report --trace PATH [--wall-s S] "
                        "[--rows N] [--format table|json], or bench-diff "
                        "RECORD... [--threshold PCT] [--format "
                        "table|json] [--no-fail]")
    p.set_defaults(fn=cmd_obs)

    p = sub.add_parser("repair-batch",
                       help="re-pair a corrupted batch-response JSONL")
    p.add_argument("--requests", required=True, help="request JSONL")
    p.add_argument("--responses", required=True, help="corrupted response JSONL")
    p.add_argument("--output", required=True)
    p.set_defaults(fn=cmd_repair_batch)

    p = sub.add_parser("extract-survey2-questions",
                       help="extract part-2 questions from Qualtrics headers")
    p.add_argument("--survey-csv", required=True)
    p.add_argument("--output", default="data/question_list_part_2_actual.txt")
    p.add_argument("--ascii-quotes", action="store_true",
                   help="normalize the 7 curly-quoted Qualtrics headers to "
                        "straight quotes — the form the reference sweep "
                        "actually ran (its hardcoded prompts list, "
                        "compare_instruct_models_survey2.py:298-355, is a "
                        "straight-quote transcription of this extractor's "
                        "output)")
    p.set_defaults(fn=cmd_extract_survey2)

    p = sub.add_parser("sample-statements",
                       help="seeded LaTeX sample of the irrelevant statements")
    p.add_argument("--k", type=int, default=50)
    p.add_argument("--seed", type=int, default=42)
    p.add_argument("--output", default=None)
    p.set_defaults(fn=cmd_sample_statements)

    p = sub.add_parser("analyze-3way",
                       help="base-vs-instruct-vs-human comparison "
                            "(correlations, validity audit, scatter)")
    p.add_argument("--llm-csv", required=True)
    p.add_argument("--survey1-csv", required=True)
    p.add_argument("--survey2-csv", default=None)
    p.add_argument("--output-dir", default="results/three_way")
    p.add_argument("--no-figures", action="store_true")
    p.set_defaults(fn=cmd_analyze_3way)

    p = sub.add_parser("analyze-agreement",
                       help="point-estimate + question-bootstrap LLM/human "
                            "agreement (both reference JSON report shapes)")
    p.add_argument("--llm-csv", required=True,
                   help="instruct_model_comparison_results.csv")
    p.add_argument("--base-csv", default=None,
                   help="model_comparison_results.csv (base models)")
    p.add_argument("--survey-csv", required=True,
                   help="survey-1 Qualtrics export (the 50 mapped questions)")
    p.add_argument("--output-dir", default="results/agreement")
    p.add_argument("--bootstrap", type=int, default=1000)
    p.add_argument("--seed", type=int, default=42)
    p.set_defaults(fn=cmd_analyze_agreement)

    p = sub.add_parser("analyze-family-differences",
                       help="respondent-bootstrap agreement + per-family "
                            "MAE/MSE/MAPE differences")
    p.add_argument("--llm-csv", default=None)
    p.add_argument("--survey1-csv", default=None)
    p.add_argument("--survey2-csv", default=None)
    p.add_argument("--agreement-json", default=None,
                   help="reuse a saved llm_human_agreement_bootstrap.json")
    p.add_argument("--output-dir", default="results/family_differences")
    p.add_argument("--bootstrap", type=int, default=100)
    p.set_defaults(fn=cmd_analyze_family_differences)

    p = sub.add_parser("ground-truth-figure",
                       help="human ground-truth distribution figures")
    p.add_argument("--survey1-csv", required=True)
    p.add_argument("--survey2-csv", default=None)
    p.add_argument("--output-dir", default="results/ground_truth")
    p.set_defaults(fn=cmd_ground_truth_figure)

    p = sub.add_parser("model-comparison",
                       help="inter-model correlation report + heatmap + kappa "
                            "over a results CSV (prompt/model/relative_prob)")
    p.add_argument("--results", required=True,
                   help="instruct_model_comparison_results*.csv-style CSV")
    p.add_argument("--output-dir", default="results/model_comparison")
    p.add_argument("--reference-model", default=None,
                   help="strip-plot anchor (default: auto-detect Baichuan)")
    p.add_argument("--bootstrap", type=int, default=1000)
    p.add_argument("--no-figures", action="store_true")
    p.set_defaults(fn=cmd_model_comparison)

    p = sub.add_parser("cross-kappa",
                       help="aggregate Cohen's kappa across experiment CSVs")
    p.add_argument("--results", nargs="+", required=True,
                   help="one or more results CSVs (same schema)")
    p.add_argument("--threshold", type=float, default=0.5)
    p.add_argument("--bootstrap", type=int, default=1000)
    p.add_argument("--output-json", default=None)
    p.set_defaults(fn=cmd_cross_kappa)

    p = sub.add_parser("power-analysis",
                       help="sample-size / power report from pilot MAEs")
    p.add_argument("--pilot-json", default=None,
                   help="override the built-in pilot results asset")
    p.add_argument("--output-dir", default="results/power_analysis")
    p.add_argument("--alpha", type=float, default=0.05)
    p.add_argument("--simulations", type=int, default=10_000)
    p.set_defaults(fn=cmd_power_analysis)

    p = sub.add_parser("analyze-mae-100q",
                       help="paper Table 5: per-family base-vs-instruct MAE "
                            "vs human survey means (paired bootstrap)")
    p.add_argument("--results", required=True,
                   help="base_vs_instruct_100q_results.csv")
    p.add_argument("--survey1-csv", required=True)
    p.add_argument("--survey2-csv", default=None)
    p.add_argument("--latex", action="store_true", help="print the LaTeX table")
    p.add_argument("--output-tex", default=None, help="write the LaTeX table here")
    p.add_argument("--output-json", default=None, help="write family records here")
    p.set_defaults(fn=cmd_analyze_mae_100q)

    args = parser.parse_args(argv)
    # Persistent XLA compilation cache, env-gated: export
    # LLM_INTERP_COMPILE_CACHE=/path to make every CLI sweep start hot
    # (resume-after-preemption and repeat runs deserialize their compiled
    # programs in seconds instead of re-paying 1.5-4 min per program).
    from .runtime.loader import enable_compile_cache

    enable_compile_cache()
    # Strict mode (runtime/strict.py): --strict on any local-model command
    # or LLM_INTERP_STRICT=1 arms the transfer guard + recompile sentry so
    # the run's operating point is auditable (recompile_events /
    # blocked_transfers telemetry counters).
    from .runtime import strict as strict_mod

    if getattr(args, "strict", False):
        strict_mod.activate()
    else:
        strict_mod.activate_from_env()
    # Observability (obs/): --trace arms the span tracer for the whole
    # command (JSONL streams as spans close; the Chrome trace exports on
    # the way out, success or failure), --profile wraps the command in a
    # jax.profiler capture window, --metrics streams the JSONL metrics
    # log (one sample per sweep heartbeat).  All measurement-only.
    if getattr(args, "metrics", None):
        from .obs import metrics as obs_metrics

        obs_metrics.enable_jsonl(args.metrics)
        print(f"# obs: metrics log streaming to {args.metrics}",
              file=sys.stderr)
    trace_path = getattr(args, "trace", None)
    profile_dir = getattr(args, "profile", None)
    if not trace_path and not profile_dir:
        args.fn(args)
        return
    from .obs import enable as obs_enable
    from .obs import export_chrome as obs_export
    from .obs.profiler import profile_window

    if trace_path:
        obs_enable(jsonl_path=trace_path + ".spans.jsonl", memory=True)
    try:
        with profile_window(profile_dir, enabled=bool(profile_dir)):
            args.fn(args)
    finally:
        if trace_path:
            path = obs_export(trace_path)
            print(f"# obs: trace written to {path} (span log "
                  f"{trace_path}.spans.jsonl)", file=sys.stderr)


if __name__ == "__main__":
    main()
