/* Levenshtein edit distance — native kernel.
 *
 * The reference's prompt-similarity validator depends on python-Levenshtein
 * (a C library; requirements.txt + calculate_prompt_similarity.py).  This is
 * the equivalent native component for the TPU build: banded two-row DP over
 * UTF-32 code points, O(min(m,n)) memory, called from Python via ctypes.
 */

#include <stdint.h>
#include <stdlib.h>
#include <string.h>

static size_t min3(size_t a, size_t b, size_t c) {
    size_t m = a < b ? a : b;
    return m < c ? m : c;
}

/* Distance over uint32 code-point arrays. Returns SIZE_MAX on alloc failure. */
size_t levenshtein_u32(const uint32_t *a, size_t la, const uint32_t *b, size_t lb) {
    if (la == 0) return lb;
    if (lb == 0) return la;
    /* keep the shorter string in the inner dimension */
    if (lb > la) {
        const uint32_t *ts = a; a = b; b = ts;
        size_t tl = la; la = lb; lb = tl;
    }
    size_t *prev = (size_t *)malloc((lb + 1) * sizeof(size_t));
    size_t *curr = (size_t *)malloc((lb + 1) * sizeof(size_t));
    if (!prev || !curr) {
        free(prev); free(curr);
        return (size_t)-1;
    }
    for (size_t j = 0; j <= lb; j++) prev[j] = j;
    for (size_t i = 1; i <= la; i++) {
        curr[0] = i;
        uint32_t ca = a[i - 1];
        for (size_t j = 1; j <= lb; j++) {
            size_t cost = (ca == b[j - 1]) ? 0 : 1;
            curr[j] = min3(prev[j] + 1, curr[j - 1] + 1, prev[j - 1] + cost);
        }
        size_t *tmp = prev; prev = curr; curr = tmp;
    }
    size_t result = prev[lb];
    free(prev);
    free(curr);
    return result;
}

/* Batched pairwise distances: out[i] = d(a, bs_i); offsets delimit bs rows. */
void levenshtein_u32_batch(
    const uint32_t *a, size_t la,
    const uint32_t *bs, const size_t *offsets, size_t n,
    size_t *out) {
    for (size_t i = 0; i < n; i++) {
        size_t start = offsets[i];
        size_t end = offsets[i + 1];
        out[i] = levenshtein_u32(a, la, bs + start, end - start);
    }
}
