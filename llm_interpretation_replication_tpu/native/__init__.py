"""Native (C) components, built on demand with the system compiler.

``levenshtein`` mirrors the python-Levenshtein C dependency of the reference's
similarity validator (calculate_prompt_similarity.py).  The shared object is
compiled once into this directory and loaded via ctypes; a pure-python
fallback keeps everything working if no compiler is available.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "levenshtein.c")
_SO = os.path.join(_DIR, "_levenshtein.so")

_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _build() -> Optional[ctypes.CDLL]:
    global _build_failed
    if _build_failed:
        return None
    try:
        if not os.path.exists(_SO) or os.path.getmtime(_SO) < os.path.getmtime(_SRC):
            cc = os.environ.get("CC", "cc")
            subprocess.run(
                [cc, "-O3", "-shared", "-fPIC", "-o", _SO, _SRC],
                check=True,
                capture_output=True,
            )
        lib = ctypes.CDLL(_SO)
        lib.levenshtein_u32.restype = ctypes.c_size_t
        lib.levenshtein_u32.argtypes = [
            ctypes.POINTER(ctypes.c_uint32), ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_uint32), ctypes.c_size_t,
        ]
        return lib
    except Exception:
        _build_failed = True
        return None


def _get_lib() -> Optional[ctypes.CDLL]:
    global _lib
    if _lib is None and not _build_failed:
        _lib = _build()
    return _lib


def _as_u32(s: str):
    data = s.encode("utf-32-le")
    n = len(data) // 4
    buf = (ctypes.c_uint32 * n).from_buffer_copy(data) if n else (ctypes.c_uint32 * 1)()
    return buf, n


def _levenshtein_py(a: str, b: str) -> int:
    if not a:
        return len(b)
    if not b:
        return len(a)
    prev = list(range(len(b) + 1))
    for i, ca in enumerate(a, 1):
        curr = [i]
        for j, cb in enumerate(b, 1):
            cost = 0 if ca == cb else 1
            curr.append(min(prev[j] + 1, curr[j - 1] + 1, prev[j - 1] + cost))
        prev = curr
    return prev[-1]


def levenshtein(a: str, b: str) -> int:
    """Edit distance (native C when available, python fallback otherwise)."""
    lib = _get_lib()
    if lib is None:
        return _levenshtein_py(a, b)
    ba, la = _as_u32(a)
    bb, lb = _as_u32(b)
    out = lib.levenshtein_u32(ba, la, bb, lb)
    if out == ctypes.c_size_t(-1).value:  # alloc failure
        return _levenshtein_py(a, b)
    return int(out)


def normalized_levenshtein_similarity(a: str, b: str) -> float:
    """1 − d/max_len (the reference's normalized similarity)."""
    if not a and not b:
        return 1.0
    m = max(len(a), len(b))
    return 1.0 - levenshtein(a, b) / m


def using_native() -> bool:
    return _get_lib() is not None
