"""Inter-model correlation and agreement engine.

Behavioral replica of model_comparison_graph.py:207-341/495-709 and
calculate_cohens_kappa.py: pivot prompts×models, all pairwise Pearson/Spearman
correlations, prompt-resampling bootstrap of summary statistics, and Cohen's
kappa on binary judgments thresholded at 0.5.
"""

from __future__ import annotations

from itertools import combinations
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import pandas as pd
from scipy import stats as scipy_stats


def pivot_model_values(df: pd.DataFrame, value_col: str = "relative_prob",
                       prompt_col: str = "prompt", model_col: str = "model") -> pd.DataFrame:
    """prompts × models matrix of ``value_col``."""
    return df.pivot_table(index=prompt_col, columns=model_col, values=value_col)


def pairwise_correlations(pivot: pd.DataFrame) -> pd.DataFrame:
    """All model pairs: Pearson r/p and Spearman ρ/p over shared prompts."""
    rows = []
    for a, b in combinations(pivot.columns, 2):
        sub = pivot[[a, b]].dropna()
        if len(sub) < 3:
            continue
        pr, pp = scipy_stats.pearsonr(sub[a], sub[b])
        sr, sp = scipy_stats.spearmanr(sub[a], sub[b])
        rows.append(
            {
                "model_1": a,
                "model_2": b,
                "n": len(sub),
                "pearson_r": float(pr),
                "pearson_p": float(pp),
                "spearman_r": float(sr),
                "spearman_p": float(sp),
            }
        )
    return pd.DataFrame(rows)


def _pairwise_pearson_values(matrix: np.ndarray) -> np.ndarray:
    """Pearson r for every column pair of a prompts×models matrix (NaN-pair
    rows dropped per pair)."""
    n_models = matrix.shape[1]
    out = []
    for i, j in combinations(range(n_models), 2):
        a, b = matrix[:, i], matrix[:, j]
        ok = np.isfinite(a) & np.isfinite(b)
        if ok.sum() < 3 or np.std(a[ok]) == 0 or np.std(b[ok]) == 0:
            continue
        out.append(np.corrcoef(a[ok], b[ok])[0, 1])
    return np.asarray(out)


def correlation_summary_bootstrap(
    pivot: pd.DataFrame,
    n_bootstrap: int = 1000,
    seed: int = 42,
) -> Dict:
    """Mean/median/std of all pairwise correlations with CIs from resampling
    *prompts* (model_comparison_graph.py:207-341)."""
    matrix = pivot.to_numpy(dtype=float)
    observed = _pairwise_pearson_values(matrix)
    rng = np.random.default_rng(seed)
    n_prompts = matrix.shape[0]
    means, medians, stds = [], [], []
    for _ in range(n_bootstrap):
        idx = rng.choice(n_prompts, size=n_prompts, replace=True)
        vals = _pairwise_pearson_values(matrix[idx])
        if vals.size:
            means.append(np.mean(vals))
            medians.append(np.median(vals))
            stds.append(np.std(vals))

    def ci(arr):
        return (float(np.percentile(arr, 2.5)), float(np.percentile(arr, 97.5)))

    return {
        "n_pairs": int(observed.size),
        "mean": float(np.mean(observed)),
        "mean_ci": ci(means),
        "median": float(np.median(observed)),
        "median_ci": ci(medians),
        "std": float(np.std(observed)),
        "std_ci": ci(stds),
        "values": observed.tolist(),
    }


def cohens_kappa(a: Sequence[int], b: Sequence[int]) -> float:
    """Cohen's kappa for two binary (or categorical) raters."""
    a = np.asarray(a)
    b = np.asarray(b)
    cats = np.unique(np.concatenate([a, b]))
    n = len(a)
    po = float(np.mean(a == b))
    pe = 0.0
    for c in cats:
        pe += float(np.mean(a == c)) * float(np.mean(b == c))
    if pe >= 1.0:
        return 1.0 if po >= 1.0 else 0.0
    return (po - pe) / (1 - pe)


def pairwise_kappa(
    pivot: pd.DataFrame,
    threshold: float = 0.5,
    n_bootstrap: int = 1000,
    seed: int = 42,
) -> Dict:
    """Per-pair and aggregate Cohen's kappa of thresholded judgments with a
    prompt-resampling bootstrap (model_comparison_graph.py:495-709)."""
    binary = (pivot.to_numpy(dtype=float) >= threshold).astype(int)
    finite = np.isfinite(pivot.to_numpy(dtype=float))
    pairs = []
    for i, j in combinations(range(binary.shape[1]), 2):
        ok = finite[:, i] & finite[:, j]
        if ok.sum() < 3:
            continue
        pairs.append(
            {
                "model_1": pivot.columns[i],
                "model_2": pivot.columns[j],
                "kappa": cohens_kappa(binary[ok, i], binary[ok, j]),
                "n": int(ok.sum()),
            }
        )
    kappas = np.array([p["kappa"] for p in pairs])
    rng = np.random.default_rng(seed)
    n_prompts = binary.shape[0]
    boot_means = []
    for _ in range(n_bootstrap):
        idx = rng.choice(n_prompts, size=n_prompts, replace=True)
        bs = []
        for i, j in combinations(range(binary.shape[1]), 2):
            ok = finite[idx, i] & finite[idx, j]
            if ok.sum() < 3:
                continue
            bs.append(cohens_kappa(binary[idx][ok, i], binary[idx][ok, j]))
        if bs:
            boot_means.append(np.mean(bs))
    return {
        "pairs": pairs,
        "mean_kappa": float(np.mean(kappas)) if kappas.size else float("nan"),
        "mean_kappa_ci": (
            float(np.percentile(boot_means, 2.5)),
            float(np.percentile(boot_means, 97.5)),
        )
        if boot_means
        else (float("nan"), float("nan")),
    }


def fisher_z_pvalue(r: float, n: int) -> float:
    """Two-sided p for a Pearson r via the Fisher z transform
    (calculate_correlation_pvalues.py)."""
    if n < 4 or abs(r) >= 1:
        return float("nan")
    z = 0.5 * np.log((1 + r) / (1 - r)) * np.sqrt(n - 3)
    return float(2 * (1 - scipy_stats.norm.cdf(abs(z))))


def compare_correlation_distributions(
    a: Sequence[float],
    b: Sequence[float],
    labels: Tuple[str, str] = ("a", "b"),
    p_values_a: Optional[Sequence[float]] = None,
    p_values_b: Optional[Sequence[float]] = None,
    alpha: float = 0.05,
) -> Dict:
    """Compare two correlation distributions — the reference's
    ``compare_distributions`` (calculate_correlation_pvalues.py:138-205),
    the last coverage partial (VERDICT Missing #2): Mann-Whitney U and
    two-sample Kolmogorov-Smirnov on the raw correlation samples, Welch's
    independent t-test, Cohen's d on the pooled standard deviation, plus
    per-sample summary statistics and — when per-correlation p-values are
    supplied — the proportion of significant correlations at ``alpha``.

    NaNs are dropped per sample (a failed pairwise correlation must not
    poison the distribution tests).  Requires >= 2 finite values per side;
    raises ValueError otherwise (the reference indexes blindly and would
    emit NaN statistics)."""
    arr_a = np.asarray(list(a), dtype=float)
    arr_b = np.asarray(list(b), dtype=float)
    arr_a = arr_a[np.isfinite(arr_a)]
    arr_b = arr_b[np.isfinite(arr_b)]
    if arr_a.size < 2 or arr_b.size < 2:
        raise ValueError(
            f"need >= 2 finite correlations per sample, got "
            f"{arr_a.size} ({labels[0]}) and {arr_b.size} ({labels[1]})"
        )
    mw_stat, mw_p = scipy_stats.mannwhitneyu(arr_a, arr_b,
                                             alternative="two-sided")
    ks_stat, ks_p = scipy_stats.ks_2samp(arr_a, arr_b)
    t_stat, t_p = scipy_stats.ttest_ind(arr_a, arr_b, equal_var=False)
    # Cohen's d on the pooled (n-1 weighted) standard deviation
    na, nb = arr_a.size, arr_b.size
    pooled = np.sqrt(((na - 1) * arr_a.var(ddof=1)
                      + (nb - 1) * arr_b.var(ddof=1)) / (na + nb - 2))
    d = float((arr_a.mean() - arr_b.mean()) / pooled) if pooled else 0.0

    def summary(arr):
        return {
            "n": int(arr.size),
            "mean": float(arr.mean()),
            "median": float(np.median(arr)),
            "std": float(arr.std(ddof=1)),
            "min": float(arr.min()),
            "max": float(arr.max()),
        }

    out = {
        "labels": list(labels),
        labels[0]: summary(arr_a),
        labels[1]: summary(arr_b),
        "mannwhitney_u": float(mw_stat),
        "mannwhitney_p": float(mw_p),
        "ks_statistic": float(ks_stat),
        "ks_p": float(ks_p),
        "t_statistic": float(t_stat),
        "t_p": float(t_p),
        "cohens_d": d,
    }
    for key, pvals in ((labels[0], p_values_a), (labels[1], p_values_b)):
        if pvals is not None:
            pv = np.asarray(list(pvals), dtype=float)
            pv = pv[np.isfinite(pv)]
            out[key]["proportion_significant"] = (
                float((pv < alpha).mean()) if pv.size else float("nan"))
    return out
