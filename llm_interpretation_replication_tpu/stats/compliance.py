"""Output/confidence compliance audits.

Behavioral replicas of analyze_perturbation_results.py:1191-1500 (did the model
literally obey "answer only X"?) and :1501-1718 (is the confidence reply a bare
integer 0-100?) — effectively behavioral tests of the prompt/parser contract.
"""

from __future__ import annotations

import ast
import json
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import pandas as pd

#: Expected first tokens / full responses per scenario (data contract —
#: analyze_perturbation_results.py:1206-1248)
EXPECTED_TOKENS = [
    {
        "first_tokens": ["Covered", "Not"],
        "full_responses": {
            "Covered": ["Covered"],
            "Not": ["Not Covered", "Not covered"],
        },
    },
    {
        "first_tokens": ["First", "Ultimate"],
        "full_responses": {
            "First": ["First Petition", "First petition"],
            "Ultimate": ["Ultimate Petition", "Ultimate petition"],
        },
    },
    {
        "first_tokens": ["Existing", "Future"],
        "full_responses": {
            "Existing": ["Existing Affiliates", "Existing affiliates"],
            "Future": ["Future Affiliates", "Future affiliates"],
        },
    },
    {
        "first_tokens": ["Monthly", "Payment"],
        "full_responses": {
            "Monthly": [
                "Monthly Installment Payments",
                "Monthly installment payments",
                "Monthly Installment Payment",
            ],
            "Payment": ["Payment Upon Completion", "Payment upon completion", "Payment Upon"],
        },
    },
    {
        "first_tokens": ["Covered", "Not"],
        "full_responses": {
            "Covered": ["Covered"],
            "Not": ["Not Covered", "Not covered"],
        },
    },
]


def parse_logprobs_field(value):
    """Parse the stringified 'Log Probabilities' column (JSON or repr)."""
    if isinstance(value, dict):
        return value
    if not isinstance(value, str):
        return None
    try:
        return json.loads(value)
    except (json.JSONDecodeError, ValueError):
        try:
            return ast.literal_eval(value)
        except (ValueError, SyntaxError):
            return None


def check_first_and_full(
    first_token: str, full_response: str, expected: Dict
) -> Tuple[bool, Optional[bool]]:
    """(first-token compliant, full-response compliant | None if first failed)."""
    matched = None
    for exp in expected["first_tokens"]:
        if first_token == exp or first_token.startswith(exp):
            matched = exp
            break
    if matched is None:
        return False, None
    norm_resp = full_response.replace(" ", "")
    for exp_full in expected["full_responses"].get(matched, []):
        norm_exp = exp_full.replace(" ", "")
        if full_response == exp_full or norm_resp == norm_exp or norm_resp.startswith(norm_exp):
            return True, True
    return True, False


def check_output_compliance(
    df: pd.DataFrame,
    expected_tokens: Sequence[Dict] = EXPECTED_TOKENS,
    response_col: str = "Model Response",
) -> pd.DataFrame:
    """Per-scenario compliance rates over a perturbation workbook.

    Prefers the API-style 'Log Probabilities' content tokens when parseable
    (first token + concatenated response); otherwise falls back to the text in
    ``response_col`` (first whitespace token + full string), which covers the
    local-TPU sweep rows.
    """
    results = []
    for idx, original in enumerate(df["Original Main Part"].unique()):
        if idx >= len(expected_tokens):
            continue
        expected = expected_tokens[idx]
        sub = df[df["Original Main Part"] == original]
        if "Relative_Prob" in sub.columns:
            sub = sub[np.isfinite(sub["Relative_Prob"])]
        total = len(sub)
        if total == 0:
            continue
        first_ok = first_bad = full_ok = full_bad = 0
        bad_first_examples: List[str] = []
        bad_full_examples: List[str] = []
        for _, row in sub.iterrows():
            first_token, full_response = None, None
            lp = parse_logprobs_field(row.get("Log Probabilities"))
            if lp and isinstance(lp, dict) and lp.get("content"):
                first_token = lp["content"][0].get("token", "")
                full_response = "".join(
                    t.get("token", "") for t in lp["content"]
                ).strip()
            else:
                text = str(row.get(response_col, "") or "")
                stripped = text.strip()
                first_token = stripped.split()[0] if stripped.split() else ""
                full_response = stripped
            ok1, ok2 = check_first_and_full(first_token, full_response, expected)
            if ok1:
                first_ok += 1
                if ok2:
                    full_ok += 1
                else:
                    full_bad += 1
                    if len(bad_full_examples) < 5:
                        bad_full_examples.append(full_response)
            else:
                first_bad += 1
                if len(bad_first_examples) < 5:
                    bad_first_examples.append(first_token)
        rec = {
            "Prompt": idx + 1,
            "Expected_First_Tokens": ", ".join(expected["first_tokens"]),
            "Total_Samples": total,
            "First_Token_Compliant": first_ok,
            "First_Token_Non_Compliant": first_bad,
            "First_Token_Compliance_Rate": 100.0 * first_ok / total,
            "First_Token_Non_Compliance_Rate": 100.0 * first_bad / total,
            "Non_Compliant_First_Examples": bad_first_examples,
            "Non_Compliant_Full_Examples": bad_full_examples,
        }
        if first_ok > 0:
            rec.update(
                {
                    "Conditional_Subsequent_Compliant": full_ok,
                    "Conditional_Subsequent_Non_Compliant": full_bad,
                    "Conditional_Subsequent_Compliance_Rate": 100.0 * full_ok / first_ok,
                }
            )
        results.append(rec)
    return pd.DataFrame(results)


def classify_confidence_response(value) -> str:
    """'compliant' | 'out_of_range' | 'float' | 'text' | 'other'."""
    s = str(value).strip()
    try:
        v = int(s)
        return "compliant" if 0 <= v <= 100 else "out_of_range"
    except ValueError:
        pass
    try:
        float(s)
        return "float"
    except ValueError:
        pass
    if any(c.isalpha() for c in s):
        return "text"
    return "other"


def check_confidence_compliance(df: pd.DataFrame) -> pd.DataFrame:
    """Per-scenario confidence-format compliance over the workbook."""
    results = []
    for idx, original in enumerate(df["Original Main Part"].unique()):
        sub = df[df["Original Main Part"] == original]
        sub = sub[sub["Model Confidence Response"].notna()]
        total = len(sub)
        if total == 0:
            continue
        counts = {"compliant": 0, "out_of_range": 0, "float": 0, "text": 0, "other": 0}
        for _, row in sub.iterrows():
            counts[classify_confidence_response(row["Model Confidence Response"])] += 1
        compliant = counts["compliant"]
        results.append(
            {
                "Prompt": idx + 1,
                "Total_Confidence_Samples": total,
                "Confidence_Compliant": compliant,
                "Confidence_Non_Compliant": total - compliant,
                "Confidence_Compliance_Rate": 100.0 * compliant / total,
                "Confidence_Non_Compliance_Rate": 100.0 * (total - compliant) / total,
                "Float_Errors": counts["float"],
                "Text_Errors": counts["text"],
                "Out_Of_Range_Errors": counts["out_of_range"],
                "Other_Errors": counts["other"],
            }
        )
    return pd.DataFrame(results)
