"""Zero/one-inflated clipped-normal fit with Monte-Carlo adequacy tests.

Behavioral replica of analyze_perturbation_results.py:113-337: find (μ, σ) of
an underlying normal whose [0,1]-clipped version matches the observed mean/std
(damped iterative search, max 30 iterations, 1e-4 convergence, direct mean
shift), with a scipy ``truncnorm`` alternative when the relative error stays
above 1%; adequacy via two-sample KS and k-sample Anderson-Darling against
100k simulated draws.

Improvement over the reference: an explicit seeded Generator instead of global
numpy state, so fits are reproducible.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np
from scipy import stats as scipy_stats

EPSILON = 1e-6


def simulate_clipped_normal(rng, mu: float, sigma: float, n: int) -> np.ndarray:
    return np.clip(rng.normal(mu, sigma, n), 0.0, 1.0)


def fit_clipped_normal(
    values,
    n_simulations: int = 100_000,
    seed: int = 42,
    max_iterations: int = 30,
    convergence_threshold: float = 1e-4,
    damping: float = 0.5,
) -> Tuple[Dict, np.ndarray]:
    """Fit + test; returns (results dict, simulated draws)."""
    values = np.asarray(values, dtype=float)
    values = values[np.isfinite(values)]
    if len(values) == 0:
        return {"fit": "failed-no-finite-values"}, np.array([])

    n_zeros = int(np.sum(values < EPSILON))
    n_ones = int(np.sum(values > 1 - EPSILON))
    zero_prop = n_zeros / len(values)
    one_prop = n_ones / len(values)
    interior = values[(values >= EPSILON) & (values <= 1 - EPSILON)]
    if len(interior) == 0:
        return (
            {
                "fit": "failed-all-boundary",
                "zero_proportion": zero_prop,
                "one_proportion": one_prop,
            },
            np.array([]),
        )

    target_mean = float(np.mean(values))
    target_std = float(np.std(values))
    rng = np.random.default_rng(seed)
    mu, sigma = target_mean, target_std

    for _ in range(max_iterations):
        sim = simulate_clipped_normal(rng, mu, sigma, n_simulations)
        sim_mean, sim_std = float(np.mean(sim)), float(np.std(sim))
        mean_diff = abs(sim_mean - target_mean)
        std_diff = abs(sim_std - target_std)
        if mean_diff < convergence_threshold and std_diff < convergence_threshold:
            break
        mean_adj = (target_mean / sim_mean) if sim_mean > 0 else 1.0
        std_adj = (target_std / sim_std) if sim_std > 0 else 1.0
        mu *= 1 + damping * (mean_adj - 1)
        sigma *= 1 + damping * (std_adj - 1)
        if mean_diff > 1e-3:
            mu += damping * (target_mean - sim_mean)

    simulated = simulate_clipped_normal(rng, mu, sigma, n_simulations)
    sim_mean, sim_std = float(np.mean(simulated)), float(np.std(simulated))
    mean_err = abs(sim_mean - target_mean) / target_mean if target_mean else abs(sim_mean)
    std_err = abs(sim_std - target_std) / target_std if target_std else abs(sim_std)

    if mean_err > 0.01 or std_err > 0.01:
        # scipy truncnorm alternative (truncates instead of clipping — no
        # boundary atoms, but sometimes matches moments better)
        try:
            a = (0 - mu) / sigma
            b = (1 - mu) / sigma
            alt = scipy_stats.truncnorm.rvs(
                a, b, loc=mu, scale=sigma, size=n_simulations, random_state=rng
            )
            alt_mean, alt_std = float(np.mean(alt)), float(np.std(alt))
            alt_mean_err = abs(alt_mean - target_mean) / target_mean if target_mean else abs(alt_mean)
            alt_std_err = abs(alt_std - target_std) / target_std if target_std else abs(alt_std)
            if alt_mean_err < mean_err and alt_std_err < std_err:
                simulated, sim_mean, sim_std = alt, alt_mean, alt_std
                mean_err, std_err = alt_mean_err, alt_std_err
        except Exception:
            pass

    ks_stat, ks_p = scipy_stats.ks_2samp(values, simulated)
    try:
        ad = scipy_stats.anderson_ksamp([values, simulated])
        ad_stat, ad_p = float(ad.statistic), float(ad.pvalue)
        ad_ok = ad_p > 0.05
    except Exception:
        ad_stat, ad_p, ad_ok = float("nan"), float("nan"), False

    results = {
        "fit": "ok",
        "model_type": "Truncated Normal with Zero/One Inflation",
        "underlying_mean": mu,
        "underlying_std": sigma,
        "observed_mean": target_mean,
        "observed_std": target_std,
        "simulated_mean": sim_mean,
        "simulated_std": sim_std,
        "mean_relative_error": mean_err,
        "std_relative_error": std_err,
        "zero_proportion": zero_prop,
        "one_proportion": one_prop,
        "interior_mean": float(np.mean(interior)),
        "interior_std": float(np.std(interior)),
        "ks_stat": float(ks_stat),
        "ks_p": float(ks_p),
        "ad_stat": ad_stat,
        "ad_p": ad_p,
        "adequate_ks": bool(ks_p > 0.05),
        "adequate_ad": bool(ad_ok),
        "adequate": bool(ks_p > 0.05) and bool(ad_ok),
    }
    return results, simulated
