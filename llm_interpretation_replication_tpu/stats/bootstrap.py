"""Bootstrap machinery: MAE CIs, MAE differences, paired diffs.

Behavioral replicas with the reference's seed discipline (seed 42,
``np.random.default_rng``, percentile method):

- ``bootstrap_mae`` — evaluate_closed_source_models.py:818-850 (scipy
  ``bootstrap`` over mean absolute error).
- ``bootstrap_mae_difference`` — ibid.:852-915 (resample-index difference with
  the two-sided sign-crossing p-value).
- ``paired_mean_diff_bootstrap`` — run_base_vs_instruct_100q.py:606-712 and
  analyze_base_vs_instruct_mae_100q.py:270-420 (instruct−base paired diffs).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import numpy as np
from scipy.stats import bootstrap as scipy_bootstrap


def bootstrap_mae(
    values: Sequence[float],
    n_bootstrap: int = 10_000,
    confidence_level: float = 0.95,
    seed: int = 42,
) -> Tuple[Optional[float], Optional[float], Optional[float]]:
    """(mean, ci_low, ci_high) of the mean of ``values`` (absolute errors)."""
    values = np.asarray(list(values), dtype=float)
    if values.size == 0:
        return None, None, None
    rng = np.random.default_rng(seed)
    res = scipy_bootstrap(
        (values,),
        np.mean,
        n_resamples=n_bootstrap,
        confidence_level=confidence_level,
        random_state=rng,
        method="percentile",
    )
    return (
        float(np.mean(values)),
        float(res.confidence_interval.low),
        float(res.confidence_interval.high),
    )


def bootstrap_mae_difference(
    model_values: Sequence[float],
    baseline_values,
    n_bootstrap: int = 10_000,
    confidence_level: float = 0.95,
    seed: int = 42,
):
    """(diff, ci_low, ci_high, p) for mean(model) − mean(baseline).

    Scalar baselines broadcast; mismatched lengths collapse the baseline to its
    mean (reference semantics).  p is the doubled one-sided sign-crossing
    proportion of the bootstrap distribution.
    """
    model = np.asarray(list(model_values), dtype=float)
    if model.size == 0:
        return None, None, None, None
    if np.isscalar(baseline_values):
        baseline = np.full_like(model, float(baseline_values))
    else:
        baseline = np.asarray(list(baseline_values), dtype=float)
        if baseline.size != model.size:
            baseline = np.full_like(model, float(np.mean(baseline)))
    observed = float(np.mean(model) - np.mean(baseline))
    rng = np.random.default_rng(seed)
    n = model.size
    idx = rng.choice(n, size=(n_bootstrap, n), replace=True)
    diffs = np.mean(model[idx], axis=1) - np.mean(baseline[idx], axis=1)
    alpha = 1 - confidence_level
    ci_low = float(np.percentile(diffs, 100 * alpha / 2))
    ci_high = float(np.percentile(diffs, 100 * (1 - alpha / 2)))
    if observed > 0:
        p = 2 * min(float(np.mean(diffs <= 0)), float(np.mean(diffs >= 0)))
    else:
        p = 2 * min(float(np.mean(diffs >= 0)), float(np.mean(diffs <= 0)))
    return observed, ci_low, ci_high, min(p, 1.0)


def paired_mean_diff_bootstrap(
    diffs: Sequence[float],
    n_bootstrap: int = 10_000,
    seed: int = 42,
) -> Dict:
    """Bootstrap of a paired-difference mean (e.g. instruct − base per prompt):
    CI + two-sided p against 0."""
    diffs = np.asarray(list(diffs), dtype=float)
    diffs = diffs[np.isfinite(diffs)]
    if diffs.size == 0:
        return {"n": 0}
    rng = np.random.default_rng(seed)
    idx = rng.choice(diffs.size, size=(n_bootstrap, diffs.size), replace=True)
    boot = np.mean(diffs[idx], axis=1)
    observed = float(np.mean(diffs))
    if observed > 0:
        p = 2 * float(np.mean(boot <= 0))
    else:
        p = 2 * float(np.mean(boot >= 0))
    return {
        "n": int(diffs.size),
        "mean_diff": observed,
        "mae": float(np.mean(np.abs(diffs))),
        "ci_lower": float(np.percentile(boot, 2.5)),
        "ci_upper": float(np.percentile(boot, 97.5)),
        "p_value": min(p, 1.0),
    }


def base_vs_instruct_analysis(df, value_col: str = "relative_prob",
                              n_bootstrap: int = 10_000, seed: int = 42) -> Dict[str, Dict]:
    """Per-family instruct−base paired bootstrap over a 100q results frame
    (columns model_family / base_or_instruct / prompt / value_col)."""
    import pandas as pd

    out: Dict[str, Dict] = {}
    for family in df["model_family"].unique():
        fam = df[df["model_family"] == family]
        base = fam[fam["base_or_instruct"] == "base"]
        inst = fam[fam["base_or_instruct"] == "instruct"]
        merged = pd.merge(
            base[["prompt", value_col]],
            inst[["prompt", value_col]],
            on="prompt",
            suffixes=("_base", "_instruct"),
        ).dropna()
        if len(merged) < 10:
            out[family] = {"n": len(merged), "skipped": True}
            continue
        diffs = merged[f"{value_col}_instruct"].values - merged[f"{value_col}_base"].values
        out[family] = paired_mean_diff_bootstrap(diffs, n_bootstrap, seed)
    return out


def bootstrap_statistic(
    values: Sequence[float],
    statistic=np.mean,
    n_bootstrap: int = 1000,
    confidence_level: float = 0.95,
    seed: int = 42,
) -> Dict:
    """Generic percentile bootstrap of any statistic (the survey pipeline's
    helper — bootstrap_confidence_intervals.py)."""
    values = np.asarray(list(values), dtype=float)
    rng = np.random.default_rng(seed)
    idx = rng.choice(values.size, size=(n_bootstrap, values.size), replace=True)
    boots = np.array([statistic(values[row]) for row in idx])
    alpha = 1 - confidence_level
    return {
        "estimate": float(statistic(values)),
        "ci_lower": float(np.percentile(boots, 100 * alpha / 2)),
        "ci_upper": float(np.percentile(boots, 100 * (1 - alpha / 2))),
        "n": int(values.size),
    }
