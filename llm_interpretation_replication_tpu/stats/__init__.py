from .bootstrap import (
    base_vs_instruct_analysis,
    bootstrap_mae,
    bootstrap_mae_difference,
    bootstrap_statistic,
    paired_mean_diff_bootstrap,
)
from .compliance import (
    EXPECTED_TOKENS,
    check_confidence_compliance,
    check_first_and_full,
    check_output_compliance,
    classify_confidence_response,
)
from .correlations import (
    cohens_kappa,
    compare_correlation_distributions,
    correlation_summary_bootstrap,
    fisher_z_pvalue,
    pairwise_correlations,
    pairwise_kappa,
    pivot_model_values,
)
from .normality import ad_pvalue_from_bands, normality_tests
from .power import power_curve, power_report, required_sample_size, simulated_power
from .similarity import (
    BM25Okapi,
    bm25_similarity_matrix,
    calculate_all_similarities,
    levenshtein_similarity_matrix,
    tfidf_cosine_matrix,
)
from .truncated import fit_clipped_normal, simulate_clipped_normal
