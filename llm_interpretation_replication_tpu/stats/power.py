"""Power analysis: analytic sample-size requirements + simulated power.

Behavioral replica of power_analysis.py:10-95 (one-sample t-test framing over
MAE differences from a baseline, with the t-correction and safety margin, and
seeded Monte-Carlo power curves).
"""

from __future__ import annotations

from typing import Dict, Sequence

import numpy as np
from scipy import stats as scipy_stats

POWER_LEVELS = (0.70, 0.80, 0.85, 0.90, 0.95)


def required_sample_size(
    observed_mae_diff: float,
    observed_std: float,
    alpha: float = 0.05,
    margin_factor: float = 1.5,
    power_levels: Sequence[float] = POWER_LEVELS,
) -> Dict:
    effect_size = abs(observed_mae_diff) / observed_std if observed_std > 0 else 0.0
    sample_sizes = {}
    for target_power in power_levels:
        key = f"power_{int(target_power * 100)}"
        if effect_size > 0:
            z_alpha = scipy_stats.norm.ppf(1 - alpha / 2)
            z_beta = scipy_stats.norm.ppf(target_power)
            n = ((z_alpha + z_beta) / effect_size) ** 2
            if n > 2:
                n = n * (1 + 1 / (4 * (n - 1)))  # t-distribution correction
            sample_sizes[key] = {
                "raw": int(np.ceil(n)),
                "with_margin": int(np.ceil(n * margin_factor)),
            }
        else:
            sample_sizes[key] = {"raw": np.inf, "with_margin": np.inf}
    return {
        "effect_size": effect_size,
        "sample_sizes": sample_sizes,
        "observed_mae_diff": observed_mae_diff,
        "observed_std": observed_std,
    }


def simulated_power(
    mae_diff: float,
    std: float,
    sample_size: int,
    n_simulations: int = 10_000,
    alpha: float = 0.05,
    seed: int = 42,
) -> float:
    """Proportion of seeded simulations where a one-sample t-test vs 0 rejects."""
    rng = np.random.default_rng(seed)
    samples = rng.normal(mae_diff, std, size=(n_simulations, sample_size))
    _, p = scipy_stats.ttest_1samp(samples, 0.0, axis=1)
    return float(np.mean(p < alpha))


def power_curve(
    mae_diff: float,
    std: float,
    sample_sizes: Sequence[int],
    n_simulations: int = 2000,
    alpha: float = 0.05,
    seed: int = 42,
) -> Dict[int, float]:
    return {
        int(n): simulated_power(mae_diff, std, int(n), n_simulations, alpha, seed)
        for n in sample_sizes
    }


def power_report(
    model_results: Dict[str, Dict],
    baseline_mae: float,
    sample_size: int,
    alpha: float = 0.05,
    n_simulations: int = 10_000,
    output_tex: str = None,
) -> Dict:
    """Full power-analysis report (power_analysis.py `main`, :96-278).

    ``model_results`` maps model name -> {"mae", "mae_std", "mae_diff",
    "ci_lower", "ci_upper"}.  Computes per-model effect sizes, required
    sample sizes at every power level, simulated power at the current
    ``sample_size``, and the 80%/90%-power recommendation (the max over
    models, i.e. the smallest effect is the limiting factor).  Optionally
    writes a LaTeX table (``power_analysis_report.tex``).
    """
    report: Dict = {"models": {}, "baseline_mae": baseline_mae,
                    "sample_size": sample_size}
    for name, res in model_results.items():
        analysis = required_sample_size(
            res["mae_diff"], res["mae_std"], alpha=alpha
        )
        analysis["achieved_power"] = simulated_power(
            res["mae_diff"], res["mae_std"], sample_size,
            n_simulations=n_simulations, alpha=alpha,
        )
        analysis["significant"] = not (
            res.get("ci_lower", -np.inf) <= 0 <= res.get("ci_upper", np.inf)
        )
        report["models"][name] = analysis

    def _max_required(level: str):
        best_n, best_margin, limiting = 0, 0, None
        for name, analysis in report["models"].items():
            sizes = analysis["sample_sizes"][level]
            if sizes["raw"] > best_n:
                best_n, best_margin, limiting = sizes["raw"], sizes["with_margin"], name
        # a zero-effect model keeps raw=inf: no N can power it, and the
        # recommendation must say so rather than silently dropping the model
        return {"raw": best_n, "with_margin": best_margin, "limiting_model": limiting}

    report["recommendation"] = {
        "power_80": _max_required("power_80"),
        "power_90": _max_required("power_90"),
    }

    if output_tex:
        from ..viz.latex import power_analysis_table

        with open(output_tex, "w") as f:
            f.write(power_analysis_table(report, alpha=alpha,
                                         sample_size=sample_size))
        report["tex_path"] = output_tex
    return report
