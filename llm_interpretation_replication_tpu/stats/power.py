"""Power analysis: analytic sample-size requirements + simulated power.

Behavioral replica of power_analysis.py:10-95 (one-sample t-test framing over
MAE differences from a baseline, with the t-correction and safety margin, and
seeded Monte-Carlo power curves).
"""

from __future__ import annotations

from typing import Dict, Sequence

import numpy as np
from scipy import stats as scipy_stats

POWER_LEVELS = (0.70, 0.80, 0.85, 0.90, 0.95)


def required_sample_size(
    observed_mae_diff: float,
    observed_std: float,
    alpha: float = 0.05,
    margin_factor: float = 1.5,
    power_levels: Sequence[float] = POWER_LEVELS,
) -> Dict:
    effect_size = abs(observed_mae_diff) / observed_std if observed_std > 0 else 0.0
    sample_sizes = {}
    for target_power in power_levels:
        key = f"power_{int(target_power * 100)}"
        if effect_size > 0:
            z_alpha = scipy_stats.norm.ppf(1 - alpha / 2)
            z_beta = scipy_stats.norm.ppf(target_power)
            n = ((z_alpha + z_beta) / effect_size) ** 2
            if n > 2:
                n = n * (1 + 1 / (4 * (n - 1)))  # t-distribution correction
            sample_sizes[key] = {
                "raw": int(np.ceil(n)),
                "with_margin": int(np.ceil(n * margin_factor)),
            }
        else:
            sample_sizes[key] = {"raw": np.inf, "with_margin": np.inf}
    return {
        "effect_size": effect_size,
        "sample_sizes": sample_sizes,
        "observed_mae_diff": observed_mae_diff,
        "observed_std": observed_std,
    }


def simulated_power(
    mae_diff: float,
    std: float,
    sample_size: int,
    n_simulations: int = 10_000,
    alpha: float = 0.05,
    seed: int = 42,
) -> float:
    """Proportion of seeded simulations where a one-sample t-test vs 0 rejects."""
    rng = np.random.default_rng(seed)
    samples = rng.normal(mae_diff, std, size=(n_simulations, sample_size))
    _, p = scipy_stats.ttest_1samp(samples, 0.0, axis=1)
    return float(np.mean(p < alpha))


def power_curve(
    mae_diff: float,
    std: float,
    sample_sizes: Sequence[int],
    n_simulations: int = 2000,
    alpha: float = 0.05,
    seed: int = 42,
) -> Dict[int, float]:
    return {
        int(n): simulated_power(mae_diff, std, int(n), n_simulations, alpha, seed)
        for n in sample_sizes
    }
