"""Normality tests: KS + Anderson-Darling against a fitted normal.

Behavioral replica of analyze_perturbation_results.py:21-110, including the
reference's banded AD p-value approximation from the critical-value table
(scipy provides no AD p-value for the normal case).
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np
from scipy import stats as scipy_stats


# Known AD critical-value tables for the 'norm' case.  scipy 1.17 revised
# BOTH the asymptotic table (Stephens' values → recomputed ones) and the
# finite-n correction divisor, so critical values (and hence the banded
# p-values and normal/not-normal flags) shift between scipy eras.  The
# reference's recorded analyses were produced on a legacy-table scipy;
# pinning code detects the active era and compares bit-exactly against the
# matching table instead of a loose tolerance (PARITY.md §6).
AD_NORM_TABLES = {
    # scipy < 1.17: Stephens (1974) via D'Agostino correction
    "legacy": ((0.576, 0.656, 0.787, 0.918, 1.092),
               lambda n: 1.0 + 4.0 / n - 25.0 / n ** 2),
    # scipy >= 1.17: revised table + 1 + 0.75/n + 2.25/n^2 correction
    "scipy117": ((0.561, 0.631, 0.752, 0.873, 1.035),
                 lambda n: 1.0 + 0.75 / n + 2.25 / n ** 2),
}


def ad_critical_values(n: int, version: str) -> np.ndarray:
    """The five AD critical values scipy's anderson(..., 'norm') returns for
    a sample of size ``n`` under the given table era (3-decimal rounding
    exactly as scipy applies it)."""
    base, correction = AD_NORM_TABLES[version]
    return np.around(np.asarray(base) / correction(n), 3)


def active_ad_table_version(probe_n: int = 100) -> str:
    """Which AD table era the INSTALLED scipy uses, detected empirically:
    run anderson() on a fixed sample and match the returned critical values
    against each known table.  Returns 'unknown' for a future scipy whose
    table matches neither — callers should fail loudly and add the new era
    to AD_NORM_TABLES."""
    import warnings

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        res = scipy_stats.anderson(np.linspace(-2.0, 2.0, probe_n), "norm")
    crit = np.asarray(res.critical_values, dtype=float)
    for version in AD_NORM_TABLES:
        if np.array_equal(crit, ad_critical_values(probe_n, version)):
            return version
    return "unknown"


def ad_pvalue_from_bands(ad_statistic: float, critical_values) -> float:
    """Reference's banded approximation (index 2 = 5% level)."""
    if ad_statistic > 10:
        return 0.0001
    if ad_statistic > critical_values[4]:
        return 0.005
    if ad_statistic > critical_values[3]:
        return 0.015
    if ad_statistic > critical_values[2]:
        return 0.035
    if ad_statistic > critical_values[1]:
        return 0.075
    return 0.15


def normality_tests(values, label: Optional[str] = None) -> Dict:
    """KS + AD tests of ``values`` against a normal fitted to them.

    Returns the reference's result fields; non-finite values are dropped, and
    n<3 yields a degenerate record with NaN statistics.
    """
    values = np.asarray(values, dtype=float)
    values = values[np.isfinite(values)]
    base = {"label": label, "n": int(len(values))}
    if len(values) < 3:
        return {
            **base,
            "mean": float(np.mean(values)) if len(values) else float("nan"),
            "std": float(np.std(values)) if len(values) > 1 else float("nan"),
            "ks_stat": float("nan"),
            "ks_p": float("nan"),
            "ks_normal": False,
            "ad_stat": float("nan"),
            "ad_p": float("nan"),
            "ad_crit_5pct": float("nan"),
            "ad_normal": False,
        }
    mu, sigma = scipy_stats.norm.fit(values)
    ks_stat, ks_p = scipy_stats.kstest(values, "norm", args=(mu, sigma))
    ad = scipy_stats.anderson(values, "norm")
    ad_p = ad_pvalue_from_bands(ad.statistic, ad.critical_values)
    return {
        **base,
        "mean": float(mu),
        "std": float(sigma),
        "ks_stat": float(ks_stat),
        "ks_p": float(ks_p),
        "ks_normal": bool(ks_p > 0.05),
        "ad_stat": float(ad.statistic),
        "ad_p": float(ad_p),
        "ad_crit_5pct": float(ad.critical_values[2]),
        "ad_normal": bool(ad.statistic < ad.critical_values[2]),
    }
