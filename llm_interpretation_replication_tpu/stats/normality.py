"""Normality tests: KS + Anderson-Darling against a fitted normal.

Behavioral replica of analyze_perturbation_results.py:21-110, including the
reference's banded AD p-value approximation from the critical-value table
(scipy provides no AD p-value for the normal case).
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np
from scipy import stats as scipy_stats


def ad_pvalue_from_bands(ad_statistic: float, critical_values) -> float:
    """Reference's banded approximation (index 2 = 5% level)."""
    if ad_statistic > 10:
        return 0.0001
    if ad_statistic > critical_values[4]:
        return 0.005
    if ad_statistic > critical_values[3]:
        return 0.015
    if ad_statistic > critical_values[2]:
        return 0.035
    if ad_statistic > critical_values[1]:
        return 0.075
    return 0.15


def normality_tests(values, label: Optional[str] = None) -> Dict:
    """KS + AD tests of ``values`` against a normal fitted to them.

    Returns the reference's result fields; non-finite values are dropped, and
    n<3 yields a degenerate record with NaN statistics.
    """
    values = np.asarray(values, dtype=float)
    values = values[np.isfinite(values)]
    base = {"label": label, "n": int(len(values))}
    if len(values) < 3:
        return {
            **base,
            "mean": float(np.mean(values)) if len(values) else float("nan"),
            "std": float(np.std(values)) if len(values) > 1 else float("nan"),
            "ks_stat": float("nan"),
            "ks_p": float("nan"),
            "ks_normal": False,
            "ad_stat": float("nan"),
            "ad_p": float("nan"),
            "ad_crit_5pct": float("nan"),
            "ad_normal": False,
        }
    mu, sigma = scipy_stats.norm.fit(values)
    ks_stat, ks_p = scipy_stats.kstest(values, "norm", args=(mu, sigma))
    ad = scipy_stats.anderson(values, "norm")
    ad_p = ad_pvalue_from_bands(ad.statistic, ad.critical_values)
    return {
        **base,
        "mean": float(mu),
        "std": float(sigma),
        "ks_stat": float(ks_stat),
        "ks_p": float(ks_p),
        "ks_normal": bool(ks_p > 0.05),
        "ad_stat": float(ad.statistic),
        "ad_p": float(ad_p),
        "ad_crit_5pct": float(ad.critical_values[2]),
        "ad_normal": bool(ad.statistic < ad.critical_values[2]),
    }
