"""Prompt-similarity validator (rephrasings stay close to their originals).

Behavioral replica of calculate_prompt_similarity.py:76-207 with an in-package
Okapi BM25 (rank_bm25 is not in this image) and the native C Levenshtein
kernel; sentence-transformer embeddings stay optional/gated exactly like the
reference.
"""

from __future__ import annotations

import math
from collections import Counter
from itertools import combinations
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..native import normalized_levenshtein_similarity


def tfidf_cosine_matrix(texts: Sequence[str]) -> np.ndarray:
    from sklearn.feature_extraction.text import TfidfVectorizer
    from sklearn.metrics.pairwise import cosine_similarity

    vec = TfidfVectorizer()
    tfidf = vec.fit_transform(list(texts))
    return cosine_similarity(tfidf)


class BM25Okapi:
    """Okapi BM25 (k1=1.5, b=0.75, rank_bm25-compatible idf with floor)."""

    def __init__(self, corpus: Sequence[Sequence[str]], k1: float = 1.5,
                 b: float = 0.75, epsilon: float = 0.25):
        self.k1 = k1
        self.b = b
        self.corpus = [list(doc) for doc in corpus]
        self.doc_len = [len(doc) for doc in self.corpus]
        self.avgdl = sum(self.doc_len) / max(len(self.corpus), 1)
        self.doc_freqs: List[Counter] = [Counter(doc) for doc in self.corpus]
        df: Counter = Counter()
        for counts in self.doc_freqs:
            df.update(counts.keys())
        n = len(self.corpus)
        # rank_bm25's idf: log((N - df + 0.5)/(df + 0.5)); negative idfs are
        # replaced by epsilon * average idf, where the average runs over ALL
        # terms (negative contributions included in both sum and count —
        # rank_bm25 BM25Okapi._calc_idf exactly; pinned bit-exact against the
        # reference's recorded similarity workbook in
        # tests/test_published_regression.py)
        idf = {}
        negative = []
        total = 0.0
        for term, freq in df.items():
            v = math.log((n - freq + 0.5) / (freq + 0.5))
            idf[term] = v
            total += v
            if v < 0:
                negative.append(term)
        avg_idf = total / max(len(idf), 1)
        for term in negative:
            idf[term] = epsilon * avg_idf
        self.idf = idf

    def get_scores(self, query: Sequence[str]) -> np.ndarray:
        scores = np.zeros(len(self.corpus))
        for term in query:
            idf = self.idf.get(term)
            if idf is None:
                continue
            for i, counts in enumerate(self.doc_freqs):
                f = counts.get(term, 0)
                if not f:
                    continue
                denom = f + self.k1 * (1 - self.b + self.b * self.doc_len[i] / self.avgdl)
                scores[i] += idf * f * (self.k1 + 1) / denom
        return scores


def bm25_similarity_matrix(texts: Sequence[str]) -> np.ndarray:
    tokenized = [t.lower().split() for t in texts]
    bm25 = BM25Okapi(tokenized)
    sim = np.zeros((len(texts), len(texts)))
    for i, query in enumerate(tokenized):
        scores = bm25.get_scores(query)
        max_score = scores.max() if scores.max() > 0 else 1.0
        sim[i] = scores / max_score
    return (sim + sim.T) / 2


def levenshtein_similarity_matrix(texts: Sequence[str]) -> np.ndarray:
    n = len(texts)
    sim = np.zeros((n, n))
    for i in range(n):
        sim[i, i] = 1.0
        for j in range(i + 1, n):
            s = normalized_levenshtein_similarity(texts[i], texts[j])
            sim[i, j] = sim[j, i] = s
    return sim


def calculate_all_similarities(
    original: str,
    rephrasings: Sequence[str],
    embedding_model=None,
) -> Dict:
    """Original-vs-rephrasings + pairwise similarities and summary stats."""
    all_texts = [original] + list(rephrasings)
    if embedding_model is not None:
        emb = embedding_model.encode(all_texts)
        emb = np.asarray(emb)
        norm = emb / np.linalg.norm(emb, axis=1, keepdims=True)
        embedding_sim = norm @ norm.T
    else:
        embedding_sim = None
    tfidf_sim = tfidf_cosine_matrix(all_texts)
    bm25_sim = bm25_similarity_matrix(all_texts)
    lev_sim = levenshtein_similarity_matrix(all_texts)

    def record(i, j):
        rec = {
            "tfidf_cosine_similarity": float(tfidf_sim[i, j]),
            "bm25_similarity": float(bm25_sim[i, j]),
            "levenshtein_similarity": float(lev_sim[i, j]),
            "embedding_cosine_similarity": (
                float(embedding_sim[i, j]) if embedding_sim is not None else None
            ),
        }
        return rec

    original_vs = []
    for idx, rephrasing in enumerate(rephrasings):
        original_vs.append(
            {"rephrasing_index": idx, "rephrasing": rephrasing, **record(0, idx + 1)}
        )
    pairwise = []
    for i, j in combinations(range(len(rephrasings)), 2):
        pairwise.append(
            {
                "rephrasing_1_index": i,
                "rephrasing_2_index": j,
                **record(i + 1, j + 1),
            }
        )

    metrics = ["tfidf_cosine_similarity", "bm25_similarity", "levenshtein_similarity"]
    if embedding_sim is not None:
        metrics.insert(0, "embedding_cosine_similarity")
    summary = {}
    for metric in metrics:
        ov = [r[metric] for r in original_vs if r[metric] is not None]
        pw = [r[metric] for r in pairwise if r[metric] is not None]
        if not ov or not pw:
            continue
        summary[metric] = {
            "original_vs_rephrasings": _stats(ov),
            "pairwise_rephrasings": _stats(pw),
        }
    return {
        "original_vs_rephrasings": original_vs,
        "pairwise_rephrasings": pairwise,
        "summary_stats": summary,
    }


def _stats(values):
    return {
        "mean": float(np.mean(values)),
        "std": float(np.std(values)),
        "min": float(np.min(values)),
        "max": float(np.max(values)),
        "median": float(np.median(values)),
    }
