from .anthropic_client import AnthropicClient
from .anthropic_client import build_batch_request as build_anthropic_batch_request
from .cache import REQUIRED_FIELDS, ResponseCache, cache_key
from .cost import CostTracker
from .evaluators import (
    evaluate_claude,
    evaluate_gemini_binary,
    evaluate_gemini_confidence,
    evaluate_gpt_binary,
    evaluate_gpt_confidence,
    evaluate_normal_baseline,
    evaluate_random_baseline,
    first_token_target_probs,
)
from .gemini_client import (
    GeminiClient,
    extract_text_from_response_string,
    repair_batch_responses,
)
from .openai_client import OpenAIClient
from .openai_client import build_batch_request as build_openai_batch_request
from .openai_client import is_reasoning_model
from .transport import FakeTransport, TransportError, UrllibTransport
