"""Anthropic client: sync messages + Message Batches.

Behavioral spec from perturb_prompts_claude.py and
perturb_prompts_claude_batch.py: Claude exposes no logprobs, so the binary leg
is a deterministic single reply (probs zeroed) or ``approximate_logprobs`` =
N repeated samples counted per target token (:124-157); batches cap at 10,000
requests with a 30 s poll up to 24 h (:26, 200-241).
"""

from __future__ import annotations

import json
import time
from typing import Dict, List, Optional, Sequence, Tuple

from ..utils.retry import RetryPolicy, retry_with_exponential_backoff
from .transport import TransportError, UrllibTransport

BASE_URL = "https://api.anthropic.com/v1"
API_VERSION = "2023-06-01"
MAX_BATCH_SIZE = 10_000


class AnthropicClient:
    def __init__(self, api_key: str, transport=None, base_url: str = BASE_URL,
                 retry_policy: Optional[RetryPolicy] = None):
        self.api_key = api_key
        self.transport = transport or UrllibTransport()
        self.base_url = base_url
        self.retry_policy = retry_policy or RetryPolicy(
            retry_on=(TransportError,), max_retries=10
        )

    def _request(self, method: str, path: str, json_body=None):
        headers = {
            "x-api-key": self.api_key,
            "anthropic-version": API_VERSION,
        }

        @retry_with_exponential_backoff(self.retry_policy)
        def call():
            try:
                status, body = self.transport.request(
                    method, f"{self.base_url}{path}", headers, json_body
                )
            except TransportError as err:
                if not err.retryable:
                    raise RuntimeError(str(err)) from err
                raise
            return body

        return call()

    def create_message(self, model: str, messages: Sequence[Dict],
                       max_tokens: int = 500, temperature: float = 0.0) -> Dict:
        return json.loads(
            self._request(
                "POST", "/messages",
                json_body={
                    "model": model,
                    "max_tokens": max_tokens,
                    "temperature": temperature,
                    "messages": list(messages),
                },
            )
        )

    @staticmethod
    def text_of(message: Dict) -> str:
        return "".join(
            block.get("text", "") for block in message.get("content", [])
            if block.get("type") == "text"
        ).strip()

    def approximate_logprobs(
        self,
        model: str,
        messages: Sequence[Dict],
        target_tokens: Sequence[str],
        n_samples: int = 10,
        temperature: float = 1.0,
        max_tokens: int = 500,
    ) -> Tuple[Dict[str, float], List[str]]:
        """Frequency-based probability estimate over repeated samples
        (perturb_prompts_claude.py:124-157).  Faithful quirks: the FIRST
        matching target in target order is counted (so 'Not Covered' counts as
        'Covered' when targets are ('Covered', 'Not')), and zero matches fall
        back to a uniform distribution."""
        counts = {t: 0 for t in target_tokens}
        texts = []
        for _ in range(n_samples):
            msg = self.create_message(model, messages, max_tokens, temperature)
            text = self.text_of(msg)
            texts.append(text)
            for t in target_tokens:
                if t in text:
                    counts[t] += 1
                    break
        if sum(counts.values()) == 0:
            probs = {t: 1.0 / len(target_tokens) for t in target_tokens}
        else:
            probs = {t: c / n_samples for t, c in counts.items()}
        return probs, texts

    # -- message batches --------------------------------------------------

    def create_batch(self, requests: Sequence[Dict]) -> Dict:
        if len(requests) > MAX_BATCH_SIZE:
            raise ValueError(f"batch of {len(requests)} exceeds {MAX_BATCH_SIZE}")
        return json.loads(
            self._request("POST", "/messages/batches", json_body={"requests": list(requests)})
        )

    def get_batch(self, batch_id: str) -> Dict:
        return json.loads(self._request("GET", f"/messages/batches/{batch_id}"))

    def wait_for_batch(self, batch_id: str, poll_interval: float = 30.0,
                       timeout: float = 24 * 3600, sleep=time.sleep,
                       clock=time.monotonic) -> Dict:
        """Poll until ``processing_status == "ended"``; elapsed time uses a
        monotonic clock (injectable) so request latency and retry backoffs
        count toward ``timeout``, not just the sleeps."""
        started = clock()
        while True:
            batch = self.get_batch(batch_id)
            if batch.get("processing_status") == "ended":
                return batch
            if clock() - started >= timeout:
                raise TimeoutError(f"batch {batch_id} not done after {timeout}s")
            sleep(poll_interval)

    def batch_results(self, batch: Dict) -> List[Dict]:
        raw = self._request("GET", f"/messages/batches/{batch['id']}/results")
        return [json.loads(line) for line in raw.decode().splitlines() if line.strip()]

    def run_batches(self, requests: Sequence[Dict], poll_interval: float = 30.0,
                    sleep=time.sleep) -> List[Dict]:
        results: List[Dict] = []
        for start in range(0, len(requests), MAX_BATCH_SIZE):
            chunk = list(requests[start : start + MAX_BATCH_SIZE])
            batch = self.create_batch(chunk)
            batch = self.wait_for_batch(batch["id"], poll_interval, sleep=sleep)
            results.extend(self.batch_results(batch))
        return results


def build_batch_request(custom_id: str, model: str, messages: Sequence[Dict],
                        max_tokens: int = 500, temperature: float = 0.0) -> Dict:
    return {
        "custom_id": custom_id,
        "params": {
            "model": model,
            "max_tokens": max_tokens,
            "temperature": temperature,
            "messages": list(messages),
        },
    }
