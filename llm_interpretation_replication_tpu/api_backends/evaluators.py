"""Per-vendor question evaluators + baselines.

Behavioral replicas of evaluate_closed_source_models.py: each evaluator runs a
binary leg (target-token probabilities from first-token top-logprobs where the
vendor provides them) and a confidence leg (verbalized 0-100 + logprob-weighted
confidence); plus the Random and Normal baselines (:458-512) and the
batch-request builders of perturb_prompts.py:190-269.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..scoring.confidence import (
    extract_first_int,
    weighted_confidence_digits,
    weighted_confidence_single_tokens,
)

BINARY_SUFFIX = "Answer either 'Yes' or 'No', without any other text."
CONFIDENCE_SUFFIX = (
    "How confident are you that the answer is Yes, on a scale from 0 (not "
    "confident) to 100 (most confident)? Answer only with a number, without "
    "any other explanation."
)


def first_token_target_probs(
    top_logprobs: Sequence[Dict], targets: Tuple[str, str]
) -> Tuple[float, float]:
    """exp(logprob) of each target among the FIRST token's top-logprobs; 0 when
    absent (perturb_prompts.py:480-498)."""
    p1 = p2 = 0.0
    for item in top_logprobs:
        token = item.get("token")
        if token == targets[0]:
            p1 = math.exp(item["logprob"])
        elif token == targets[1]:
            p2 = math.exp(item["logprob"])
    return p1, p2


def openai_content_and_logprobs(response: Dict):
    choice = response["choices"][0]
    text = (choice["message"].get("content") or "").strip()
    content = (choice.get("logprobs") or {}).get("content") or []
    return text, content


def evaluate_gpt_binary(client, model: str, question: str,
                        targets: Tuple[str, str] = ("Yes", "No")) -> Dict:
    resp = client.chat_completion(
        model, [{"role": "user", "content": f"{question} {BINARY_SUFFIX}"}]
    )
    text, content = openai_content_and_logprobs(resp)
    top = content[0].get("top_logprobs", []) if content else []
    p1, p2 = first_token_target_probs(top, targets)
    total = p1 + p2
    return {
        "response": text,
        "yes_prob": p1,
        "no_prob": p2,
        "relative_prob": p1 / total if total > 0 else 0.5,
        "raw": resp,
    }


def evaluate_gpt_confidence(client, model: str, question: str) -> Dict:
    resp = client.chat_completion(
        model, [{"role": "user", "content": f"{question} {CONFIDENCE_SUFFIX}"}]
    )
    text, content = openai_content_and_logprobs(resp)
    positions = [
        [(i["token"], i["logprob"]) for i in tok.get("top_logprobs", [])]
        for tok in content
    ]
    return {
        "response": text,
        "confidence": extract_first_int(text),
        "weighted_confidence": weighted_confidence_single_tokens(positions),
        "raw": resp,
    }


def evaluate_gemini_binary(client, model: str, question: str,
                           targets: Tuple[str, str] = ("Yes", "No")) -> Dict:
    resp = client.generate_content(
        model, f"{question} {BINARY_SUFFIX}", response_logprobs=True
    )
    text = client.text_of(resp)
    positions = client.top_candidates_of(resp)
    p1 = p2 = 0.0
    if positions:
        for token, logprob in positions[0]:
            if token.strip() == targets[0]:
                p1 = math.exp(logprob)
            elif token.strip() == targets[1]:
                p2 = math.exp(logprob)
    total = p1 + p2
    return {
        "response": text,
        "yes_prob": p1,
        "no_prob": p2,
        "relative_prob": p1 / total if total > 0 else 0.5,
        "raw": resp,
    }


def evaluate_gemini_confidence(client, model: str, question: str) -> Dict:
    resp = client.generate_content(
        model, f"{question} {CONFIDENCE_SUFFIX}", response_logprobs=True
    )
    text = client.text_of(resp)
    positions = client.top_candidates_of(resp)
    return {
        "response": text,
        "confidence": extract_first_int(text),
        "weighted_confidence": weighted_confidence_digits(positions),
        "raw": resp,
    }


def evaluate_claude(client, model: str, question: str,
                    sleep=None, delay: float = 0.0) -> Dict:
    """Claude has no logprobs: binary text + verbalized confidence only
    (evaluate_closed_source_models.py:514-552).  ``sleep``/``delay`` pace the
    two requests like the reference's CLAUDE_DELAY after EACH call (:716,719)
    — the pause must sit between the calls, not after the pair."""
    binary = client.create_message(
        model, [{"role": "user", "content": f"{question} {BINARY_SUFFIX}"}]
    )
    if sleep is not None:
        sleep(delay)
    confidence = client.create_message(
        model, [{"role": "user", "content": f"{question} {CONFIDENCE_SUFFIX}"}]
    )
    conf_text = client.text_of(confidence)
    return {
        "response": client.text_of(binary),
        "confidence": extract_first_int(conf_text),
        "confidence_response": conf_text,
    }


def evaluate_random_baseline(rng: Optional[np.random.Generator] = None) -> Dict:
    """Uniform Yes/No + uniform confidence (reference :458-475)."""
    rng = rng or np.random.default_rng()
    answer = "Yes" if rng.random() < 0.5 else "No"
    return {
        "response": answer,
        "relative_prob": 1.0 if answer == "Yes" else 0.0,
        "confidence": int(rng.integers(0, 101)),
    }


def evaluate_normal_baseline(human_mean: float, human_std: float,
                             rng: Optional[np.random.Generator] = None) -> Dict:
    """Draw from N(human μ, σ) clipped to [0,1] (reference :477-512)."""
    rng = rng or np.random.default_rng()
    value = float(np.clip(rng.normal(human_mean, human_std), 0.0, 1.0))
    return {
        "response": "Yes" if value >= 0.5 else "No",
        "relative_prob": value,
        "confidence": int(round(value * 100)),
    }
