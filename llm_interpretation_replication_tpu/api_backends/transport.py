"""HTTP transport for the vendor API clients.

The image ships no openai/anthropic SDKs, so the clients speak HTTP directly
through this thin transport (urllib, stdlib-only).  The transport is
injectable: tests drive the full client logic with ``FakeTransport``; the
zero-egress build never needs a socket until deployed with real keys.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
import uuid
from typing import Dict, Optional, Tuple


class TransportError(Exception):
    def __init__(self, status: int, body: str, retryable: bool):
        super().__init__(f"HTTP {status}: {body[:200]}")
        self.status = status
        self.body = body
        self.retryable = retryable


RETRYABLE_STATUS = {408, 409, 425, 429, 500, 502, 503, 504, 529}


class UrllibTransport:
    def __init__(self, timeout: float = 120.0):
        self.timeout = timeout

    def request(
        self,
        method: str,
        url: str,
        headers: Optional[Dict[str, str]] = None,
        json_body=None,
        data: Optional[bytes] = None,
    ) -> Tuple[int, bytes]:
        body = data
        headers = dict(headers or {})
        if json_body is not None:
            body = json.dumps(json_body).encode()
            headers.setdefault("Content-Type", "application/json")
        req = urllib.request.Request(url, data=body, headers=headers, method=method)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return resp.status, resp.read()
        except urllib.error.HTTPError as err:
            raise TransportError(
                err.code, err.read().decode(errors="replace"),
                retryable=err.code in RETRYABLE_STATUS,
            ) from err
        except urllib.error.URLError as err:
            raise TransportError(0, str(err.reason), retryable=True) from err


def multipart_form(fields: Dict[str, str], files: Dict[str, Tuple[str, bytes]]):
    """(content_type, body) for multipart/form-data uploads (batch JSONL)."""
    boundary = uuid.uuid4().hex
    parts = []
    for name, value in fields.items():
        parts.append(
            f"--{boundary}\r\nContent-Disposition: form-data; name=\"{name}\"\r\n\r\n{value}\r\n".encode()
        )
    for name, (filename, content) in files.items():
        parts.append(
            (
                f"--{boundary}\r\nContent-Disposition: form-data; name=\"{name}\"; "
                f"filename=\"{filename}\"\r\nContent-Type: application/octet-stream\r\n\r\n"
            ).encode()
            + content
            + b"\r\n"
        )
    parts.append(f"--{boundary}--\r\n".encode())
    return f"multipart/form-data; boundary={boundary}", b"".join(parts)


class FakeTransport:
    """Programmable transport for tests: queue of (matcher, responder)."""

    def __init__(self):
        self.calls = []
        self.handlers = []

    def add(self, method: str, url_substr: str, responder):
        """responder(call) -> (status, body_dict_or_bytes); errors may raise."""
        self.handlers.append((method, url_substr, responder))

    def request(self, method, url, headers=None, json_body=None, data=None):
        call = {
            "method": method,
            "url": url,
            "headers": headers or {},
            "json": json_body,
            "data": data,
        }
        self.calls.append(call)
        for m, sub, responder in self.handlers:
            if m == method and sub in url:
                status, body = responder(call)
                if isinstance(body, (dict, list)):
                    body = json.dumps(body).encode()
                return status, body
        raise TransportError(404, f"no fake handler for {method} {url}", retryable=False)
