"""Gemini client: generateContent with logprobs, safety-off, threaded fan-out.

Behavioral spec from perturb_prompts_gemini.py (response_logprobs=True,
logprobs=19; client-side rate limiting), perturb_prompts_gemini_parallel.py
(20 threads, ~2.3 req/s token bucket), perturb_prompts_gemini_batch.py (true
batch jobs: inlined-request submit, 30 s JOB_STATE_* polling, resumable saved
batch-id :236-470), and evaluate_irrelevant_perturbations.py (BLOCK_NONE
safety thresholds :72-78; ``max_output_tokens`` deliberately unset to dodge
the empty-response bug :336-350).
"""

from __future__ import annotations

import json
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Sequence

from ..utils.retry import RateLimiter, RetryPolicy, retry_with_exponential_backoff
from .transport import TransportError, UrllibTransport

BASE_URL = "https://generativelanguage.googleapis.com/v1beta"

SAFETY_OFF = [
    {"category": c, "threshold": "BLOCK_NONE"}
    for c in (
        "HARM_CATEGORY_HARASSMENT",
        "HARM_CATEGORY_HATE_SPEECH",
        "HARM_CATEGORY_SEXUALLY_EXPLICIT",
        "HARM_CATEGORY_DANGEROUS_CONTENT",
    )
]


class BatchTerminalError(RuntimeError):
    """A batch reached FAILED/CANCELLED/EXPIRED — the saved id is dead."""


class GeminiClient:
    def __init__(self, api_key: str, transport=None, base_url: str = BASE_URL,
                 retry_policy: Optional[RetryPolicy] = None,
                 requests_per_second: Optional[float] = None):
        self.api_key = api_key
        self.transport = transport or UrllibTransport()
        self.base_url = base_url
        self.retry_policy = retry_policy or RetryPolicy(
            retry_on=(TransportError,), max_retries=10,
            initial_delay=60.0, max_delay=300.0,
        )
        self.rate_limiter = (
            RateLimiter(requests_per_second) if requests_per_second else None
        )

    def generate_content(
        self,
        model: str,
        prompt: str,
        temperature: float = 0.0,
        max_output_tokens: Optional[int] = None,  # None on purpose (bug dodge)
        response_logprobs: bool = False,
        logprobs: int = 19,
        safety_off: bool = True,
    ) -> Dict:
        if self.rate_limiter:
            self.rate_limiter.acquire()
        generation_config: Dict = {"temperature": temperature}
        if max_output_tokens is not None:
            generation_config["maxOutputTokens"] = max_output_tokens
        if response_logprobs:
            generation_config["responseLogprobs"] = True
            generation_config["logprobs"] = logprobs
        body = {
            "contents": [{"parts": [{"text": prompt}]}],
            "generationConfig": generation_config,
        }
        if safety_off:
            body["safetySettings"] = SAFETY_OFF
        path = f"/models/{model}:generateContent?key={self.api_key}"

        @retry_with_exponential_backoff(self.retry_policy)
        def call():
            try:
                _, raw = self.transport.request("POST", f"{self.base_url}{path}", {}, body)
            except TransportError as err:
                if not err.retryable:
                    raise RuntimeError(str(err)) from err
                raise
            return raw

        return json.loads(call())

    @staticmethod
    def text_of(response: Dict) -> str:
        try:
            parts = response["candidates"][0]["content"]["parts"]
            return "".join(p.get("text", "") for p in parts).strip()
        except (KeyError, IndexError):
            return ""

    @staticmethod
    def top_candidates_of(response: Dict) -> List[List[tuple]]:
        """Per-position [(token, logprob)] lists from logprobsResult."""
        try:
            lr = response["candidates"][0]["logprobsResult"]
        except (KeyError, IndexError):
            return []
        positions = []
        for pos in lr.get("topCandidates", []):
            positions.append(
                [
                    (c.get("token", ""), float(c.get("logProbability", 0.0)))
                    for c in pos.get("candidates", [])
                ]
            )
        return positions

    def generate_many(self, model: str, prompts: Sequence[str], max_workers: int = 20,
                      **kwargs) -> List[Dict]:
        """Threaded fan-out (the reference's 'parallel'/'batch auto' mode)."""
        with ThreadPoolExecutor(max_workers=max_workers) as pool:
            futures = [
                pool.submit(self.generate_content, model, p, **kwargs) for p in prompts
            ]
            return [f.result() for f in futures]

    # -- true batch-job pipeline (perturb_prompts_gemini_batch.py) ----------
    #
    # Submit inlined requests to the Batch API, poll every 30 s against the
    # JOB_STATE_* machine, and persist the batch name to a resume file so an
    # interrupted run re-attaches instead of re-submitting
    # (save/load/clear_batch_id, reference :349-381).

    TERMINAL_STATES = frozenset({
        "JOB_STATE_SUCCEEDED", "JOB_STATE_FAILED",
        "JOB_STATE_CANCELLED", "JOB_STATE_EXPIRED",
    })

    def create_batch(self, model: str, prompts: Sequence[str],
                     display_name: Optional[str] = None, temperature: float = 0.0,
                     response_logprobs: bool = False, logprobs: int = 19,
                     safety_off: bool = True) -> str:
        """Submit a batch of inlined generateContent requests; returns the
        batch resource name (``batches/...``) used for polling/retrieval."""
        generation_config: Dict = {"temperature": temperature}
        if response_logprobs:
            generation_config["responseLogprobs"] = True
            generation_config["logprobs"] = logprobs
        requests = []
        for i, prompt in enumerate(prompts):
            req: Dict = {
                "contents": [{"parts": [{"text": prompt}]}],
                "generationConfig": generation_config,
            }
            if safety_off:
                req["safetySettings"] = SAFETY_OFF
            requests.append({"request": req, "metadata": {"key": str(i)}})
        body = {
            "batch": {
                "displayName": display_name or f"sweep-batch-{len(prompts)}",
                "inputConfig": {"requests": {"requests": requests}},
            }
        }
        path = f"/models/{model}:batchGenerateContent?key={self.api_key}"

        @retry_with_exponential_backoff(self.retry_policy)
        def call():
            try:
                _, raw = self.transport.request("POST", f"{self.base_url}{path}", {}, body)
            except TransportError as err:
                if not err.retryable:   # 400 payload-too-large / 403: surface now
                    raise RuntimeError(str(err)) from err
                raise
            return raw

        return json.loads(call())["name"]

    def get_batch(self, name: str) -> Dict:
        @retry_with_exponential_backoff(self.retry_policy)
        def call():
            try:
                _, raw = self.transport.request(
                    "GET", f"{self.base_url}/{name}?key={self.api_key}", {}, None
                )
            except TransportError as err:
                if not err.retryable:
                    raise RuntimeError(str(err)) from err
                raise
            return raw

        return json.loads(call())

    @staticmethod
    def batch_state(batch: Dict) -> str:
        return (batch.get("metadata", {}).get("state")
                or batch.get("state", "JOB_STATE_UNSPECIFIED"))

    def wait_for_batch(self, name: str, poll_interval: float = 30.0,
                       max_wait: float = 24 * 3600.0, sleep_fn=None,
                       clock_fn=None) -> Dict:
        """Poll until a terminal JOB_STATE_*; raises on failed/cancelled/
        expired (the reference treats them as run-ending, :337-343).

        Elapsed time is measured with a monotonic clock (injectable as
        ``clock_fn`` for tests), not by summing sleep intervals — get_batch
        latency and its retry backoffs count toward ``max_wait`` too.
        """
        import time as _time

        sleep_fn = sleep_fn or _time.sleep
        clock_fn = clock_fn or _time.monotonic
        started = clock_fn()
        while True:
            batch = self.get_batch(name)
            state = self.batch_state(batch)
            if state == "JOB_STATE_SUCCEEDED":
                return batch
            if state in self.TERMINAL_STATES:
                raise BatchTerminalError(f"gemini batch {name} ended in {state}")
            waited = clock_fn() - started
            if waited >= max_wait:
                raise TimeoutError(f"gemini batch {name} still {state} after {waited:.0f}s")
            sleep_fn(poll_interval)

    @staticmethod
    def batch_responses(batch: Dict) -> List[Dict]:
        """Per-request response dicts, re-paired to submit order.

        Each submitted request carries ``metadata.key = str(i)``; when the
        service echoes it, responses are ordered by that key rather than
        trusting wire order (mis-pairing would silently attribute every
        logprob to the wrong prompt).  Keyless responses keep wire order."""
        inlined = (batch.get("response", {}).get("inlinedResponses", {})
                   .get("inlinedResponses", []))
        def _key(r):
            try:
                return int(r.get("metadata", {}).get("key"))
            except (TypeError, ValueError):
                return None

        keys = [_key(r) for r in inlined]
        if keys and None not in keys and len(set(keys)) == len(keys):
            inlined = [r for _, r in sorted(zip(keys, inlined))]
        return [r.get("response", {}) for r in inlined]

    def run_batch(self, model: str, prompts: Sequence[str],
                  resume_file: Optional[str] = None, poll_interval: float = 30.0,
                  sleep_fn=None, **kwargs) -> List[Dict]:
        """Submit-or-resume → wait → collect.  With ``resume_file``, a saved
        batch name is re-attached to (and cleared on success) so a crashed
        orchestrator never double-submits 20k requests."""
        name = load_batch_id(resume_file) if resume_file else None
        if name is None:
            name = self.create_batch(model, prompts, **kwargs)
            if resume_file:
                save_batch_id(resume_file, name)
        try:
            batch = self.wait_for_batch(name, poll_interval, sleep_fn=sleep_fn)
        except BatchTerminalError:
            # FAILED/CANCELLED/EXPIRED: the saved id is dead — clear it so the
            # next run resubmits.  Other errors (transient poll failures, auth
            # hiccups) keep the file: the batch may still be running.
            if resume_file:
                clear_batch_id(resume_file)
            raise
        if resume_file:
            clear_batch_id(resume_file)
        return self.batch_responses(batch)


# Resume-file helpers ride utils/checkpoint.CheckpointFile (atomic tmp +
# os.replace writes) so a crash mid-save can never leave a truncated batch
# name for the next run to poll.

def save_batch_id(path: str, name: str) -> None:
    from ..utils.checkpoint import CheckpointFile

    CheckpointFile(path).save({"batch_name": name})


def load_batch_id(path: str) -> Optional[str]:
    from ..utils.checkpoint import CheckpointFile

    return CheckpointFile(path).load().get("batch_name") or None


def clear_batch_id(path: str) -> None:
    from ..utils.checkpoint import CheckpointFile

    CheckpointFile(path).clear()


# ---------------------------------------------------------------------------
# Batch-response repair (fix_batch_responses.py)
#
# A buggy batch download can leave each JSONL row's text field holding the
# *string repr* of a response object instead of the text itself, with the
# custom_id lost.  The repair pass re-pairs rows with the original request
# custom_ids (by line position) and regex-recovers the text.
# ---------------------------------------------------------------------------

def extract_text_from_response_string(response_str: str) -> str:
    """Recover the reply text from a stringified response object
    (fix_batch_responses.py:21-28: the ``text='...'`` group, else '').

    Unlike the reference's ``[^']*`` regex, this also handles Python reprs
    that switch to double quotes (``text="It's likely"``) and backslash-
    escaped quotes inside the literal, so apostrophed answers survive the
    repair instead of being silently truncated or blanked.
    """
    import re

    s = str(response_str)
    for pattern, unescape in (
        (r"text='((?:[^'\\]|\\.)*)'", (("\\'", "'"),)),
        (r'text="((?:[^"\\]|\\.)*)"', (('\\"', '"'),)),
    ):
        match = re.search(pattern, s)
        if match:
            text = match.group(1)
            for src, dst in unescape + (("\\\\", "\\"),):
                text = text.replace(src, dst)
            return text
    return ""


def repair_batch_responses(request_jsonl: str, response_jsonl: str,
                           output_jsonl: str) -> int:
    """Rewrite a corrupted batch-response JSONL (fix_batch_responses.py:30-77).

    Reads custom_ids from ``request_jsonl`` (positional pairing; rows past the
    request list get ``result_{i}`` ids), extracts the real text out of each
    stringified response, and writes rows in the canonical
    ``{"custom_id", "response": {"candidates": [{"content": {"parts":
    [{"text": ...}]}, "logprobs_result": None}]}}`` shape.  Returns the number
    of rows repaired.
    """
    with open(request_jsonl) as f:
        request_ids = [json.loads(line)["custom_id"] for line in f if line.strip()]
    with open(response_jsonl) as f:
        responses = [json.loads(line) for line in f if line.strip()]

    fixed = 0
    with open(output_jsonl, "w") as f:
        for idx, row in enumerate(responses):
            custom_id = request_ids[idx] if idx < len(request_ids) else f"result_{idx}"
            try:
                raw = row["response"]["candidates"][0]["content"]["parts"][0]["text"]
            except (KeyError, IndexError, TypeError):
                raw = ""
            f.write(json.dumps({
                "custom_id": custom_id,
                "response": {
                    "candidates": [{
                        "content": {"parts": [{"text": extract_text_from_response_string(raw)}]},
                        "logprobs_result": None,
                    }]
                },
            }) + "\n")
            fixed += 1
    return fixed
