"""OpenAI client: sync chat completions + Batch API pipeline.

Behavioral spec from the reference (perturb_prompts.py:108-726,
perturb_prompts_gpt.py, evaluate_closed_source_models.py:161-261):
- non-reasoning models: temperature=0, logprobs=True, top_logprobs=20,
  max_tokens=500; reasoning models (o3*, gpt-5*): max_completion_tokens=2000,
  no logprobs.
- Batch pipeline: JSONL upload (purpose=batch) → batches.create
  (completion_window=24h) → poll → download output file; 50k-request chunking.
"""

from __future__ import annotations

import json
import time
from typing import Dict, List, Optional, Sequence

from ..utils.retry import RetryPolicy, retry_with_exponential_backoff
from .transport import TransportError, UrllibTransport, multipart_form

BASE_URL = "https://api.openai.com/v1"
MAX_BATCH_REQUESTS = 50_000  # reference chunking threshold (:577-667)

REASONING_PREFIXES = ("o1", "o3", "o4", "gpt-5")


def is_reasoning_model(model: str) -> bool:
    return model.startswith(REASONING_PREFIXES)


class OpenAIClient:
    def __init__(self, api_key: str, transport=None, base_url: str = BASE_URL,
                 retry_policy: Optional[RetryPolicy] = None):
        self.api_key = api_key
        self.transport = transport or UrllibTransport()
        self.base_url = base_url
        self.retry_policy = retry_policy or RetryPolicy(
            retry_on=(TransportError,), max_retries=10
        )

    def _headers(self):
        return {"Authorization": f"Bearer {self.api_key}"}

    def _request(self, method: str, path: str, json_body=None, data=None, headers=None):
        hdrs = {**self._headers(), **(headers or {})}

        @retry_with_exponential_backoff(self.retry_policy)
        def call():
            try:
                status, body = self.transport.request(
                    method, f"{self.base_url}{path}", hdrs, json_body, data
                )
            except TransportError as err:
                if not err.retryable:
                    raise RuntimeError(str(err)) from err
                raise
            return body

        return call()

    # -- chat ------------------------------------------------------------

    def chat_completion(
        self,
        model: str,
        messages: Sequence[Dict],
        temperature: float = 0.0,
        max_tokens: int = 500,
        logprobs: bool = True,
        top_logprobs: int = 20,
    ) -> Dict:
        body: Dict = {"model": model, "messages": list(messages)}
        if is_reasoning_model(model):
            body["max_completion_tokens"] = 2000
        else:
            body.update(
                temperature=temperature,
                max_tokens=max_tokens,
                logprobs=logprobs,
                top_logprobs=top_logprobs if logprobs else None,
            )
            if not logprobs:
                body.pop("top_logprobs")
        return json.loads(self._request("POST", "/chat/completions", json_body=body))

    # -- batch -----------------------------------------------------------

    def upload_batch_file(self, jsonl_lines: Sequence[Dict]) -> str:
        content = "\n".join(json.dumps(l) for l in jsonl_lines).encode()
        ctype, body = multipart_form(
            {"purpose": "batch"}, {"file": ("batch.jsonl", content)}
        )
        resp = json.loads(
            self._request("POST", "/files", data=body, headers={"Content-Type": ctype})
        )
        return resp["id"]

    def create_batch(self, file_id: str, endpoint: str = "/v1/chat/completions",
                     completion_window: str = "24h") -> Dict:
        return json.loads(
            self._request(
                "POST", "/batches",
                json_body={
                    "input_file_id": file_id,
                    "endpoint": endpoint,
                    "completion_window": completion_window,
                },
            )
        )

    def get_batch(self, batch_id: str) -> Dict:
        return json.loads(self._request("GET", f"/batches/{batch_id}"))

    def download_file(self, file_id: str) -> bytes:
        return self._request("GET", f"/files/{file_id}/content")

    def wait_for_batch(self, batch_id: str, poll_interval: float = 60.0,
                       timeout: float = 24 * 3600, sleep=time.sleep,
                       clock=time.monotonic) -> Dict:
        """Poll until terminal state (reference: 60 s loop, failed/cancelled/
        expired are errors — perturb_prompts.py:313-330).  Elapsed time is
        measured with a monotonic clock (injectable), so get_batch latency
        and retry backoffs count toward ``timeout`` too."""
        started = clock()
        while True:
            batch = self.get_batch(batch_id)
            status = batch.get("status")
            if status == "completed":
                return batch
            if status in ("failed", "cancelled", "expired"):
                raise RuntimeError(f"batch {batch_id} terminal state: {status}")
            if clock() - started >= timeout:
                raise TimeoutError(f"batch {batch_id} not done after {timeout}s")
            sleep(poll_interval)

    def retrieve_batch_results(self, batch: Dict) -> List[Dict]:
        raw = self.download_file(batch["output_file_id"])
        return [json.loads(line) for line in raw.decode().splitlines() if line.strip()]

    def run_batch(self, requests: Sequence[Dict], poll_interval: float = 60.0,
                  sleep=time.sleep) -> List[Dict]:
        """Submit (chunked at 50k), wait, download, concatenate."""
        results: List[Dict] = []
        chunks = [
            list(requests[i : i + MAX_BATCH_REQUESTS])
            for i in range(0, len(requests), MAX_BATCH_REQUESTS)
        ]
        for chunk in chunks:
            file_id = self.upload_batch_file(chunk)
            batch = self.create_batch(file_id)
            batch = self.wait_for_batch(batch["id"], poll_interval, sleep=sleep)
            results.extend(self.retrieve_batch_results(batch))
        return results


def build_batch_request(custom_id: str, model: str, messages: Sequence[Dict],
                        temperature: float = 0.0, max_tokens: int = 500,
                        logprobs: bool = True, top_logprobs: int = 20) -> Dict:
    """One JSONL line of the batch input (reference create_batch_requests
    semantics, perturb_prompts.py:190-269)."""
    body: Dict = {"model": model, "messages": list(messages)}
    if is_reasoning_model(model):
        body["max_completion_tokens"] = 2000
    else:
        body.update(
            temperature=temperature, max_tokens=max_tokens,
            logprobs=logprobs, top_logprobs=top_logprobs,
        )
    return {
        "custom_id": custom_id,
        "method": "POST",
        "url": "/v1/chat/completions",
        "body": body,
    }
