"""API response cache with field-level completeness validation.

Behavioral replica of evaluate_closed_source_models.py:554-745: JSON cache
keyed on the first 100 characters of the question, per-model required-field
sets, and partial re-runs (only the missing evaluators re-execute).
"""

from __future__ import annotations

import json
import os
from typing import Dict, Iterable, Optional, Sequence

KEY_PREFIX_LEN = 100

#: required result fields per evaluator (reference completeness check)
REQUIRED_FIELDS = {
    "gpt": ["gpt_response", "gpt_yes_prob", "gpt_no_prob", "gpt_relative_prob",
            "gpt_confidence", "gpt_weighted_confidence"],
    "gemini": ["gemini_response", "gemini_yes_prob", "gemini_no_prob",
               "gemini_relative_prob", "gemini_confidence", "gemini_weighted_confidence"],
    "claude": ["claude_response", "claude_confidence"],
    "random": ["random_response", "random_confidence"],
}


def cache_key(question: str) -> str:
    return question[:KEY_PREFIX_LEN]


class ResponseCache:
    def __init__(self, path: Optional[str] = None):
        self.path = path
        self._data: Dict[str, Dict] = {}
        if path and os.path.exists(path):
            with open(path) as f:
                self._data = json.load(f)

    def get(self, question: str) -> Optional[Dict]:
        return self._data.get(cache_key(question))

    def put(self, question: str, record: Dict, flush: bool = True) -> None:
        key = cache_key(question)
        existing = self._data.get(key, {})
        existing.update(record)
        self._data[key] = existing
        if flush:
            self.flush()

    def flush(self) -> None:
        if self.path:
            os.makedirs(os.path.dirname(os.path.abspath(self.path)), exist_ok=True)
            tmp = self.path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(self._data, f, indent=2, default=str)
            os.replace(tmp, self.path)

    def missing_evaluators(
        self, question: str, evaluators: Sequence[str] = ("gpt", "gemini", "claude", "random")
    ) -> list:
        """Which evaluators still need to run for this question (partial
        re-run logic)."""
        record = self.get(question) or {}
        missing = []
        for name in evaluators:
            fields = REQUIRED_FIELDS.get(name, [])
            # key presence marks the evaluator as run: None is a legitimate
            # value (e.g. Gemini weighted confidence with no digit tokens)
            if any(f not in record for f in fields):
                missing.append(name)
        return missing

    def is_complete(self, question: str,
                    evaluators: Sequence[str] = ("gpt", "gemini", "claude", "random")) -> bool:
        return not self.missing_evaluators(question, evaluators)

    def __len__(self) -> int:
        return len(self._data)
