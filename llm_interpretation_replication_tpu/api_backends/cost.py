"""Token/cost accounting with subset extrapolation.

Behavioral replica of perturb_prompts.py:347-350, 653-665, 1020-1066: per-model
input/output token tallies priced from the MODEL_PRICING table (USD per 1M
tokens), with full-sweep cost extrapolation from a processed subset.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from ..config import api_models


class CostTracker:
    def __init__(self, pricing: Optional[Dict] = None):
        self.pricing = pricing if pricing is not None else api_models().get("pricing", {})
        self.usage: Dict[str, Dict[str, int]] = {}
        # one tracker is shared by every RemoteReplica worker thread and
        # the sweep's per-model threads at once; the tally increments
        # below are read-modify-write (G09 api_backends/cost.py
        # 'CostTracker.usage' — lost updates undercount spend)
        self._lock = threading.Lock()

    def record(self, model: str, input_tokens: int, output_tokens: int) -> None:
        with self._lock:
            u = self.usage.setdefault(model, {"input_tokens": 0, "output_tokens": 0, "requests": 0})
            u["input_tokens"] += int(input_tokens)
            u["output_tokens"] += int(output_tokens)
            u["requests"] += 1

    def record_response(self, model: str, response: Dict) -> None:
        """Pull usage out of an OpenAI-style response object."""
        usage = response.get("usage", {})
        self.record(
            model,
            usage.get("prompt_tokens", usage.get("input_tokens", 0)),
            usage.get("completion_tokens", usage.get("output_tokens", 0)),
        )

    def cost(self, model: str) -> float:
        u = self.usage.get(model)
        p = self.pricing.get(model)
        if not u or not p:
            return 0.0
        return (
            u["input_tokens"] / 1e6 * p.get("input", 0.0)
            + u["output_tokens"] / 1e6 * p.get("output", 0.0)
        )

    def total_cost(self) -> float:
        return sum(self.cost(m) for m in self.usage)

    def extrapolate(self, model: str, processed: int, total: int) -> float:
        """Full-sweep cost estimate from a processed subset."""
        if processed <= 0:
            return 0.0
        return self.cost(model) * (total / processed)

    def summary(self) -> Dict[str, Dict]:
        return {
            model: {**u, "cost_usd": round(self.cost(model), 4)}
            for model, u in self.usage.items()
        }
