"""``obs bench-diff``: the bench-trajectory regression analyzer.

The repo's perf history lives in the driver's ``BENCH_r*.json`` records
(r01 38.15 → r05 120.15 p/s), but every round the trajectory was
compared BY HAND and transcribed into ROADMAP prose.  This subcommand
makes the comparison a checked artifact: read two or more records, align
their headline, secondary metrics, phase decomposition, and operating-
context counters across rounds, and print a regression table — exit 1
when any throughput metric fell by more than the threshold, so a CI step
(or the next round's author) catches a regression the moment the record
lands instead of five rounds later.

Record shapes accepted, newest-field-tolerant:

- the driver wrapper ``{"n", "cmd", "rc", "tail", "parsed": {...}}`` —
  the checked-in ``BENCH_r*.json`` files;
- a bare bench record (the one JSON line ``bench.py`` prints):
  ``{"metric", "value", "unit", "secondary": [...], "phases": {...},
  "context": {...}}``.

Metric alignment: the headline rows align positionally ("headline" key);
secondary rows align by a STABLE KEY derived from the metric description
(workload class + prompt-token length + batch-independent tags), because
the free-text metric strings legitimately drift round over round (batch
sizes, hit rates).  Rows present in only one record report as ``new`` /
``gone`` instead of silently vanishing from the table.

Regression semantics: throughput rows (prompts/sec, rows/sec — higher
is better) regress on a drop beyond ``--threshold`` percent; the
serve-load latency rows (``ms`` — ISSUE 11, aligned per offered rate
from the record's ``serve_load`` block) regress on GROWTH beyond it;
phase rows compare ``ms_per_row`` (lower is better) when both records
carry a ``phases`` block.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
from typing import Dict, List, Optional, Sequence, Tuple

#: units where larger values are better (everything bench records today).
_HIGHER_IS_BETTER_UNITS = ("prompts/sec", "rows/sec")

#: units where SMALLER values are better — the serve-load latency rows
#: (ISSUE 11): a p99 that grew past the threshold is the regression.
_LOWER_IS_BETTER_UNITS = ("ms", "idle-frac")

#: units where ANY non-zero value is a regression, no percentage
#: threshold: the self-healing recovery block's ``requests_lost`` — one
#: lost request means the always-answered contract broke, and "only 3%
#: worse than last round's zero" is not a sentence that parses.
_HARD_ZERO_UNITS = ("lost-requests",)

#: The bench-record block contract (cross-checked by ``lint contracts``):
#: every top-level block ``bench.py`` emits must be classified in exactly
#: one of these tuples, and every ALIGNED/CONTEXT entry must actually be
#: read by this module — so a new bench block cannot land without
#: teaching the diff what it means, and a block this module claims to
#: align cannot silently stop being flattened.
#:
#: blocks :func:`flatten_metrics` aligns into verdict/informational rows:
ALIGNED_BLOCKS = ("secondary", "brackets", "packed", "k_decode",
                  "occupancy", "serve_load", "serve_load_pool",
                  "recovery")
#: blocks :func:`diff_records` reads as cross-round context tables:
CONTEXT_BLOCKS = ("context", "phases")
#: blocks deliberately NOT aligned (free-form diagnostics whose shape is
#: owned by their producer; listed so the classification is a conscious
#: decision, not an omission):
INFORMATIONAL_BLOCKS = ("strict", "plan_search", "packed_drift", "serve",
                        "repeats")


def load_bench_record(path: str) -> Dict:
    """One record, unwrapped from the driver shape when present, with a
    ``label`` derived from the filename (``BENCH_r04.json`` → ``r04``)."""
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    rec = doc.get("parsed") if isinstance(doc.get("parsed"), dict) else doc
    if not isinstance(rec, dict) or "value" not in rec:
        raise ValueError(f"{path}: not a bench record (no 'value' field)")
    stem = os.path.splitext(os.path.basename(path))[0]
    m = re.search(r"(r\d+)$", stem)
    rec = dict(rec)
    rec["label"] = m.group(1) if m else stem
    return rec


def metric_key(metric: str, unit: str) -> str:
    """Stable cross-round identity for a metric row.

    The free-text descriptions drift (batch sizes, measured hit rates,
    attn impl), so the key keeps only what identifies the WORKLOAD:
    the mode class, the prompt-token length when named, and the unit —
    plus the decode bracket and packing factor (ISSUE 10): an
    EOS-typical bracket row and a packed row are DIFFERENT workload
    shapes from their no-EOS / isolated twins, and cross-comparing them
    would report the bracket span as a regression.  The no-EOS /
    isolated spellings stay untagged so legacy records (r01-r05, which
    never name a bracket) keep aligning with their successors."""
    text = metric.lower()
    if "packed" in text:
        mode = "packed"
    elif "full-study" in text or "full row contract" in text:
        mode = "full-study"
    elif "end-to-end" in text:
        mode = "e2e-sweep"
    elif "single forward" in text:
        mode = "single"
    elif "decode, all rows" in text or "all rows" in text:
        mode = "decode-all"
    elif "two-phase" in text:
        mode = "parity"
    else:
        mode = "other"
    tags = []
    m = re.search(r"(\d+)-token prompts", text)
    if m:
        tags.append(f"{m.group(1)}tok")
    if "sweep operating point" in text:
        tags.append("sweep-point")
    tags.extend(_shape_tags(text))
    key = mode + (("@" + "+".join(tags)) if tags else "")
    return f"{key} [{unit}]"


def _shape_tags(text: str) -> List[str]:
    """The workload-SHAPE tags (decode bracket, packing factor, joint
    K-decode block size) that must never cross-compare — shared by
    :func:`metric_key` and the headline key, which is otherwise
    positional.  No-EOS / isolated / sequential (K=1) spellings stay
    untagged so legacy records keep aligning."""
    tags = []
    if "eos-typical" in text:
        tags.append("eos-typical")
    m = re.search(r"(?:q=|packing )(\d+)", text)
    if m:
        tags.append(f"q{m.group(1)}")
    m = re.search(r"decode-k (\d+)", text)
    if m and int(m.group(1)) > 1:
        # an ISSUE-13 joint-K-decode run is a different workload shape
        # from its sequential twin (the decode legs run different
        # programs); K-tagged rows align only with K-tagged rows
        tags.append(f"k{m.group(1)}")
    return tags


def flatten_metrics(rec: Dict) -> Dict[str, Dict]:
    """``{aligned key: {"value", "unit", "metric"}}`` for the headline +
    every secondary row, plus the ISSUE-10 blocks: ``brackets`` rows
    (keyed with their eos-mode tag, so a no-EOS row can never
    cross-compare with an EOS-typical one) and the ``packed`` companion
    record.  Key collisions (two secondaries of one class) disambiguate
    by index."""
    # the headline key is positional, EXCEPT for the workload-shape tags:
    # an --eos-mode typical (or packed) headline is a different workload
    # from the default bracket's and must report new/gone, not a verdict
    shape = _shape_tags(rec.get("metric", "").lower())
    head_key = "headline" + (("@" + "+".join(shape)) if shape else "")
    out: Dict[str, Dict] = {
        head_key: {"value": rec["value"], "unit": rec.get("unit", ""),
                   "metric": rec.get("metric", "")},
    }
    extra_rows = list(rec.get("secondary", ()) or ())
    for holder in [rec] + [e for e in extra_rows if isinstance(e, dict)]:
        # bracket rows ride top-level on a direct sweep-full record and
        # NESTED on the parent sweep record's full-study child secondary
        # (the bench child-extras forwarding) — flatten both
        for entry in holder.get("brackets", ()) or ():
            extra_rows.append(dict(entry, metric=entry.get(
                "metric",
                f"({entry.get('eos_mode', '?')} decode bracket)")))
    if isinstance(rec.get("packed"), dict) and "value" in rec["packed"]:
        extra_rows.append(rec["packed"])
    for entry in extra_rows:
        key = metric_key(entry.get("metric", ""), entry.get("unit", ""))
        base, n = key, 2
        while key in out:
            key = f"{base} #{n}"
            n += 1
        out[key] = {"value": entry.get("value"),
                    "unit": entry.get("unit", ""),
                    "metric": entry.get("metric", "")}
    # k_decode blocks ride top-level on a sweep-full record and NESTED on
    # the sweep record's full-study child secondary — flatten both (the
    # brackets discipline); first spelling wins on a key collision
    for holder in [rec] + [e for e in extra_rows if isinstance(e, dict)]:
        for key, row in _k_decode_rows(holder).items():
            out.setdefault(key, row)
    # occupancy blocks (ROADMAP item 3) follow the same two-home rule
    for holder in [rec] + [e for e in extra_rows if isinstance(e, dict)]:
        for key, row in _occupancy_rows(holder).items():
            out.setdefault(key, row)
    out.update(_serve_load_rows(rec))
    out.update(_serve_pool_rows(rec))
    out.update(_recovery_rows(rec))
    return out


def _occupancy_rows(rec: Dict) -> Dict[str, Dict]:
    """Aligned rows from a record's ``occupancy`` block (ROADMAP item 3,
    decode-then-repack): the SLOT-IDLE FRACTION is a lower-is-better
    verdict row (unit ``idle-frac`` — occupancy regressing means the
    repack pipeline stopped refilling lanes), the whole-flush
    counterfactual and refill/stall counts ride along as informational
    rows so an idle-fraction move is explainable in place."""
    block = rec.get("occupancy")
    if not isinstance(block, dict):
        return {}
    out: Dict[str, Dict] = {}
    if block.get("slot_idle_frac") is not None:
        out["slot idle fraction [idle-frac]"] = {
            "value": block["slot_idle_frac"], "unit": "idle-frac",
            "metric": "decode slot-idle fraction under repack "
                      "(lower = fuller lanes)"}
    if block.get("slot_idle_frac_no_repack") is not None:
        out["slot idle fraction (no-repack counterfactual)"] = {
            "value": block["slot_idle_frac_no_repack"], "unit": "",
            "metric": "whole-flush counterfactual slot-idle fraction "
                      "(same rows, legacy schedule)"}
    for name in ("refills", "repack_stalls"):
        if block.get(name) is not None:
            out[f"slot {name.replace('_', ' ')}"] = {
                "value": block[name], "unit": "",
                "metric": f"decode-then-repack {name.replace('_', ' ')} "
                          f"(informational)"}
    return out


def _k_decode_rows(rec: Dict) -> Dict[str, Dict]:
    """Aligned rows from a record's ``k_decode`` block (ISSUE 13): the
    per-leg steps saved, the mean accepted K, and the block reject rate
    — informational rows (no regression verdict: steps-saved scale with
    corpus size and the reject rate is a prior-calibration input, not a
    perf promise), keyed by the leg name so rounds compare like for
    like."""
    block = rec.get("k_decode")
    if not isinstance(block, dict):
        return {}
    out: Dict[str, Dict] = {}
    k = block.get("decode_k")
    saved = block.get("k_steps_saved") or {}
    for leg in ("confidence", "completion"):
        if saved.get(leg) is not None:
            out[f"k-decode steps-saved ({leg})"] = {
                "value": saved.get(leg), "unit": "",
                "metric": f"joint decode-k {k} steps saved on the {leg} "
                          f"leg (measured repeats)"}
    if block.get("accepted_k_mean") is not None:
        out["k-decode accepted-k mean"] = {
            "value": block["accepted_k_mean"], "unit": "",
            "metric": f"mean accepted block length at decode-k {k}"}
    if block.get("k_reject_rate") is not None:
        out["k-decode reject rate"] = {
            "value": block["k_reject_rate"], "unit": "",
            "metric": f"verify-and-accept block reject rate at "
                      f"decode-k {k}"}
    return out


def _serve_load_rows(rec: Dict) -> Dict[str, Dict]:
    """Aligned rows from a record's ``serve_load`` block (ISSUE 11): per
    rate point, achieved throughput (higher-better) and p99 end-to-end
    latency (LOWER-better, unit ``ms``), plus the saturation estimate.

    Keyed by SWEEP POSITION, not the offered-rate value: the default
    ``--serve-load-rates auto`` derives each record's rates from its own
    measured offline ceiling, so the floats never repeat across rounds
    and value-keyed rows would all report new/gone instead of comparing.
    Position i is the same BRACKET of the ceiling round over round
    (auto: 0.5x/1.0x/1.5x), which is the comparison that means
    something; the offered rate itself rides along as an informational
    row so a bracket drift is visible next to its latency verdict."""
    block = rec.get("serve_load")
    if not isinstance(block, dict):
        return {}
    out: Dict[str, Dict] = {}
    for i, point in enumerate(block.get("rates", ()) or ()):
        offered = point.get("offered_rate")
        tag = f"serve-load[{i}]"
        out[f"{tag} offered"] = {
            "value": offered, "unit": "",
            "metric": f"serve load sweep point {i} offered rate (rows/s)"}
        out[f"{tag} achieved [rows/sec]"] = {
            "value": point.get("achieved_rows_per_s"), "unit": "rows/sec",
            "metric": f"serve load achieved rate at sweep point {i} "
                      f"({offered} offered)"}
        p99 = (point.get("latency_ms") or {}).get("p99")
        out[f"{tag} p99 [ms]"] = {
            "value": p99, "unit": "ms",
            "metric": f"serve load p99 e2e latency at sweep point {i} "
                      f"({offered} offered)"}
    if block.get("saturation_rows_per_s") is not None:
        out["serve-load saturation [rows/sec]"] = {
            "value": block["saturation_rows_per_s"], "unit": "rows/sec",
            "metric": "serve load saturation throughput"}
    return out


def _pool_roster_tag(entry: Dict) -> str:
    """Cross-round identity of one ``serve_load_pool`` configuration.

    Keyed by ROLE COMPOSITION, not the free-text name: a disaggregated
    roster tags itself ``prefill:N,decode:M`` (sorted so spelling order
    in the flag never splits the series) and compares only with rosters
    of the same composition; symmetric rosters key as ``symmetric-xN``
    by replica count — so the ISSUE-20 knee-vs-knee comparison
    (disaggregated vs symmetric at equal chips) lands as two adjacent
    verdict rows instead of one mis-aligned one."""
    roles = entry.get("roles")
    if isinstance(roles, dict) and roles:
        return ",".join(f"{r}:{roles[r]}" for r in sorted(roles,
                                                          reverse=True))
    name = str(entry.get("name", ""))
    n = len(entry.get("replicas", ()) or ())
    if name.startswith("single-model"):
        return f"symmetric-x{n}" if n else name
    return name or f"symmetric-x{n}"


def _serve_pool_rows(rec: Dict) -> Dict[str, Dict]:
    """Aligned rows from a record's ``serve_load_pool`` block (ISSUE 12
    fleet, ISSUE 20 roles): per roster configuration — keyed by
    :func:`_pool_roster_tag` — the saturation throughput
    (higher-better ``rows/sec``: the roster's knee) and the p99 e2e
    latency at the TOP swept rate (lower-better ``ms``), with the
    replica count riding along informationally so a knee move is
    explainable by a fleet-size change in place."""
    block = rec.get("serve_load_pool")
    if not isinstance(block, dict):
        return {}
    out: Dict[str, Dict] = {}
    for entry in block.get("configurations", ()) or ():
        if not isinstance(entry, dict):
            continue
        tag = _pool_roster_tag(entry)
        sl = entry.get("serve_load")
        if not isinstance(sl, dict):
            continue
        if sl.get("saturation_rows_per_s") is not None:
            out[f"pool[{tag}] saturation [rows/sec]"] = {
                "value": sl["saturation_rows_per_s"], "unit": "rows/sec",
                "metric": f"pool roster {tag} saturation throughput "
                          f"(knee of the rate sweep)"}
        points = sl.get("rates", ()) or ()
        if points:
            p99 = (points[-1].get("latency_ms") or {}).get("p99")
            if p99 is not None:
                out[f"pool[{tag}] p99@top [ms]"] = {
                    "value": p99, "unit": "ms",
                    "metric": f"pool roster {tag} p99 e2e latency at "
                              f"the top swept rate"}
        n = len(entry.get("replicas", ()) or ())
        if n:
            out[f"pool[{tag}] replicas"] = {
                "value": n, "unit": "",
                "metric": f"pool roster {tag} replica count "
                          f"(informational)"}
    return out


def _recovery_rows(rec: Dict) -> Dict[str, Dict]:
    """Aligned rows from a record's ``recovery`` block (ISSUE 16): the
    self-healing drill that ``--serve-load-faults`` runs.  Detection and
    restart latency are lower-is-better ``ms`` rows; ``requests_lost``
    carries the zero-tolerance ``lost-requests`` unit — the contract is
    that every request is ANSWERED (a result or a typed rejection), so a
    single lost request is a hard regression regardless of percentage.
    Incident, failover and restart counts ride along informationally:
    their absolute values track the injected fault schedule, not code
    quality, so no verdict is attached to them."""
    block = rec.get("recovery")
    if not isinstance(block, dict):
        return {}
    out: Dict[str, Dict] = {}
    det = block.get("detection_ms") or {}
    if det.get("mean") is not None:
        out["recovery detection mean [ms]"] = {
            "value": det["mean"], "unit": "ms",
            "metric": "mean fault-to-quarantine detection latency over "
                      f"{det.get('n')} incident(s)"}
    rst = block.get("restart_ms") or {}
    if rst.get("mean") is not None:
        out["recovery restart mean [ms]"] = {
            "value": rst["mean"], "unit": "ms",
            "metric": "mean quarantine-to-live replica rebuild latency "
                      f"over {rst.get('n')} rebuild(s)"}
    if block.get("requests_lost") is not None:
        out["recovery lost [lost-requests]"] = {
            "value": block["requests_lost"], "unit": "lost-requests",
            "metric": "requests neither answered nor rejected under "
                      "injected faults (must stay 0)"}
    for key, label in (("requests_failed_over", "failed-over"),
                       ("incidents", "incidents"),
                       ("restarts", "restarts")):
        if block.get(key) is not None:
            out[f"recovery {label}"] = {
                "value": block[key], "unit": "",
                "metric": f"self-healing {label.replace('-', ' ')} count "
                          "under the injected fault schedule"}
    return out


def _pct(old: Optional[float], new: Optional[float]) -> Optional[float]:
    if old is None or new is None or not old:
        return None
    return (new - old) / old * 100.0


def diff_records(records: Sequence[Dict],
                 threshold_pct: float = 5.0) -> Dict:
    """Align ``records`` (round order) and classify every metric row.

    Returns ``{"labels", "metrics": [row...], "phases": [row...],
    "context": [row...], "regressions": [...]}`` where each metric row is
    ``{key, values, delta_pct, verdict}`` over the FIRST→LAST pair (the
    middle rounds print for trajectory context)."""
    labels = [r["label"] for r in records]
    flats = [flatten_metrics(r) for r in records]
    keys: List[str] = []
    for flat in flats:
        for k in flat:
            if k not in keys:
                keys.append(k)
    metrics, regressions = [], []
    for key in keys:
        values = [flat.get(key, {}).get("value") for flat in flats]
        unit = next((flat[key]["unit"] for flat in flats if key in flat), "")
        first = next((v for v in values if v is not None), None)
        last = next((v for v in reversed(values) if v is not None), None)
        delta = _pct(first, last)
        if unit in _HARD_ZERO_UNITS:
            # zero-tolerance rows: any non-zero value in the newest
            # round is a regression outright — no threshold, and "new"
            # is no excuse (the first round the row shows up non-zero
            # is exactly when it must scream)
            if last:
                verdict = "REGRESSION"
            elif values[-1] is None:
                verdict, delta = "gone", None
            else:
                verdict, delta = "ok", None
        elif values[0] is None:
            verdict, delta = "new", None
        elif values[-1] is None:
            verdict, delta = "gone", None
        elif delta is None:
            verdict = "n/a"
        elif unit in _HIGHER_IS_BETTER_UNITS and delta < -threshold_pct:
            verdict = "REGRESSION"
        elif unit in _HIGHER_IS_BETTER_UNITS and delta > threshold_pct:
            verdict = "improved"
        elif unit in _LOWER_IS_BETTER_UNITS and delta > threshold_pct:
            verdict = "REGRESSION"   # latency rows: growth is the bug
        elif unit in _LOWER_IS_BETTER_UNITS and delta < -threshold_pct:
            verdict = "improved"
        else:
            verdict = "ok"
        row = {"key": key, "unit": unit, "values": values,
               "delta_pct": None if delta is None else round(delta, 2),
               "verdict": verdict}
        metrics.append(row)
        if verdict == "REGRESSION":
            regressions.append(row)

    phases = []
    phase_blocks = [r.get("phases") or {} for r in records]
    if sum(1 for b in phase_blocks if b.get("per_phase")) >= 2:
        names: List[str] = []
        for block in phase_blocks:
            for name in block.get("per_phase", {}):
                if name not in names:
                    names.append(name)
        for name in names:
            values = [
                (block.get("per_phase", {}).get(name) or {}).get(
                    "ms_per_row",
                    (block.get("per_phase", {}).get(name) or {}).get(
                        "seconds"))
                for block in phase_blocks
            ]
            first = next((v for v in values if v is not None), None)
            last = next((v for v in reversed(values) if v is not None),
                        None)
            delta = _pct(first, last)
            # phase cost: LOWER is better
            if values[0] is None:
                verdict, delta = "new", None
            elif values[-1] is None:
                verdict, delta = "gone", None
            elif delta is not None and delta > threshold_pct:
                verdict = "REGRESSION"
            elif delta is not None and delta < -threshold_pct:
                verdict = "improved"
            else:
                verdict = "ok"
            row = {"key": f"phase:{name}", "unit": "ms/row",
                   "values": values,
                   "delta_pct": None if delta is None else round(delta, 2),
                   "verdict": verdict}
            phases.append(row)
            if verdict == "REGRESSION":
                regressions.append(row)

    context = []
    ctx_blocks = [r.get("context") or {} for r in records]
    if sum(1 for b in ctx_blocks if b) >= 2:
        names = []
        for block in ctx_blocks:
            for name in block:
                if name not in names:
                    names.append(name)
        for name in names:
            values = [block.get(name) for block in ctx_blocks]
            if all(v == values[0] for v in values):
                continue                    # unchanged context is noise
            context.append({"key": f"context:{name}", "values": values})

    return {"labels": labels, "threshold_pct": threshold_pct,
            "metrics": metrics, "phases": phases, "context": context,
            "regressions": regressions}


def format_diff_table(diff: Dict) -> str:
    """The aligned regression table (stdout)."""
    labels = diff["labels"]
    width = max([len("metric")] + [len(r["key"])
                                   for r in diff["metrics"] + diff["phases"]
                                   + diff["context"]])

    def fmt(v):
        if v is None:
            return "-"
        if isinstance(v, float):
            return f"{v:.2f}"
        return str(v)

    lines = [
        f"# bench trajectory: {' -> '.join(labels)} "
        f"(threshold {diff['threshold_pct']:g}%)",
        "  " + "metric".ljust(width) + "  "
        + "  ".join(f"{lab:>10}" for lab in labels)
        + f"  {'delta':>9}  verdict",
    ]
    for row in diff["metrics"] + diff["phases"]:
        delta = ("" if row["delta_pct"] is None
                 else f"{row['delta_pct']:+8.2f}%")
        lines.append(
            "  " + row["key"].ljust(width) + "  "
            + "  ".join(f"{fmt(v):>10}" for v in row["values"])
            + f"  {delta:>9}  {row['verdict']}")
    for row in diff["context"]:
        lines.append(
            "  " + row["key"].ljust(width) + "  "
            + "  ".join(f"{fmt(v):>10}" for v in row["values"]))
    n_reg = len(diff["regressions"])
    lines.append(f"  {n_reg} regression(s) beyond "
                 f"{diff['threshold_pct']:g}%"
                 + ("" if not n_reg else ": "
                    + ", ".join(r["key"] for r in diff["regressions"])))
    return "\n".join(lines)


def main(argv: Optional[Sequence[str]] = None) -> int:
    """``obs bench-diff`` CLI body (routed from obs/report.py)."""
    parser = argparse.ArgumentParser(
        prog="llm_interpretation_replication_tpu obs bench-diff",
        description="align two or more BENCH_r*.json records and print a "
                    "regression table over the perf trajectory")
    parser.add_argument("records", nargs="+", metavar="BENCH.json",
                        help="two or more bench records, oldest first "
                             "(driver wrapper or bare bench JSON line)")
    parser.add_argument("--threshold", type=float, default=5.0,
                        metavar="PCT",
                        help="regression threshold in percent (throughput "
                             "drop / phase ms-per-row growth beyond this "
                             "fails; default 5)")
    parser.add_argument("--format", choices=["table", "json"],
                        default="table")
    parser.add_argument("--no-fail", action="store_true",
                        help="always exit 0 (report-only mode; default "
                             "exits 1 when any regression exceeds the "
                             "threshold)")
    args = parser.parse_args(argv)
    if len(args.records) < 2:
        parser.error("need at least two records to diff")
    try:
        records = [load_bench_record(p) for p in args.records]
    except (OSError, ValueError) as err:
        print(f"obs bench-diff: {err}", file=sys.stderr)
        return 2
    diff = diff_records(records, threshold_pct=args.threshold)
    if args.format == "json":
        print(json.dumps(diff, indent=2))
    else:
        print(format_diff_table(diff))
    if diff["regressions"] and not args.no_fail:
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
