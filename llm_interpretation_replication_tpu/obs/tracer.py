"""Thread-safe nested span tracer with phase attribution.

A *span* is one timed interval on one thread — a prefill launch, a d2h
fetch, a tokenize chunk, a serve request's queue wait — recorded on ONE
monotonic clock (``time.monotonic``, the clock the serve layer already
timestamps with) so durations are immune to wall-clock steps and
manually-timed cross-thread spans share the context-managed spans'
epoch.  Spans nest per thread; a span opened while another is
active becomes its child and inherits its ``leg`` and ``trace_id`` tags
unless it sets its own.

**Phase attribution (the reason this exists).**  Spans tagged with a
``phase`` feed the per-phase totals bench's ``phases`` block reports.
Totals are SELF time: when phase spans nest (a ``decode`` span inside a
``d2h_fetch`` consume span), the parent's contribution is its duration
minus the time covered by phase-tagged descendants, so the per-phase
totals partition the instrumented wall-clock instead of double-counting
it.  Structural spans (``phase=None``) are transparent: their
phase-covered time propagates through to the nearest phase-tagged
ancestor.

**Async dispatch caveat.**  JAX launches are asynchronous: a span around
a ``launch`` closure measures *dispatch* time, and the device time of
everything in flight surfaces in the ``d2h_fetch`` span of whichever
consume blocks on it.  That decomposition is still a true partition of
host wall-clock (and is what the default traced mode reports, at ~zero
overhead).  For per-phase *device* attribution, ``enable(sync=True)``
opts in to ``jax.block_until_ready`` at the close of spans that passed a
``sync_obj`` — this serializes the pipeline overlap (measurement mode,
never the default) and runs inside the strict layer's sanctioned-fetch
scope, so ``LLM_INTERP_STRICT=1`` stays ``blocked_transfers == 0``.

**Outputs.**  Closed spans accumulate in a bounded in-memory ring (the
``phases`` totals are O(1) regardless), stream to a JSONL span log when
``enable(jsonl_path=...)`` is given, and export as Chrome-trace JSON
(``export_chrome``) loadable by Perfetto / ``chrome://tracing``.

When the tracer is disabled every entry point is a cheap no-op, so the
permanent instrumentation in the engine/sweeps/serve layers costs
nothing in ordinary runs.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Any, Dict, Iterator, List, Optional, Tuple

#: spans kept in memory (newest wins); phase totals are unaffected by
#: eviction — they accumulate at span close, not at export time.
DEFAULT_SPAN_CAP = 200_000

#: The canonical phase table.  Every ``phase=`` tag on a span must come
#: from this set: the ``phases`` block partitions wall-clock across these
#: names, ``obs report`` and bench-diff aggregate by them, and the README
#: "Span / phase names" table documents them row for row (graftlint G08
#: enforces the literal-membership rule statically; ``lint contracts``
#: cross-checks this set against the README table).
KNOWN_PHASES = frozenset({
    "host_tokenize", "host_prep", "dispatch", "prefill", "extend_prefill",
    "decode", "pooled_decode", "d2h_fetch", "host_rows", "host_write",
    "serve_queue_wait", "serve_coalesce", "serve_engine", "serve_respond",
})


class _ThreadState(threading.local):
    def __init__(self):
        self.stack: List[Dict[str, Any]] = []


class SpanTracer:
    """One tracer instance == one trace session (module-level singleton
    via :func:`get_tracer` for the instrumented layers)."""

    def __init__(self, span_cap: int = DEFAULT_SPAN_CAP):
        self._lock = threading.Lock()
        self._local = _ThreadState()
        self._on = False
        self._sync = False
        self._memory = False
        self._span_cap = max(1, int(span_cap))
        self._spans: List[Dict[str, Any]] = []
        self._evicted = 0
        self._totals: Dict[Tuple[str, str], float] = {}  # (phase, leg) -> s
        self._counts: Dict[Tuple[str, str], int] = {}
        self._next_id = 0
        self._jsonl_path: Optional[str] = None
        self._jsonl_file = None
        self._t0 = time.monotonic()

    # -- lifecycle -------------------------------------------------------

    @property
    def enabled(self) -> bool:
        return self._on

    def enable(self, jsonl_path: Optional[str] = None, sync: bool = False,
               memory: bool = False) -> None:
        """Arm the tracer (idempotent).  ``jsonl_path`` streams each
        closed span as one JSON line; ``sync`` opts in to
        ``block_until_ready`` at the close of spans carrying a
        ``sync_obj`` (device-time attribution mode — serializes the
        pipeline overlap); ``memory`` attaches a per-device
        ``bytes_in_use`` snapshot to each closed span."""
        with self._lock:
            self._on = True
            self._sync = bool(sync)
            self._memory = bool(memory)
            if jsonl_path and self._jsonl_file is None:
                parent = os.path.dirname(os.path.abspath(jsonl_path))
                os.makedirs(parent, exist_ok=True)
                self._jsonl_path = jsonl_path
                # "w", not "a": the log is ONE session's spans — two runs
                # defaulting to the same path must not aggregate into a
                # doubled-totals report in `obs report`
                self._jsonl_file = open(jsonl_path, "w", encoding="utf-8")
            if self._t0 is None:
                self._t0 = time.monotonic()

    def disable(self) -> None:
        """Stop recording and close the JSONL log.  Recorded spans and
        phase totals stay readable (export after disable is fine)."""
        with self._lock:
            self._on = False
            if self._jsonl_file is not None:
                self._jsonl_file.close()
                self._jsonl_file = None
                self._jsonl_path = None

    def reset(self) -> None:
        """Drop every recorded span and total (tests / fresh sessions)."""
        with self._lock:
            self._spans = []
            self._evicted = 0
            self._totals = {}
            self._counts = {}
            self._t0 = time.monotonic()

    # -- recording -------------------------------------------------------

    def _alloc_id(self) -> int:
        self._next_id += 1
        return self._next_id

    @contextlib.contextmanager
    def span(self, name: str, phase: Optional[str] = None,
             leg: Optional[str] = None, trace_id: Optional[str] = None,
             sync_obj: Any = None, **attrs) -> Iterator[Optional[Dict]]:
        """Open one nested span on the calling thread.

        ``phase`` routes the span's SELF time into the per-phase totals;
        ``leg``/``trace_id`` inherit from the enclosing span when None;
        ``sync_obj`` (any jax pytree) is blocked on at close when the
        tracer was enabled with ``sync=True``; a body whose outputs only
        exist after it runs sets ``rec["_sync_obj"]`` on the yielded
        span instead.  Extra keyword args land in the span's ``args``
        (length bucket, batch size, rows, ...).  Yields the live span
        dict (mutate ``rec["args"]`` to attach results) — or None when
        tracing is off."""
        if not self._on:
            yield None
            return
        stack = self._local.stack
        parent = stack[-1] if stack else None
        if parent is not None:
            leg = leg if leg is not None else parent.get("leg")
            trace_id = (trace_id if trace_id is not None
                        else parent.get("trace_id"))
        rec = {
            "name": name, "phase": phase, "leg": leg, "trace_id": trace_id,
            "t0": time.monotonic(), "t1": None,
            "tid": threading.get_ident(),
            "id": None,
            "parent": parent["id"] if parent is not None else None,
            "args": dict(attrs),
            "_covered": 0.0,
        }
        with self._lock:
            rec["id"] = self._alloc_id()
        stack.append(rec)
        try:
            yield rec
        finally:
            target = rec.pop("_sync_obj", sync_obj)
            if self._sync and target is not None:
                self._block_until_ready(target)
            stack.pop()
            self._close(rec, parent)

    def add_span(self, name: str, start: float, end: float,
                 phase: Optional[str] = None, leg: Optional[str] = None,
                 trace_id: Optional[str] = None, **attrs) -> None:
        """Record a manually-timed span (``start``/``end`` MUST be
        ``time.monotonic`` seconds — the tracer's one clock, so the
        exported timeline aligns with context-managed spans) — the
        cross-thread case the context manager cannot cover, e.g. a serve
        request's queue wait measured between its submitting thread's
        enqueue and the scheduler thread's pop."""
        if not self._on:
            return
        rec = {
            "name": name, "phase": phase, "leg": leg, "trace_id": trace_id,
            "t0": float(start), "t1": float(end),
            "tid": threading.get_ident(),
            "id": None, "parent": None,
            "args": dict(attrs), "_covered": 0.0,
        }
        with self._lock:
            rec["id"] = self._alloc_id()
        self._close(rec, None, already_timed=True)

    def _close(self, rec: Dict, parent: Optional[Dict],
               already_timed: bool = False) -> None:
        if not already_timed:
            rec["t1"] = time.monotonic()
        dur = max(0.0, rec["t1"] - rec["t0"])
        covered = min(rec.pop("_covered"), dur)
        if self._memory:
            mem = _device_bytes_in_use()
            if mem is not None:
                rec["args"]["hbm_bytes_in_use"] = mem
        rec["dur"] = dur
        rec["self"] = dur - covered if rec["phase"] else 0.0
        if parent is not None:
            # a phase span shields its whole duration from the ancestors'
            # self time; a structural span passes through what its own
            # phase-tagged descendants covered
            parent["_covered"] += dur if rec["phase"] else covered
        with self._lock:
            if rec["phase"]:
                key = (rec["phase"], rec["leg"] or "")
                self._totals[key] = self._totals.get(key, 0.0) + rec["self"]
                self._counts[key] = self._counts.get(key, 0) + 1
            self._spans.append(rec)
            if len(self._spans) > self._span_cap:
                drop = len(self._spans) - self._span_cap
                del self._spans[:drop]
                self._evicted += drop
            f = self._jsonl_file
            if f is not None:
                f.write(json.dumps(_public_span(rec)) + "\n")
                # flush per span: the log's crash-recovery promise (a
                # killed run still leaves its spans on disk) is worth
                # more than a buffered write at span volumes (hundreds
                # per sweep, not per token)
                f.flush()

    @staticmethod
    def _block_until_ready(sync_obj: Any) -> None:
        """Opt-in device sync at span close, inside the strict layer's
        sanctioned-fetch scope so an armed transfer guard never counts it
        (``block_until_ready`` waits, it does not transfer — the scope is
        belt-and-braces for backends that materialize on wait)."""
        import jax

        from ..runtime import strict

        with strict.sanctioned_fetch():
            jax.block_until_ready(sync_obj)

    # -- reading ---------------------------------------------------------

    def spans(self) -> List[Dict[str, Any]]:
        """Copy of the retained closed spans (public fields only)."""
        with self._lock:
            return [_public_span(r) for r in self._spans]

    def span_count(self) -> Tuple[int, int]:
        """(retained, evicted) closed-span counts."""
        with self._lock:
            return len(self._spans), self._evicted

    def phase_totals(self, by_leg: bool = False) -> Dict:
        """Accumulated per-phase SELF seconds.  ``by_leg=False`` returns
        ``{phase: seconds}``; ``by_leg=True`` returns
        ``{phase: {leg_or_"": seconds}}``."""
        with self._lock:
            items = list(self._totals.items())
        if not by_leg:
            out: Dict[str, float] = {}
            for (phase, _leg), s in items:
                out[phase] = out.get(phase, 0.0) + s
            return out
        nested: Dict[str, Dict[str, float]] = {}
        for (phase, leg), s in items:
            nested.setdefault(phase, {})[leg] = (
                nested.get(phase, {}).get(leg, 0.0) + s)
        return nested

    def phase_snapshot(self) -> Dict[Tuple[str, str], float]:
        """Opaque snapshot for :meth:`phase_totals_since` — the totals
        are session-cumulative, so a bench scopes its ``phases`` block to
        the measured repeats by snapshotting after warmup/calibration
        (the ``counters_since`` pattern)."""
        with self._lock:
            return dict(self._totals)

    def phase_totals_since(self, snapshot: Dict[Tuple[str, str], float],
                           by_leg: bool = False) -> Dict:
        """Per-phase totals accumulated since ``snapshot``."""
        with self._lock:
            delta = {k: v - snapshot.get(k, 0.0)
                     for k, v in self._totals.items()
                     if v - snapshot.get(k, 0.0) > 0.0}
        if not by_leg:
            out: Dict[str, float] = {}
            for (phase, _leg), s in delta.items():
                out[phase] = out.get(phase, 0.0) + s
            return out
        nested: Dict[str, Dict[str, float]] = {}
        for (phase, leg), s in delta.items():
            nested.setdefault(phase, {})[leg] = s
        return nested

    # -- export ----------------------------------------------------------

    def export_chrome(self, path: str) -> str:
        """Write the retained spans as Chrome-trace JSON (the
        ``traceEvents`` array of complete "X" events, microsecond
        timestamps) — loads in Perfetto and ``chrome://tracing``."""
        with self._lock:
            spans = list(self._spans)
            base = self._t0 or 0.0
        pid = os.getpid()
        events = []
        for r in spans:
            args = dict(r["args"])
            if r["leg"]:
                args["leg"] = r["leg"]
            if r["trace_id"]:
                args["trace_id"] = r["trace_id"]
            args["self_us"] = round(r["self"] * 1e6, 1)
            events.append({
                "name": r["name"],
                "cat": r["phase"] or "span",
                "ph": "X",
                "ts": round((r["t0"] - base) * 1e6, 3),
                "dur": round(r["dur"] * 1e6, 3),
                "pid": pid,
                "tid": r["tid"],
                "args": args,
            })
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        with open(path, "w", encoding="utf-8") as f:
            json.dump({"traceEvents": events,
                       "displayTimeUnit": "ms"}, f)
        return path


def _public_span(rec: Dict) -> Dict:
    return {k: v for k, v in rec.items() if not k.startswith("_")}


def _device_bytes_in_use() -> Optional[int]:
    """Summed ``bytes_in_use`` across local devices; None when the
    backend has no memory stats (CPU) or jax is unavailable."""
    try:
        import jax

        total = 0
        seen = False
        for d in jax.local_devices():
            ms = d.memory_stats() or {}
            if "bytes_in_use" in ms:
                total += int(ms["bytes_in_use"])
                seen = True
        return total if seen else None
    # graftlint: disable=G05 telemetry probe: memory stats are best-effort decoration on a measurement span; a backend without them must never fail the traced run
    except Exception:
        return None


# ---------------------------------------------------------------------------
# Module-level singleton: the instrumented layers (engine, sweeps, serve,
# batching) call these; when disabled every call is a cheap no-op.
# ---------------------------------------------------------------------------

_TRACER = SpanTracer()


def get_tracer() -> SpanTracer:
    return _TRACER


def enabled() -> bool:
    return _TRACER.enabled


def enable(jsonl_path: Optional[str] = None, sync: bool = False,
           memory: bool = False) -> SpanTracer:
    _TRACER.enable(jsonl_path=jsonl_path, sync=sync, memory=memory)
    return _TRACER


def disable() -> None:
    _TRACER.disable()


def span(name: str, **kw):
    return _TRACER.span(name, **kw)


def add_span(name: str, start: float, end: float, **kw) -> None:
    _TRACER.add_span(name, start, end, **kw)


def phase_totals(by_leg: bool = False) -> Dict:
    return _TRACER.phase_totals(by_leg=by_leg)


def phase_snapshot() -> Dict:
    return _TRACER.phase_snapshot()


def phase_totals_since(snapshot: Dict, by_leg: bool = False) -> Dict:
    return _TRACER.phase_totals_since(snapshot, by_leg=by_leg)


def export_chrome(path: str) -> str:
    return _TRACER.export_chrome(path)
