"""Phase-attribution reporting: the ``phases`` block, the stderr table,
and the ``obs report`` CLI over saved traces.

The live path (bench): :func:`phases_block` turns the tracer's per-phase
self-time totals into the JSON block a bench record carries, and
:func:`format_phase_table` renders the same numbers as the stderr table.
The offline path (``python -m llm_interpretation_replication_tpu obs
report --trace FILE``): :func:`load_spans` reads either export format —
the JSONL span log or the Chrome-trace JSON — re-aggregates per
phase/leg, and prints the table, so a saved trace from any past run
stays explainable without re-running it.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional, Sequence


def phases_block(totals_by_leg: Dict[str, Dict[str, float]],
                 wall_s: Optional[float] = None,
                 rows: Optional[int] = None) -> Dict:
    """The bench-record ``phases`` block from per-(phase, leg) self-time
    totals (:meth:`..obs.tracer.SpanTracer.phase_totals` with
    ``by_leg=True``, or a :func:`load_spans` re-aggregation).

    ``wall_s`` is the measured wall-clock the totals should decompose
    (the sum of a bench's timed repeats): ``coverage`` = instrumented
    phase seconds / wall seconds — the acceptance bar is >= 0.9, i.e.
    at most 10% of the measured time is unattributed glue.  Phases on
    background threads (host tokenize overlap) are the honest reason
    coverage can exceed 1.0.  ``rows`` adds per-row milliseconds."""
    phases = {}
    total = 0.0
    for phase in sorted(totals_by_leg):
        legs = totals_by_leg[phase]
        phase_s = sum(legs.values())
        total += phase_s
        entry = {"seconds": round(phase_s, 3)}
        named = {leg: round(s, 3) for leg, s in sorted(legs.items()) if leg}
        if named:
            entry["legs"] = named
        if rows:
            entry["ms_per_row"] = round(phase_s / rows * 1e3, 3)
        phases[phase] = entry
    block: Dict = {"per_phase": phases, "total_s": round(total, 3)}
    if wall_s:
        block["wall_s"] = round(wall_s, 3)
        block["coverage"] = round(total / wall_s, 3)
    if rows:
        block["rows"] = int(rows)
    return block


def format_phase_table(block: Dict, title: str = "phase attribution") -> str:
    """Render a ``phases`` block as an aligned stderr table."""
    per_phase = block.get("per_phase", {})
    total = block.get("total_s", 0.0) or sum(
        e["seconds"] for e in per_phase.values())
    rows = []
    for phase, entry in sorted(per_phase.items(),
                               key=lambda kv: -kv[1]["seconds"]):
        share = entry["seconds"] / total if total else 0.0
        legs = entry.get("legs")
        leg_txt = (" (" + ", ".join(f"{k} {v:.2f}s"
                                    for k, v in legs.items()) + ")"
                   if legs else "")
        per_row = (f" {entry['ms_per_row']:8.2f} ms/row"
                   if "ms_per_row" in entry else "")
        rows.append(f"  {phase:<16} {entry['seconds']:9.2f}s "
                    f"{share * 100:5.1f}%{per_row}{leg_txt}")
    lines = [f"# {title}:"]
    lines.extend(rows or ["  (no phase spans recorded)"])
    tail = f"  {'total':<16} {total:9.2f}s"
    if block.get("wall_s"):
        tail += (f"  of {block['wall_s']:.2f}s wall "
                 f"({block.get('coverage', 0) * 100:.1f}% attributed)")
    lines.append(tail)
    return "\n".join(lines)


def format_serve_load_table(block: Dict) -> str:
    """The per-phase latency table from a bench record's ``serve_load``
    block (ISSUE 11): one section per offered rate — end-to-end
    p50/p90/p99/p99.9 plus each phase's (queue_wait / coalesce /
    serve_engine / respond) percentiles from the exact-count
    histograms, achieved-vs-offered, and the sweep's saturation/knee
    summary."""
    pcts = ("p50", "p90", "p99", "p99.9")
    lines = [f"# serve-load latency anatomy ({block.get('mode', '?')}, "
             f"seed {block.get('seed')}, {block.get('duration_s')}s per "
             f"rate):"]
    for point in block.get("rates", ()) or ():
        lines.append(
            f"  offered {point.get('offered_rate'):g} rows/s -> achieved "
            f"{point.get('achieved_rows_per_s')} "
            f"({point.get('completed')}/{point.get('requests')} ok, "
            f"{point.get('shed', 0)} shed, queue depth max "
            f"{(point.get('queue_depth') or {}).get('max')})")
        header = "    " + "phase".ljust(14) + "".join(
            f"{p:>10}" for p in pcts)
        lines.append(header)
        rows = [("e2e", point.get("latency_ms", {}))]
        rows += [(name, (point.get("phases_ms") or {}).get(name, {}))
                 for name in ("queue_wait", "coalesce", "serve_engine",
                              "respond")]
        for name, vals in rows:
            lines.append("    " + name.ljust(14) + "".join(
                f"{vals[p]:>10.2f}" if p in vals else f"{'-':>10}"
                for p in pcts))
    if block.get("knee_floor_saturated"):
        knee_txt = "unknown (every swept rate saturated)"
    elif block.get("knee_beyond_sweep"):
        knee_txt = "beyond sweep"
    else:
        knee_txt = f"at {block.get('knee_offered_rate')} offered"
    tail = (f"  saturation {block.get('saturation_rows_per_s')} rows/s, "
            f"knee {knee_txt}")
    if block.get("parity_ok") is not None:
        tail += (", parity OK" if block["parity_ok"]
                 else ", PARITY FAILED")
    lines.append(tail)
    return "\n".join(lines)


def load_spans(path: str) -> List[Dict]:
    """Read spans back from either export format.

    JSONL span log: one span object per line.  Chrome-trace JSON: the
    ``traceEvents`` "X" events map back to spans (``cat`` is the phase,
    ``args.leg``/``args.self_us`` restore the leg and self time)."""
    with open(path, encoding="utf-8") as f:
        text = f.read()
    try:
        # one whole-file JSON document == the Chrome-trace export; a
        # JSONL span log has one object PER LINE, so the whole-file parse
        # raises on its second line
        doc = json.loads(text)
    except ValueError:
        doc = None
    if isinstance(doc, dict) and "traceEvents" in doc:
        spans = []
        for ev in doc["traceEvents"]:
            if ev.get("ph") != "X":
                continue
            args = ev.get("args", {})
            phase = ev.get("cat")
            spans.append({
                "name": ev.get("name", ""),
                "phase": None if phase in (None, "span") else phase,
                "leg": args.get("leg"),
                "trace_id": args.get("trace_id"),
                "dur": ev.get("dur", 0.0) / 1e6,
                "self": args.get("self_us", ev.get("dur", 0.0)) / 1e6,
                "args": args,
            })
        return spans
    spans = []
    dropped = 0
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            spans.append(json.loads(line))
        except ValueError:
            # a hard-killed run can tear the trailing line mid-write
            # (the tracer flushes per span, but a kill can still land
            # inside one write) — the report over the surviving spans is
            # exactly what the crashed-run case needs
            dropped += 1
    if dropped:
        print(f"# obs report: skipped {dropped} malformed span line(s) "
              f"(torn tail of a killed run?)", file=sys.stderr)
    return spans


def aggregate_spans(spans: Sequence[Dict]) -> Dict[str, Dict[str, float]]:
    """Per-(phase, leg) SELF-time totals from loaded spans — the same
    shape the live tracer's ``phase_totals(by_leg=True)`` returns."""
    out: Dict[str, Dict[str, float]] = {}
    for s in spans:
        phase = s.get("phase")
        if not phase:
            continue
        leg = s.get("leg") or ""
        self_s = s.get("self")
        if self_s is None:
            self_s = s.get("dur", 0.0)
        by_leg = out.setdefault(phase, {})
        by_leg[leg] = by_leg.get(leg, 0.0) + float(self_s)
    return out


def main(argv: Optional[Sequence[str]] = None) -> int:
    """``obs`` CLI body (routed from __main__ like ``lint``): ``obs
    report`` aggregates a saved span trace; ``obs bench-diff`` runs the
    bench-trajectory regression analyzer (:mod:`.benchdiff`)."""
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv[:1] == ["bench-diff"]:
        # dispatched before this parser: bench-diff has its own argument
        # surface (positional records + thresholds)
        from .benchdiff import main as benchdiff_main

        return benchdiff_main(argv[1:])
    parser = argparse.ArgumentParser(
        prog="llm_interpretation_replication_tpu obs",
        description="observability reports: 'report' aggregates a saved "
                    "span trace (JSONL span log or Chrome-trace/Perfetto "
                    "JSON) per phase/leg; 'bench-diff' aligns BENCH_r*."
                    "json records into a regression table")
    parser.add_argument("action", choices=["report", "bench-diff"],
                        help="'report': aggregate a saved trace per "
                             "phase/leg and print the table; "
                             "'bench-diff': compare bench records "
                             "(handled by obs/benchdiff.py)")
    parser.add_argument("--trace", default=None, metavar="PATH",
                        help="saved trace: the --trace JSONL span log or "
                             "the exported Chrome-trace JSON")
    parser.add_argument("--serve-load", default=None, metavar="BENCH.json",
                        help="render the per-phase latency table from a "
                             "bench record's serve_load block (ISSUE 11: "
                             "per-rate e2e + queue_wait/coalesce/"
                             "serve_engine/respond percentiles) instead "
                             "of a span trace")
    parser.add_argument("--wall-s", type=float, default=None, metavar="S",
                        help="measured wall-clock to compute coverage "
                             "against (e.g. the bench repeat time)")
    parser.add_argument("--rows", type=int, default=None, metavar="N",
                        help="row count for per-row milliseconds")
    parser.add_argument("--format", choices=["table", "json"],
                        default="table")
    args = parser.parse_args(argv)
    if args.serve_load:
        from .benchdiff import load_bench_record

        try:
            rec = load_bench_record(args.serve_load)
        except (OSError, ValueError) as err:
            print(f"obs report: cannot read {args.serve_load}: {err}",
                  file=sys.stderr)
            return 2
        block = rec.get("serve_load")
        if not isinstance(block, dict):
            print(f"obs report: {args.serve_load} carries no serve_load "
                  f"block (bench.py --serve-load produces one)",
                  file=sys.stderr)
            return 2
        if args.format == "json":
            print(json.dumps(block, indent=2))
        else:
            print(format_serve_load_table(block))
        return 0
    if not args.trace:
        parser.error("one of --trace or --serve-load is required")

    try:
        spans = load_spans(args.trace)
    except (OSError, ValueError) as err:
        print(f"obs report: cannot read {args.trace}: {err}",
              file=sys.stderr)
        return 2
    block = phases_block(aggregate_spans(spans), wall_s=args.wall_s,
                         rows=args.rows)
    if args.format == "json":
        print(json.dumps(block, indent=2))
    else:
        print(format_phase_table(
            block, title=f"phase attribution ({len(spans)} spans, "
                         f"{args.trace})"))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
