"""Live metrics export: registry, Prometheus text exposition, HTTP
endpoint, and the streaming JSONL metrics log.

Before this module the run-health surface was post-hoc only: telemetry
counters and sample rings (:mod:`..utils.telemetry`) become visible when
a bench record or a strict report serializes them at END of run.  A
serving process answering live traffic — or a multi-hour sweep someone
wants to watch from a dashboard — needs the same numbers continuously:

- :class:`MetricsRegistry` — the periodic sampler.  Each
  :meth:`~MetricsRegistry.sample` snapshots every telemetry counter
  (reported both raw and as the since-enable delta, the
  ``counters_since`` discipline every bench block already follows),
  every sample ring's percentiles (p50/p90/p99 + total/retained, so the
  ring-truncation semantics stay visible), and any explicitly set
  gauges, into bounded typed time-series.  Counters are Prometheus
  ``counter``\\ s (monotone), ring percentiles export as a ``summary``,
  explicit gauges as ``gauge``.
- :func:`prometheus_text` / :meth:`MetricsRegistry.prometheus_text` —
  the text exposition (format 0.0.4): sanitized metric names under the
  ``llm_interp_`` prefix, escaped label values, one ``# TYPE`` line per
  family, and NO series for rings that never recorded a sample (an
  empty ring must not fabricate a 0-quantile).
- :class:`MetricsServer` — a stdlib-only ``ThreadingHTTPServer`` on a
  daemon thread answering ``GET /metrics`` (the exposition) and
  ``GET /healthz`` (a JSON liveness document, extensible by the host —
  the serve scheduler reports queue depth and closed-ness).  Hosted by
  the ``serve`` CLI behind ``--metrics-port``.
- the JSONL metrics log (``enable_jsonl``) — sweep/bench modes have no
  resident server, so ``--metrics [PATH]`` streams one JSON object per
  sample instead; a crashed run keeps every line already flushed.
- :func:`heartbeat` — the sweep shells' ONE code path for progress:
  formats and logs the ``[heartbeat] done/total | rows/s | ETA`` line
  exactly as before, records the same numbers as registry gauges (and a
  JSONL sample when armed), and feeds the stall watchdog
  (:mod:`.flight`) — so sweep progress is observable without scraping
  stderr.

Measurement-only, like the rest of obs/: nothing here touches the
scoring path, and every export reads the telemetry layer's existing
thread-safe snapshots.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..utils import telemetry

#: points retained per time-series (newest win); bounds a week-long
#: server the same way the telemetry sample rings bound themselves.
DEFAULT_SERIES_CAP = 4096

#: every exported metric family lives under this prefix.
METRIC_PREFIX = "llm_interp_"

_NAME_OK = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")
_NAME_BAD_CHARS = re.compile(r"[^a-zA-Z0-9_:]")
_RING_PCTS = (50.0, 90.0, 99.0)
#: streaming-histogram percentiles: exact counts, no truncation, so the
#: p99.9 the bounded rings cannot keep is reportable here
_HIST_PCTS = (50.0, 90.0, 99.0, 99.9)


def sanitize_metric_name(name: str) -> str:
    """Prometheus metric-name charset: ``[a-zA-Z_:][a-zA-Z0-9_:]*``.
    Invalid characters become ``_``; a leading digit gains one."""
    name = _NAME_BAD_CHARS.sub("_", name)
    if not name or not _NAME_OK.match(name):
        name = "_" + name
    return name


def split_labeled_name(name: str):
    """``(base, labels_or_None)`` for the labeled-telemetry convention
    ``name|k=v,k2=v2`` (serve/scheduler.labeled_metric): the telemetry
    layer keys plain strings, so per-replica series ride the name — the
    exporter splits them back into ONE Prometheus family with label
    sets, which is how the EnginePool's ``serve_*`` counters and
    latency histograms read as ``{replica="r0",model="..."}`` series
    instead of N separate families."""
    if "|" not in name:
        return name, None
    base, _, rest = name.partition("|")
    labels = {}
    for part in rest.split(","):
        k, _, v = part.partition("=")
        if k:
            labels[k] = v
    return base, (labels or None)


def escape_label_value(value: str) -> str:
    """Label-value escaping per the exposition format: backslash, double
    quote, and newline."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _format_labels(labels: Optional[Dict[str, str]]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{sanitize_metric_name(k)}="{escape_label_value(v)}"'
        for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _format_value(value: float) -> str:
    if value != value:                      # NaN never reaches a scraper
        return "0"
    if float(value).is_integer() and abs(value) < 2 ** 53:
        return str(int(value))
    return repr(float(value))


class MetricsRegistry:
    """Typed time-series over the telemetry layer + explicit gauges.

    One registry per process (module singleton via :func:`get_registry`);
    tests may build their own.  All methods are thread-safe — the HTTP
    handler threads, the periodic sampler, and the sweep's heartbeat all
    touch one instance."""

    def __init__(self, series_cap: int = DEFAULT_SERIES_CAP):
        self._lock = threading.Lock()
        self._series_cap = max(1, int(series_cap))
        self._series: Dict[str, List[Tuple[float, float]]] = {}
        self._types: Dict[str, str] = {}       # series name -> counter|gauge
        self._gauges: Dict[Tuple[str, Tuple], Tuple[float, Dict]] = {}
        self._snap0 = telemetry.counters()     # since-enable baseline
        self._jsonl_path: Optional[str] = None
        self._jsonl_file = None
        self._sampler: Optional[threading.Thread] = None
        self._sampler_stop = threading.Event()
        self._t0 = time.time()

    # -- configuration ---------------------------------------------------

    def reset(self) -> None:
        """Drop every series/gauge and re-baseline the counter snapshot
        (tests / fresh sessions)."""
        self.disable_jsonl()
        with self._lock:
            self._series = {}
            self._types = {}
            self._gauges = {}
            self._snap0 = telemetry.counters()
            self._t0 = time.time()

    def enable_jsonl(self, path: str) -> None:
        """Stream one JSON object per :meth:`sample` to ``path`` (``w``
        mode: the log is one session's series, like the span log)."""
        with self._lock:
            if self._jsonl_file is not None:
                return
            parent = os.path.dirname(os.path.abspath(path))
            os.makedirs(parent, exist_ok=True)
            self._jsonl_path = path
            self._jsonl_file = open(path, "w", encoding="utf-8")

    def disable_jsonl(self) -> None:
        with self._lock:
            if self._jsonl_file is not None:
                self._jsonl_file.close()
                self._jsonl_file = None
                self._jsonl_path = None

    @property
    def jsonl_path(self) -> Optional[str]:
        return self._jsonl_path

    # -- recording -------------------------------------------------------

    def set_gauge(self, name: str, value: float,
                  labels: Optional[Dict[str, str]] = None) -> None:
        """Record an instantaneous value (progress, rate, ETA).  Each
        distinct (name, labels) pair is one series."""
        labels = dict(labels or {})
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            self._gauges[key] = (float(value), labels)
            self._record(f"{name}{_format_labels(labels)}", float(value),
                         "gauge")

    def _record(self, series: str, value: float, kind: str) -> None:
        # callers hold self._lock
        self._types[series] = kind
        points = self._series.setdefault(series, [])
        points.append((time.time(), float(value)))
        if len(points) > self._series_cap:
            del points[: len(points) - self._series_cap]

    def sample(self) -> Dict:
        """One sampler tick: snapshot counters (raw + since-enable delta
        via ``counters_since``) and ring percentiles into the series, and
        append the JSONL line when the stream is armed.  Returns the
        sampled document."""
        counters = telemetry.counters()
        delta = telemetry.counters_since(self._snap0)
        rings = {}
        for name, meta in telemetry.sample_ring_report().items():
            pct = telemetry.sample_percentiles(name, _RING_PCTS)
            rings[name] = {**meta, **pct}
        hists = {}
        for name, h in telemetry.hist_report().items():
            pct = telemetry.hist_percentiles(name, _HIST_PCTS)
            hists[name] = {"count": h["count"],
                           "sum": round(h["sum"], 3), **pct}
        doc = {
            "t": round(time.time(), 3),
            "uptime_s": round(time.time() - self._t0, 3),
            "counters": {k: v for k, v in sorted(counters.items())},
            "counters_delta": {k: v for k, v in sorted(delta.items())},
            "rings": rings,
            "hists": hists,
        }
        with self._lock:
            for name, value in counters.items():
                self._record(name, value, "counter")
            for name, meta in rings.items():
                for p in _RING_PCTS:
                    key = f"p{p:g}"
                    if key in meta:
                        self._record(f"{name}_{key}", meta[key], "gauge")
            gauges = {name + _format_labels(labels): value
                      for (name, _), (value, labels) in self._gauges.items()}
            doc["gauges"] = gauges
            f = self._jsonl_file
            if f is not None:
                f.write(json.dumps(doc) + "\n")
                f.flush()           # a killed run keeps every flushed line
        return doc

    # -- reading ---------------------------------------------------------

    def series(self, name: str) -> List[Tuple[float, float]]:
        with self._lock:
            return list(self._series.get(name, ()))

    def series_names(self) -> List[str]:
        with self._lock:
            return sorted(self._series)

    def series_type(self, name: str) -> Optional[str]:
        with self._lock:
            return self._types.get(name)

    def prometheus_text(self) -> str:
        """The current state (fresh counter/ring snapshots + gauges) in
        Prometheus text exposition format 0.0.4."""
        lines: List[str] = []
        typed: set = set()

        def family(name: str, kind: str):
            """(metric, labels) with the TYPE line emitted once per base
            family — labeled series (the ``name|k=v`` convention) group
            under their base name instead of minting a family each."""
            base, labels = split_labeled_name(name)
            metric = METRIC_PREFIX + sanitize_metric_name(base)
            if metric not in typed:
                lines.append(f"# TYPE {metric} {kind}")
                typed.add(metric)
            return metric, labels

        for name, value in sorted(telemetry.counters().items()):
            metric, labels = family(name, "counter")
            lines.append(
                f"{metric}{_format_labels(labels)} {_format_value(value)}")
        # rings as summaries; sample_ring_report only lists rings with at
        # least one recorded sample, so an empty ring emits NO series
        for name, meta in sorted(telemetry.sample_ring_report().items()):
            pct = telemetry.sample_percentiles(name, _RING_PCTS)
            if not pct:
                continue
            metric, labels = family(name, "summary")
            for p in _RING_PCTS:
                key = f"p{p:g}"
                if key in pct:
                    qlabels = {**(labels or {}), "quantile": f"{p / 100.0:g}"}
                    lines.append(f"{metric}{_format_labels(qlabels)} "
                                 f"{_format_value(pct[key])}")
            lines.append(f"{metric}_count{_format_labels(labels)} "
                         f"{int(meta['total'])}")
            lines.append(f"{metric}_retained{_format_labels(labels)} "
                         f"{int(meta['retained'])}")
        # streaming histograms (telemetry.record_hist) as Prometheus
        # ``histogram`` families: cumulative ``_bucket{le=...}`` over the
        # exact log-bucket counts plus ``_sum``/``_count``.  hist_report
        # only lists histograms with >= 1 observation, so an empty one
        # emits NO series (the empty-ring discipline above)
        for name, h in sorted(telemetry.hist_report().items()):
            metric, labels = family(name, "histogram")
            cum = 0
            for le, n in h["buckets"]:
                cum += n
                blabels = {**(labels or {}), "le": f"{le:g}"}
                lines.append(f"{metric}_bucket{_format_labels(blabels)} "
                             f"{cum}")
            inf_labels = {**(labels or {}), "le": "+Inf"}
            lines.append(f"{metric}_bucket{_format_labels(inf_labels)} "
                         f"{int(h['count'])}")
            lines.append(f"{metric}_sum{_format_labels(labels)} "
                         f"{_format_value(h['sum'])}")
            lines.append(f"{metric}_count{_format_labels(labels)} "
                         f"{int(h['count'])}")
        with self._lock:
            # sort on (name, canonical label tuple) — two gauges sharing a
            # name but differing in labels must never compare their label
            # DICTS (TypeError), which is exactly the heartbeat shape: one
            # gauge name, one series per sweep label
            gauges = sorted(
                ((name, labels, value)
                 for (name, _), (value, labels) in self._gauges.items()),
                key=lambda g: (g[0], tuple(sorted(g[1].items()))))
        seen_type = set()
        for name, labels, value in gauges:
            metric = METRIC_PREFIX + sanitize_metric_name(name)
            if metric not in seen_type:
                lines.append(f"# TYPE {metric} gauge")
                seen_type.add(metric)
            lines.append(
                f"{metric}{_format_labels(labels)} {_format_value(value)}")
        return "\n".join(lines) + "\n"

    # -- periodic sampler ------------------------------------------------

    def start_sampler(self, interval_s: float = 5.0) -> None:
        """Sample every ``interval_s`` on a daemon thread (idempotent)."""
        if self._sampler is not None and self._sampler.is_alive():
            return
        self._sampler_stop.clear()

        def loop():
            while not self._sampler_stop.wait(interval_s):
                self.sample()

        self._sampler = threading.Thread(target=loop, name="obs-metrics",
                                         daemon=True)
        self._sampler.start()

    def stop_sampler(self) -> None:
        self._sampler_stop.set()
        if self._sampler is not None:
            self._sampler.join(timeout=2.0)
            self._sampler = None


# ---------------------------------------------------------------------------
# HTTP endpoint (stdlib only)
# ---------------------------------------------------------------------------

class MetricsServer:
    """``/metrics`` + ``/healthz`` over ``http.server`` on a daemon
    thread.  ``healthz_fn`` (optional) contributes extra keys to the
    health document — the serve scheduler reports queue depth and
    closed-ness through it.  ``port=0`` binds an ephemeral port (tests);
    read :attr:`port` after :meth:`start`.

    Binds loopback by default: the endpoint is unauthenticated, so
    exposing it beyond the host is an explicit operator decision
    (``host="0.0.0.0"``), never a default."""

    def __init__(self, registry: MetricsRegistry, port: int,
                 host: str = "127.0.0.1",
                 healthz_fn: Optional[Callable[[], Dict]] = None):
        self.registry = registry
        self.host = host
        self.port = int(port)
        self.healthz_fn = healthz_fn
        self._httpd = None
        self._thread: Optional[threading.Thread] = None
        self._t0 = time.time()

    def start(self) -> "MetricsServer":
        import http.server

        server = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):   # no per-scrape stderr spam
                pass

            def _send(self, code: int, content_type: str,
                      body: bytes) -> None:
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    body = server.registry.prometheus_text().encode("utf-8")
                    self._send(200, "text/plain; version=0.0.4; "
                                    "charset=utf-8", body)
                elif path == "/healthz":
                    doc = {"status": "ok",
                           "uptime_s": round(time.time() - server._t0, 3)}
                    if server.healthz_fn is not None:
                        try:
                            doc.update(server.healthz_fn())
                        except Exception as err:  # graftlint: disable=G05 liveness probe: a failing health contributor downgrades the document, it must never 500 the scrape loop
                            doc["status"] = "degraded"
                            doc["error"] = str(err)
                    body = json.dumps(doc).encode("utf-8")
                    self._send(200, "application/json", body)
                else:
                    self._send(404, "text/plain; charset=utf-8",
                               b"not found\n")

        self._httpd = http.server.ThreadingHTTPServer(
            (self.host, self.port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="obs-metrics-http",
            daemon=True)
        self._thread.start()
        return self

    def close(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def __enter__(self) -> "MetricsServer":
        return self.start()

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()


# ---------------------------------------------------------------------------
# Module singleton + the heartbeat code path
# ---------------------------------------------------------------------------

_REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _REGISTRY


def prometheus_text() -> str:
    return _REGISTRY.prometheus_text()


def enable_jsonl(path: str) -> MetricsRegistry:
    _REGISTRY.enable_jsonl(path)
    return _REGISTRY


def heartbeat(label: str, done: int, total: int, elapsed_s: float,
              log: Optional[Callable[[str], None]] = None,
              unit: str = "rows", rate: Optional[float] = None,
              rate_unit: Optional[str] = None,
              eta_s: Optional[float] = None) -> str:
    """The sweep shells' single progress code path.

    Formats the ``[heartbeat]`` line (the perturbation shell's PR-6
    format, byte-identical; the instruct shell's line gains this
    labeled spelling), emits it through ``log`` (when given), records
    the same numbers as registry gauges — ``sweep_progress_rows``,
    ``sweep_progress_total``, ``sweep_rows_per_s``, ``sweep_eta_s``,
    each labeled by ``label`` — appends a JSONL metrics sample when the
    stream is armed, and beats the active stall watchdog
    (:mod:`.flight`).  Returns the formatted line.

    ``rate``/``rate_unit``/``eta_s`` override the ``done/elapsed``
    derivation when the progress unit and the rate unit differ (the
    instruct sweep counts MODELS but reports rows/s, so its ETA is
    caller-computed)."""
    if rate is None:
        rate = done / elapsed_s if elapsed_s > 0 else 0.0
    eta = (eta_s if eta_s is not None
           else ((total - done) / rate if rate > 0 else 0.0))
    line = (f"[heartbeat] {label}: {done}/{total} {unit} "
            f"| {rate:.2f} {rate_unit or unit}/s | ETA {eta:.0f}s")
    if log is not None:
        log(line)
    labels = {"label": label}
    _REGISTRY.set_gauge("sweep_progress_rows", done, labels)
    _REGISTRY.set_gauge("sweep_progress_total", total, labels)
    _REGISTRY.set_gauge("sweep_rows_per_s", round(rate, 3), labels)
    _REGISTRY.set_gauge("sweep_eta_s", round(eta, 1), labels)
    if _REGISTRY.jsonl_path is not None:
        _REGISTRY.sample()
    from . import flight

    flight.notify_heartbeat(label=label, done=done, total=total,
                            rate=round(rate, 3))
    return line
