"""Observability: span tracing, phase attribution, and profiler hooks.

The repo's perf story has counters and sample rings
(:mod:`..utils.telemetry`) but, before this subsystem, no *time
decomposition*: the full-study sweep runs at a fraction of the binary
leg's rate and nobody could point at where the per-row wall-clock
actually goes (ROADMAP item 1).  This package closes that gap with three
cooperating layers, all measurement-only (no numeric contract changes —
PARITY.md "Observability"):

- :mod:`.tracer` — a thread-safe nested span tracer on monotonic clocks.
  The engine hot path (host tokenize/prefetch, prefill — monolithic and
  chunked — ``extend_prefill``, decode chunks, pooled phase-2 decode,
  d2h fetch), the sweep shells, and the serve scheduler all open spans
  tagged by phase, leg, length bucket, and batch.  Spans export as
  Chrome-trace/Perfetto JSON and stream to a JSONL span log; per-phase
  SELF-time totals (nested phase spans never double-count) are the
  ``phases`` block bench records gain.
- :mod:`.profiler` — windowed ``jax.profiler`` capture (``--profile`` on
  bench/CLI) plus per-device memory snapshots, for when host spans are
  not enough and the XLA op timeline is needed.
- :mod:`.report` — the ``obs report`` CLI over saved traces and the
  table/JSON renderers bench uses live.

The LIVE/longitudinal run-health layer sits next to the tracer (all
measurement-only too):

- :mod:`.metrics` — metrics registry + Prometheus text exposition +
  stdlib ``/metrics``+``/healthz`` HTTP endpoint (``serve
  --metrics-port``) + streaming JSONL metrics log (``--metrics``), and
  the sweep shells' single :func:`~.metrics.heartbeat` code path (log
  line AND gauges from one place).
- :mod:`.flight` — flight recorder (bounded recent-activity ring dumped
  as a ``flightrec-*.json`` triage artifact on OOM-ladder engagement,
  transient-retry exhaustion, preemption, or watchdog trip) and the
  stall watchdog (warn + dump when a sweep stops progressing; never
  kills).
- :mod:`.benchdiff` — the ``obs bench-diff`` trajectory analyzer over
  ``BENCH_r*.json`` records (regression table with thresholds).

Strict-mode contract: tracing performs NO device→host transfer of its
own.  The opt-in ``sync`` at span close (``enable(sync=True)``) calls
``jax.block_until_ready`` inside the strict layer's sanctioned-fetch
scope, so a traced run under ``LLM_INTERP_STRICT=1`` stays
``blocked_transfers == 0``.
"""

from .tracer import (
    SpanTracer,
    add_span,
    disable,
    enable,
    enabled,
    export_chrome,
    get_tracer,
    phase_snapshot,
    phase_totals,
    phase_totals_since,
    span,
)

__all__ = [
    "SpanTracer",
    "add_span",
    "disable",
    "enable",
    "enabled",
    "export_chrome",
    "get_tracer",
    "phase_snapshot",
    "phase_totals",
    "phase_totals_since",
    "span",
]
