"""Windowed ``jax.profiler`` capture + device-memory snapshots.

Host spans (:mod:`.tracer`) decompose wall-clock per phase; when a phase
itself needs explaining (which XLA op inside the decode is slow), the
profiler's device timeline is the next tool down.  ``profile_window``
wraps one measured window — bench's ``--profile DIR`` captures repeat 0
of a sweep mode — and the capture is viewable in TensorBoard/Perfetto
and parseable headlessly by :func:`..utils.profiling.top_device_ops`
(the analysis that located the round-3 decode relayout loop).

Capture is best-effort by design: a backend without profiler support (or
a capture already in flight) logs one stderr line and the measured run
proceeds untraced — a perf measurement must never die on its own
instrumentation.
"""

from __future__ import annotations

import contextlib
import sys
from typing import Dict, Iterator, List, Optional

from .tracer import get_tracer


@contextlib.contextmanager
def profile_window(log_dir: Optional[str], enabled: bool = True) -> Iterator[bool]:
    """Capture a ``jax.profiler`` trace into ``log_dir`` for the duration.

    Yields True when a capture actually started (False when disabled or
    the profiler was unavailable).  Start/stop failures degrade to a
    stderr note instead of failing the profiled run."""
    if not enabled or not log_dir:
        yield False
        return
    try:
        import jax

        jax.profiler.start_trace(log_dir)
        started = True
    # graftlint: disable=G05 best-effort capture: a profiler that cannot start must not kill the measured run it was decorating
    except Exception as err:
        print(f"# obs: jax.profiler capture unavailable ({err}); "
              f"window runs unprofiled", file=sys.stderr)
        yield False
        return
    try:
        yield started
    finally:
        try:
            jax.profiler.stop_trace()
            print(f"# obs: profiler capture written to {log_dir}",
                  file=sys.stderr)
        # graftlint: disable=G05 best-effort capture teardown: a stop failure loses the capture, never the measured result
        except Exception as err:
            print(f"# obs: jax.profiler stop failed ({err})",
                  file=sys.stderr)


def device_memory_snapshot(tag: str = "") -> List[Dict]:
    """Per-device memory stats (``bytes_in_use``/``bytes_limit``/
    ``peak_bytes_in_use`` where the backend reports them), recorded as a
    zero-duration ``device_memory`` span when tracing is on.  Returns the
    snapshot list ([] on backends without stats, e.g. CPU)."""
    out: List[Dict] = []
    try:
        import jax

        for d in jax.local_devices():
            ms = d.memory_stats() or {}
            if not ms:
                continue
            out.append({
                "device": f"{d.platform}:{d.id}",
                "bytes_in_use": int(ms.get("bytes_in_use", 0)),
                "bytes_limit": int(ms.get("bytes_limit", 0)),
                "peak_bytes_in_use": int(ms.get("peak_bytes_in_use", 0)),
            })
    # graftlint: disable=G05 telemetry probe: a backend without memory stats must never fail the run being observed
    except Exception:
        return out
    tracer = get_tracer()
    if tracer.enabled and out:
        import time

        now = time.perf_counter()
        tracer.add_span("device_memory", now, now, tag=tag,
                        devices=out,
                        bytes_in_use=sum(d["bytes_in_use"] for d in out))
    return out
