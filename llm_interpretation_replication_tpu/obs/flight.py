"""Flight recorder + stall watchdog: the triage artifact a dying or
stuck run leaves behind.

A hung or preempted sweep used to leave nothing but its side-log; an
OOM-ladder walk left a stderr line and a telemetry fault event that died
with the process's memory.  This module keeps a bounded ring of recent
activity and, when something goes wrong, dumps it as ONE self-contained
``flightrec-*.json`` file next to the run's artifacts:

- :class:`FlightRecorder` — a bounded frame ring (heartbeats, notes)
  plus, at dump time, the tail of the telemetry fault-event log, the
  counter deltas since arming, every sample ring's percentiles (with the
  total-vs-retained truncation block), the span tracer's recent span
  summaries when tracing is on, and a host/device memory summary.
  Armed via :func:`enable`; it registers a telemetry fault listener, so
  EVERY existing ``record_fault`` chokepoint becomes a trigger — the
  engine's OOM ladder (``engine_oom_backoff``), the bench repeat policy
  (``sweep_oom_backoff``/``sweep_oom_skip``), the serve split/re-queue
  path (``serve_oom_split``), transient-retry exhaustion
  (``transient_exhausted``, :func:`..runtime.faults.retry_transient`),
  preemption (``preempted``, via :class:`..runtime.faults.
  PreemptionGuard`'s flush-then-record path — the sweep SIGTERM/SIGINT
  shells), and the watchdog below (``watchdog_stall``).  Dumps are
  rate-limited per trigger kind so a ladder walking three steps down
  produces one artifact, not three.
- :class:`StallWatchdog` — a heartbeat monitor for the sweep shells.
  :func:`..obs.metrics.heartbeat` beats it once per chunk; a daemon
  thread flags the sweep when no beat lands within ``k`` × the trailing
  median chunk time (with an absolute floor so fast test sweeps never
  false-positive).  A trip WARNS and dumps a flight record — it never
  kills the run: a slow-but-progressing sweep keeps its operating
  point, and the trip state resets on the next real beat.

Everything here is best-effort by design (G05 disable comments mark the
deliberate keep-alive catches): a triage artifact writer that could
crash the run it is documenting would be worse than no artifact.
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import threading
import time
from typing import Dict, List, Optional

from ..utils import telemetry

#: fault-event kinds that trigger a dump when the recorder is armed.
TRIGGER_KINDS = frozenset({
    "engine_oom_backoff", "sweep_oom_backoff", "sweep_oom_skip",
    "serve_oom_split", "transient_exhausted", "preempted",
    "watchdog_stall",
    # fleet self-healing: every replica kill / wedge / poison reject and
    # every breaker trip leaves a post-mortem artifact when armed.
    "pool_replica_crash", "pool_replica_wedged",
    "pool_replica_quarantined", "pool_poison_request", "breaker_open",
})

#: frames retained in the activity ring.
DEFAULT_FRAME_CAP = 512

#: per-trigger-kind dump cooldown: one ladder walk == one artifact.
DEFAULT_COOLDOWN_S = 30.0


class FlightRecorder:
    """Bounded recent-activity ring + the flightrec-*.json dumper."""

    def __init__(self, frame_cap: int = DEFAULT_FRAME_CAP,
                 cooldown_s: float = DEFAULT_COOLDOWN_S):
        # RLock, deliberately: the trigger path can run inside a SIGNAL
        # HANDLER (PreemptionGuard -> record_fault -> listener) on the
        # same main thread that was interrupted mid-note(); a plain Lock
        # would self-deadlock the handler.
        self._lock = threading.RLock()
        self._frames: List[Dict] = []
        self._frame_cap = max(1, int(frame_cap))
        self._cooldown_s = float(cooldown_s)
        self._out_dir: Optional[str] = None
        self._snap0: Dict[str, float] = {}
        self._armed_t: Optional[float] = None
        self._last_dump: Dict[str, float] = {}   # kind -> monotonic time
        self._seq = 0
        self._workers: List[threading.Thread] = []
        self.dumps: List[str] = []               # paths written (newest last)

    # -- lifecycle -------------------------------------------------------

    @property
    def enabled(self) -> bool:
        return self._out_dir is not None

    def enable(self, out_dir: str) -> "FlightRecorder":
        """Arm the recorder: dumps land in ``out_dir``.  Idempotent;
        re-arming with a new directory just redirects future dumps (the
        counter baseline is kept from the first arming so deltas span
        the whole run)."""
        with self._lock:
            first = self._out_dir is None
            self._out_dir = os.path.abspath(out_dir)
            if first:
                self._snap0 = telemetry.counters()
                self._armed_t = time.time()
        telemetry.add_fault_listener(self._on_fault)
        return self

    def disable(self) -> None:
        telemetry.remove_fault_listener(self._on_fault)
        with self._lock:
            self._out_dir = None
            self._frames = []
            self._last_dump = {}

    # -- activity ring ---------------------------------------------------

    def note(self, kind: str, **info) -> None:
        """Append one frame to the activity ring (no-op when disarmed)."""
        if not self.enabled:
            return
        frame = {"time": round(time.time(), 3), "kind": str(kind), **info}
        with self._lock:
            self._frames.append(frame)
            if len(self._frames) > self._frame_cap:
                del self._frames[: len(self._frames) - self._frame_cap]

    # -- triggers --------------------------------------------------------

    def _on_fault(self, event: Dict) -> None:
        kind = event.get("kind", "")
        if kind not in TRIGGER_KINDS:
            return
        # Reserve the dump slot SYNCHRONOUSLY (cheap, RLock-safe even
        # from a signal handler), then build+write the artifact on a
        # short-lived NON-daemon worker thread.  The fault path — which
        # may be a signal handler interrupting a frame that holds the
        # telemetry lock — must never call telemetry.counters() itself
        # (self-deadlock); the worker thread holds no locks, and being
        # non-daemon the interpreter waits for the write to land even
        # when the trigger is a preemption about to exit the process.
        ticket = self._reserve(kind)
        if ticket is None:
            return
        worker = threading.Thread(
            target=self._write_dump, args=(kind, dict(event)) + ticket,
            name="obs-flight-dump", daemon=False)
        with self._lock:
            self._workers = [t for t in self._workers if t.is_alive()]
            self._workers.append(worker)
        worker.start()

    def wait(self, timeout: float = 5.0) -> None:
        """Join any in-flight async dump workers (tests / orderly
        shutdown; the non-daemon threads also block interpreter exit on
        their own)."""
        with self._lock:
            workers = list(self._workers)
        for t in workers:
            t.join(timeout=timeout)

    def _reserve(self, reason: str):
        """Cooldown check + state snapshot under the recorder lock.
        Returns ``(seq, frames, snap0, armed_t, out_dir)`` or None when
        disarmed / inside the cooldown."""
        with self._lock:
            out_dir = self._out_dir
            if out_dir is None:
                return None
            now = time.monotonic()
            last = self._last_dump.get(reason)
            if last is not None and now - last < self._cooldown_s:
                return None
            self._last_dump[reason] = now
            self._seq += 1
            return (self._seq, list(self._frames), dict(self._snap0),
                    self._armed_t, out_dir)

    def dump(self, reason: str, trigger: Optional[Dict] = None
             ) -> Optional[str]:
        """Write one flightrec-*.json synchronously (rate-limited per
        ``reason``).  Returns the path, or None when disarmed / inside
        the cooldown / unwritable.  Direct callers only — the fault-
        listener trigger path goes through the async worker instead
        (see :meth:`_on_fault`)."""
        ticket = self._reserve(reason)
        if ticket is None:
            return None
        return self._write_dump(reason, trigger, *ticket)

    def _write_dump(self, reason: str, trigger: Optional[Dict],
                    seq: int, frames: List[Dict], snap0: Dict,
                    armed_t: Optional[float], out_dir: str
                    ) -> Optional[str]:
        doc = {
            "reason": reason,
            "time": round(time.time(), 3),
            "pid": os.getpid(),
            "armed_at": armed_t,
            "trigger": trigger,
            "frames": frames,
            "fault_events": telemetry.fault_events()[-100:],
            "counters": telemetry.counters(),
            "counters_since_armed": telemetry.counters_since(snap0),
            "rings": {
                name: {**meta,
                       **telemetry.sample_percentiles(name)}
                for name, meta in telemetry.sample_ring_report().items()
            },
            "memory": telemetry.get_memory_usage(),
        }
        try:
            from .tracer import get_tracer

            tracer = get_tracer()
            if tracer.enabled:
                doc["spans"] = [
                    {k: s.get(k) for k in ("name", "phase", "leg",
                                           "trace_id", "t0", "dur", "self")}
                    for s in tracer.spans()[-200:]
                ]
        # graftlint: disable=G05 triage decoration: span summaries are best-effort context on a crash artifact; a tracer hiccup must not lose the dump
        except Exception:
            pass
        path = os.path.join(out_dir,
                            f"flightrec-{reason}-{os.getpid()}-{seq}.json")
        try:
            os.makedirs(out_dir, exist_ok=True)
            with open(path, "w", encoding="utf-8") as f:
                json.dump(doc, f, indent=2, default=str)
        # graftlint: disable=G05 triage artifact writer: a full disk while dumping a crash record must never mask the fault being recorded
        except Exception as err:
            print(f"# obs: flight-record dump failed ({err})",
                  file=sys.stderr)
            return None
        with self._lock:
            self.dumps.append(path)
        print(f"# obs: flight record written to {path} (reason: {reason})",
              file=sys.stderr)
        return path


# ---------------------------------------------------------------------------
# Stall watchdog
# ---------------------------------------------------------------------------

class StallWatchdog:
    """Flag (never kill) a sweep making no forward progress.

    Fed by the heartbeat path: one :meth:`beat` per completed chunk.
    :meth:`check` trips when the time since the last beat exceeds
    ``max(floor_s, k * median(trailing chunk intervals))`` — the median
    needs ``min_beats`` intervals first, so startup and compile time
    never false-positive.  A trip records a ``watchdog_stall`` telemetry
    fault event (which dumps a flight record when the recorder is armed)
    and warns on stderr, once per stall: the trip state resets on the
    next beat.  ``clock`` is injectable for tests."""

    def __init__(self, label: str = "", k: float = 4.0, min_beats: int = 3,
                 floor_s: float = 5.0, poll_s: float = 1.0,
                 interval_window: int = 32, clock=time.monotonic):
        self.label = label
        self.k = float(k)
        self.min_beats = int(min_beats)
        self.floor_s = float(floor_s)
        self.poll_s = float(poll_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._intervals: List[float] = []
        self._interval_window = int(interval_window)
        self._last_beat: Optional[float] = None
        self._tripped = False
        self.trips = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def beat(self, progress: Optional[int] = None) -> None:
        now = self._clock()
        with self._lock:
            if self._last_beat is not None:
                self._intervals.append(now - self._last_beat)
                if len(self._intervals) > self._interval_window:
                    del self._intervals[: len(self._intervals)
                                        - self._interval_window]
            self._last_beat = now
            self._tripped = False

    def threshold_s(self) -> Optional[float]:
        """The current trip threshold, or None while history is short."""
        with self._lock:
            if len(self._intervals) < self.min_beats:
                return None
            return max(self.floor_s,
                       self.k * statistics.median(self._intervals))

    def check(self, now: Optional[float] = None) -> bool:
        """True exactly once per stall (until the next beat resets it)."""
        threshold = self.threshold_s()
        with self._lock:
            if (threshold is None or self._last_beat is None
                    or self._tripped):
                return False
            idle = (now if now is not None else self._clock()) \
                - self._last_beat
            if idle <= threshold:
                return False
            self._tripped = True
            self.trips += 1
            median = statistics.median(self._intervals)
        telemetry.record_fault(
            "watchdog_stall", label=self.label, idle_s=round(idle, 1),
            threshold_s=round(threshold, 1),
            median_chunk_s=round(median, 2))
        print(f"# obs: watchdog — {self.label or 'sweep'} made no progress "
              f"for {idle:.0f}s (threshold {threshold:.0f}s = "
              f"{self.k:g}x median chunk {median:.1f}s); run left alive, "
              f"flight record dumped if armed", file=sys.stderr)
        return True

    # -- background polling + active-watchdog registration ---------------

    def start(self) -> "StallWatchdog":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()

            def loop():
                while not self._stop.wait(self.poll_s):
                    self.check()

            self._thread = threading.Thread(
                target=loop, name="obs-watchdog", daemon=True)
            self._thread.start()
        _set_active_watchdog(self)
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        _clear_active_watchdog(self)

    def __enter__(self) -> "StallWatchdog":
        return self.start()

    def __exit__(self, exc_type, exc, tb) -> None:
        self.stop()


# ---------------------------------------------------------------------------
# Module singletons: one recorder per process; the ACTIVE watchdog is
# whatever sweep shell currently runs (the heartbeat path feeds it).
# ---------------------------------------------------------------------------

_RECORDER = FlightRecorder()
_ACTIVE_WATCHDOG: Optional[StallWatchdog] = None
_ACTIVE_LOCK = threading.Lock()


def get_recorder() -> FlightRecorder:
    return _RECORDER


def enable(out_dir: str) -> FlightRecorder:
    return _RECORDER.enable(out_dir)


def disable() -> None:
    _RECORDER.disable()


def _set_active_watchdog(wd: StallWatchdog) -> None:
    global _ACTIVE_WATCHDOG
    with _ACTIVE_LOCK:
        _ACTIVE_WATCHDOG = wd


def _clear_active_watchdog(wd: StallWatchdog) -> None:
    global _ACTIVE_WATCHDOG
    with _ACTIVE_LOCK:
        if _ACTIVE_WATCHDOG is wd:
            _ACTIVE_WATCHDOG = None


def notify_heartbeat(label: str, done: int, total: int,
                     rate: float) -> None:
    """The heartbeat fan-out (:func:`..obs.metrics.heartbeat` calls
    this): beat the active watchdog and note a frame in the recorder."""
    with _ACTIVE_LOCK:
        wd = _ACTIVE_WATCHDOG
    if wd is not None:
        wd.beat(done)
    _RECORDER.note("heartbeat", label=label, done=done, total=total,
                   rate=rate)
