"""Mixture-of-Experts MLP with expert parallelism (GShard-style all_to_all).

Beyond-reference capability (SURVEY.md §2.7 marks EP absent upstream).  Two
interchangeable compute paths over one parameter layout:

- :func:`moe_mlp_dense` — every expert computed for every token, masked and
  combined by the router gates.  Exact semantics, O(E·T·F) FLOPs; the
  correctness oracle and single-device fallback.
- :func:`moe_mlp_sharded` — the TPU path: experts sharded over a mesh axis
  (GShard maps experts across the data-parallel axis), tokens routed with
  capacity-C one-hot dispatch tensors, moved to their expert's device with
  ``lax.all_to_all`` over ICI, expert FLOPs computed locally, and combined on
  the way back.  O(T·K·F) FLOPs + two all_to_alls.

Router: softmax over all experts, take top-k, renormalize the selected
probabilities (Mixtral-style), with the Switch-Transformer auxiliary
load-balancing loss available for training.

Parameter layout (leading ``E`` axis shards over the expert axis):
  ``{"router": [H, E], "wi": [E, H, F], "wo": [E, F, H]}``
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P


def init_moe_params(key, hidden: int, ffn: int, num_experts: int, dtype=jnp.float32):
    kr, ki, ko = jax.random.split(key, 3)
    scale = 0.02
    return {
        "router": jax.random.normal(kr, (hidden, num_experts), dtype) * scale,
        "wi": jax.random.normal(ki, (num_experts, hidden, ffn), dtype) * scale,
        "wo": jax.random.normal(ko, (num_experts, ffn, hidden), dtype) * scale,
    }


def route(params, x, top_k: int, renormalize: bool = True):
    """Top-k routing.  x: [T, H] → (gates [T, K], indices [T, K] int32,
    probs [T, E] full softmax for the aux loss)."""
    logits = x.astype(jnp.float32) @ params["router"].astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    gates, indices = lax.top_k(probs, top_k)
    if renormalize:
        gates = gates / jnp.maximum(gates.sum(axis=-1, keepdims=True), 1e-9)
    return gates, indices, probs


def routing_stats(probs, indices, num_experts: int):
    """Per-expert (fraction of tokens routed, mean router prob) — the two
    reduced statistics the aux loss is built from.  The sharded path pmeans
    these across shards before the product so the global loss matches the
    single-device value."""
    one_hot = jax.nn.one_hot(indices[..., 0], num_experts, dtype=probs.dtype)
    return one_hot.mean(axis=0), probs.mean(axis=0)


def aux_loss_from_stats(fraction, mean_prob, num_experts: int):
    """Switch-Transformer aux loss: E · Σ_e fraction_e × mean_prob_e.
    Minimized at uniform routing."""
    return num_experts * jnp.sum(fraction * mean_prob)


def load_balancing_loss(probs, indices, num_experts: int):
    fraction, mean_prob = routing_stats(probs, indices, num_experts)
    return aux_loss_from_stats(fraction, mean_prob, num_experts)


def _expert_ffn(wi, wo, x, activation):
    return activation(x @ wi) @ wo


def moe_mlp_dense(params, x, top_k: int = 2, activation=jax.nn.gelu,
                  renormalize: bool = True):
    """Oracle path: compute every expert for every token, gate-combine.

    x: [T, H] → [T, H].  Also returns the aux loss.
    """
    num_experts = params["router"].shape[-1]
    gates, indices, probs = route(params, x, top_k, renormalize)
    # [E, T, H]: every expert applied to every token
    expert_out = jax.vmap(
        lambda wi, wo: _expert_ffn(wi, wo, x, activation)
    )(params["wi"], params["wo"])
    # combine weights [T, E]: gate where selected, 0 elsewhere
    combine = jnp.zeros((x.shape[0], num_experts), expert_out.dtype)
    for k in range(top_k):
        combine = combine + gates[:, k, None] * jax.nn.one_hot(
            indices[:, k], num_experts, dtype=expert_out.dtype
        )
    out = jnp.einsum("te,eth->th", combine, expert_out)
    return out.astype(x.dtype), load_balancing_loss(probs, indices, num_experts)


def _dispatch_tensors(gates, indices, num_experts: int, capacity: int):
    """Capacity-C one-hot dispatch/combine tensors from top-k routing.

    gates/indices: [T, K].  Returns (dispatch [T, E, C] one-hot,
    combine [T, E, C] gate-weighted).  Token t's k-th choice lands in expert
    e's c-th capacity slot where c counts prior assignments to e; choices
    beyond capacity are dropped (standard GShard overflow behavior).
    """
    t = gates.shape[0]
    k = gates.shape[1]
    # Flatten (k, t) so primary choices (k=0) claim capacity slots first.
    flat_idx = indices.T.reshape(-1)          # [K*T], k-major
    flat_gate = gates.T.reshape(-1)
    onehot = jax.nn.one_hot(flat_idx, num_experts, dtype=jnp.float32)  # [KT, E]
    position = jnp.cumsum(onehot, axis=0) * onehot - 1.0               # slot per row
    keep = (position >= 0) & (position < capacity)
    slot = jax.nn.one_hot(
        position.max(axis=-1).astype(jnp.int32), capacity, dtype=jnp.float32
    )
    dispatch_flat = onehot[:, :, None] * slot[:, None, :] * keep.max(-1)[:, None, None]
    combine_flat = dispatch_flat * flat_gate[:, None, None]
    dispatch = dispatch_flat.reshape(k, t, num_experts, capacity).sum(axis=0)
    combine = combine_flat.reshape(k, t, num_experts, capacity).sum(axis=0)
    return dispatch, combine


@functools.partial(
    jax.jit,
    static_argnames=(
        "mesh", "axis_name", "top_k", "capacity_factor", "activation",
        "renormalize",
    ),
)
def moe_mlp_sharded(
    params,
    x,
    mesh,
    axis_name: str = "data",
    top_k: int = 2,
    capacity_factor: float = 2.0,
    activation=jax.nn.gelu,
    renormalize: bool = True,
):
    """Expert-parallel MoE: experts sharded over ``axis_name``, tokens moved
    to their experts via all_to_all and back.

    x: [B, H] tokens sharded over ``axis_name`` on the batch dim (the usual
    data-parallel activation layout).  params leaves shard on their leading
    expert axis.  Returns ([B, H], aux_loss) matching
    :func:`moe_mlp_dense` wherever no token overflowed expert capacity.
    """
    num_experts = params["router"].shape[-1]
    n_shards = mesh.shape[axis_name]
    if num_experts % n_shards:
        raise ValueError(f"{num_experts} experts not divisible over {n_shards} shards")

    def body(router, wi, wo, xb):
        # xb: local tokens [t, H]; wi/wo: local experts [E/n, ...]
        t = xb.shape[0]
        capacity = max(1, int(capacity_factor * top_k * t / num_experts))
        gates, indices, probs = route({"router": router}, xb, top_k, renormalize)
        dispatch, combine = _dispatch_tensors(gates, indices, num_experts, capacity)
        buf = jnp.einsum("tec,th->ech", dispatch, xb.astype(jnp.float32))
        # [E, C, H] → [n, E/n·C, H] → all_to_all(tiled) → [E/n, n·C, H]:
        # shard s ends up holding, for each of its local experts, the C
        # capacity slots from every source shard.
        h = buf.shape[-1]
        buf = buf.reshape(n_shards, (num_experts // n_shards) * capacity, h)
        buf = lax.all_to_all(buf, axis_name, split_axis=0, concat_axis=0, tiled=True)
        buf = buf.reshape(n_shards, num_experts // n_shards, capacity, h)
        buf = jnp.moveaxis(buf, 0, 1).reshape(
            num_experts // n_shards, n_shards * capacity, h
        )
        out = jax.vmap(
            lambda wi_e, wo_e, xe: _expert_ffn(
                wi_e.astype(jnp.float32), wo_e.astype(jnp.float32), xe, activation
            )
        )(wi, wo, buf)  # [E/n, n·C, H]
        # Reverse the exchange back to [E, C, H] on the token-owning shard.
        out = out.reshape(num_experts // n_shards, n_shards, capacity, h)
        out = jnp.moveaxis(out, 1, 0).reshape(
            n_shards, (num_experts // n_shards) * capacity, h
        )
        out = lax.all_to_all(out, axis_name, split_axis=0, concat_axis=0, tiled=True)
        out = out.reshape(num_experts, capacity, h)
        y = jnp.einsum("tec,ech->th", combine, out)
        # Global aux loss: average the routing stats across shards BEFORE the
        # product so it equals the single-device value.
        frac, mean_prob = routing_stats(probs, indices, num_experts)
        aux = aux_loss_from_stats(
            lax.pmean(frac, axis_name), lax.pmean(mean_prob, axis_name), num_experts
        )
        return y.astype(xb.dtype), aux

    mapped = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(P(), P(axis_name), P(axis_name), P(axis_name)),
        out_specs=(P(axis_name), P()),
        axis_names=frozenset({axis_name}),
        check_vma=False,
    )
    # Partial-manual shard_map only lowers under a jit trace (see
    # parallel/pipeline.py); inside a caller's jit this traces inline.
    return jax.jit(mapped)(params["router"], params["wi"], params["wo"], x)
