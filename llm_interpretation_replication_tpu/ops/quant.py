"""W8A8 int8 quantization for the scoring forward pass.

The reference runs its 7B checkpoints through bitsandbytes ``load_in_8bit``
(run_base_vs_instruct_100q.py:414-451, compare_instruct_models.py:436-443) —
an int8 *memory* trick on CUDA.  On TPU the int8 story is different: the v5e
MXU executes int8×int8→int32 at ~1.5× the bf16 rate, so quantizing both
weights AND activations turns the compute-bound scoring sweep itself faster,
not just smaller.  This module implements that path:

- weights: symmetric per-output-channel int8 (scale = max|w| / 127 over the
  input dim), computed once at load time;
- activations: symmetric per-token dynamic int8 (scale from the running
  max|x| of each token's feature vector), computed inside the jit'd forward;
- matmul: ``lax.dot_general`` int8×int8 with ``preferred_element_type=int32``
  so XLA lowers onto the MXU's int8 path, then one fused rescale
  ``y * (s_x ⊗ s_w)`` back to the activation dtype.

Attention scores/softmax and norms stay in bf16/fp32 — only the six large
projection matmuls per block (QKV, out, MLP in/out ≈98% of FLOPs) quantize.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

# Keys eligible for quantization inside a stacked decoder layer pytree.
_ATTN_KEYS = ("wq", "wk", "wv", "wo")
_MLP_KEYS = ("wi", "wg", "wo")
_QSCALE_SUFFIX = "_qscale"


def quantize_weight(w: jnp.ndarray, *, contract_axis: int = -2):
    """Symmetric per-output-channel int8 quantization.

    ``w`` has shape ``[..., K, N]`` (possibly with a leading stacked-layer
    axis); the contraction (input) axis is ``contract_axis`` and every other
    trailing axis indexes output channels.  Returns ``(w_int8, scale_f32)``
    with ``scale`` shaped like ``w`` minus the contraction axis, such that
    ``w ≈ w_int8 * scale``.
    """
    absmax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=contract_axis, keepdims=True)
    scale = jnp.maximum(absmax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale), -127, 127).astype(jnp.int8)
    return q, jnp.squeeze(scale, axis=contract_axis)


def quantize_activations(x: jnp.ndarray):
    """Symmetric per-token dynamic int8: scale over the last (feature) axis."""
    absmax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1, keepdims=True)
    scale = jnp.maximum(absmax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale), -127, 127).astype(jnp.int8)
    return q, scale


def quantize_kv(x: jnp.ndarray):
    """Per-head symmetric int8 for decode-time K/V cache blocks.

    ``x`` is a K or V block whose LAST axis is head_dim (``[..., G, D]`` —
    the ``[L, B, T, G, D]`` stacked cache layout or any per-layer slice of
    it); the scale is the absmax over that head_dim axis, one fp32 value
    per (…, slot, head).  Per-head (not per-tensor) scales matter because
    attention K/V magnitudes vary strongly across slots and heads: a
    shared scale would crush early-token K vectors to a few codes.

    Returns ``(q_int8, scale_f32)`` with ``scale`` shaped like ``x`` minus
    the head_dim axis, such that ``x ≈ q * scale[..., None]``.  Pairs with
    :func:`dequantize_kv`; the cache stores both
    (models/decoder.KVCache.k_scale / v_scale).

    The ``jax.named_scope`` marks these (and the dequant below) carry
    into the lowered HLO's op metadata, so a ``--profile`` capture
    (obs/profiler.py) attributes the quantize/dequantize cost by name on
    the device timeline — host spans cannot see inside a jitted
    program."""
    with jax.named_scope("kv_quantize"):
        absmax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1,
                         keepdims=True)
        scale = jnp.maximum(absmax, 1e-8) / 127.0
        q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale),
                     -127, 127).astype(jnp.int8)
        return q, jnp.squeeze(scale, axis=-1)


def dequantize_kv(q: jnp.ndarray, scale: jnp.ndarray, dtype=jnp.float32):
    """Inverse of :func:`quantize_kv`: ``q * scale[..., None]`` in ``dtype``.

    The multiply runs in fp32 (scales are fp32) before the final cast so a
    bf16 target dtype rounds the PRODUCT, not the scale."""
    with jax.named_scope("kv_dequantize"):
        return (q.astype(jnp.float32) * scale[..., None]).astype(dtype)


def int8_matmul(x: jnp.ndarray, w_q: jnp.ndarray, w_scale: jnp.ndarray):
    """``x @ dequant(w_q)`` computed on the int8 MXU path.

    x: ``[..., K]`` float; w_q: ``[K, N]`` int8; w_scale: ``[N]`` fp32.
    Returns ``[..., N]`` in ``x.dtype``.
    """
    x_q, x_scale = quantize_activations(x)
    y = lax.dot_general(
        x_q, w_q,
        (((x_q.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    return (y.astype(jnp.float32) * x_scale * w_scale).astype(x.dtype)


def linear(p: dict, key: str, x: jnp.ndarray) -> jnp.ndarray:
    """Dispatch a projection: int8 path when ``{key}_qscale`` is present."""
    qs = p.get(key + _QSCALE_SUFFIX)
    if qs is not None:
        return int8_matmul(x, p[key], qs)
    return x @ p[key]


def quantize_weight_np(w, *, contract_axis: int = -2):
    """Host-side (numpy) twin of :func:`quantize_weight` for the load path —
    quantizes while weights are still host arrays, so the full bf16 copy never
    touches device HBM."""
    import numpy as np

    w = np.asarray(w, np.float32)
    absmax = np.abs(w).max(axis=contract_axis, keepdims=True)
    scale = np.maximum(absmax, 1e-8) / 127.0
    q = np.clip(np.round(w / scale), -127, 127).astype(np.int8)
    return q, np.squeeze(scale, axis=contract_axis).astype(np.float32)


def _quantize_layers(params: dict, quantize_fn) -> dict:
    """Shared walker: replace each eligible projection weight with
    ``(int8, {name}_qscale)`` produced by ``quantize_fn``."""
    out = dict(params)
    layers = dict(out["layers"])
    for group, keys in (("attn", _ATTN_KEYS), ("mlp", _MLP_KEYS)):
        if group not in layers:
            continue
        g = dict(layers[group])
        for k in keys:
            w = g.get(k)
            if w is not None and getattr(w, "ndim", 0) >= 2:
                q, s = quantize_fn(w)
                g[k] = q
                g[k + _QSCALE_SUFFIX] = s
        layers[group] = g
    out["layers"] = layers
    return out


def quantize_decoder_params_np(params: dict) -> dict:
    """Host-side twin of :func:`quantize_decoder_params` (numpy in/out)."""
    return _quantize_layers(params, quantize_weight_np)


def quantize_decoder_params(params: dict) -> dict:
    """Quantize a decoder param pytree's projection weights in place-of.

    Stacked layer weights ``[L, K, N]`` become int8 with ``[L, N]`` scales
    stored under ``{name}_qscale``.  Embedding, norms, biases, and the
    (tied) unembedding stay in their original dtype — they are a rounding
    error of the FLOPs and the logit head is accuracy-critical.
    """
    return _quantize_layers(params, quantize_weight)
