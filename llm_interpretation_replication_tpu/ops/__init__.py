from .attention import attention, flash_attention
from .moe import init_moe_params, moe_mlp_dense, moe_mlp_sharded
from .quant import (
    int8_matmul,
    quantize_decoder_params,
    quantize_decoder_params_np,
    quantize_weight,
)

__all__ = [
    "attention",
    "flash_attention",
    "init_moe_params",
    "moe_mlp_dense",
    "moe_mlp_sharded",
    "int8_matmul",
    "quantize_decoder_params",
    "quantize_decoder_params_np",
    "quantize_weight",
]
