from .attention import attention, flash_attention
