"""Fused attention ops: Pallas TPU flash attention + XLA fallback.

The prompt forward pass is the sweep's FLOP hot spot (SURVEY.md §3.1); this
kernel keeps the S×S score matrix out of HBM by computing attention blockwise
in VMEM with an online softmax (flash-attention recurrence):

    grid = (batch, heads, Sq/BLOCK_Q); per program the query block lives in
    VMEM while K/V stream through ``pl.ds`` slices; m/l/acc carry the
    softmax state in fp32; matmuls run on the MXU via
    ``preferred_element_type=float32``.

``attention(...)`` dispatches: Pallas on TPU backends, a dense XLA
implementation elsewhere (tests run the kernel in interpret mode to pin the
two paths together).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e9
DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128


def _dense_attention(q, k, v, lengths, causal: bool):
    """Reference XLA path: [B, N, S, D] inputs."""
    b, n, s, d = q.shape
    scores = jnp.einsum("bnsd,bntd->bnst", q, k).astype(jnp.float32)
    scores = scores / jnp.sqrt(jnp.asarray(d, jnp.float32))
    cols = jnp.arange(s)
    valid = cols[None, :] < lengths[:, None]                   # [B, S]
    mask = valid[:, None, None, :]
    if causal:
        mask = mask & (cols[None, None, :, None] >= cols[None, None, None, :])
    scores = jnp.where(mask, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bnst,bntd->bnsd", probs, v)


def _flash_kernel(len_ref, q_ref, k_ref, v_ref, o_ref, *, block_q, block_k,
                  seq_len, causal):
    bi = pl.program_id(0)
    qi = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32)                        # [BQ, D]
    d = q.shape[-1]
    scale = jax.lax.rsqrt(jnp.asarray(d, jnp.float32))
    q = q * scale
    length = len_ref[bi]

    rows = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
    num_kv = seq_len // block_k

    def body(ki, carry):
        m, l, acc = carry
        k_blk = k_ref[0, 0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, 0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)  # [BQ, BK]
        cols = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        mask = cols < length
        if causal:
            mask = mask & (cols <= rows)
        s = jnp.where(mask, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        correction = jnp.exp(m - m_new)
        l_new = l * correction + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * correction + jnp.dot(p, v_blk, preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, q.shape[-1]), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, num_kv, body, (m0, l0, acc0))
    out = jnp.where(l > 0, acc / jnp.maximum(l, 1e-30), 0.0)
    o_ref[0, 0] = out.astype(o_ref.dtype)


try:  # pallas imports fail gracefully on unsupported backends
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _PALLAS_OK = True
except Exception:  # pragma: no cover
    _PALLAS_OK = False


def pick_block(s: int, default: int) -> Optional[int]:
    """Largest power-of-two block ≤ ``default`` that divides ``s`` (≥8 so the
    MXU/VPU tiles stay efficient).  None when no such block exists — non-
    power-of-two length buckets like 448/320/192 are all multiples of 64, so
    in practice this only fails on pathological sequence lengths."""
    blk = default                      # defaults are powers of two
    while blk > s:
        blk //= 2
    while blk >= 8:
        if s % blk == 0:
            return blk
        blk //= 2
    return None


def flash_attention(
    q, k, v,                       # [B, N, S, D]
    lengths,                       # [B] int32 valid key counts
    causal: bool = True,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    interpret: bool = False,
):
    """Pallas flash attention over [B, N, S, D]; blocks shrink to the largest
    power-of-two divisor of S (bucketed batching keeps S a multiple of 64)."""
    b, n, s, d = q.shape
    block_q = pick_block(s, block_q)
    block_k = pick_block(s, block_k)
    if block_q is None or block_k is None:
        raise ValueError(f"seq {s} has no power-of-two block divisor >= 8")
    grid = (b, n, s // block_q)
    kernel = functools.partial(
        _flash_kernel, block_q=block_q, block_k=block_k, seq_len=s, causal=causal
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bi, ni, qi, lens: (bi, ni, qi, 0)),
            pl.BlockSpec((1, 1, s, d), lambda bi, ni, qi, lens: (bi, ni, 0, 0)),
            pl.BlockSpec((1, 1, s, d), lambda bi, ni, qi, lens: (bi, ni, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d), lambda bi, ni, qi, lens: (bi, ni, qi, 0)),
    )
    fn = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, n, s, d), q.dtype),
        interpret=interpret,
    )
    return fn(jnp.asarray(lengths, jnp.int32), q, k, v)


def attention(q, k, v, lengths, causal: bool = True, force: Optional[str] = None,
              interpret: bool = False):
    """Dispatch: 'pallas' on TPU, dense XLA elsewhere.  ``force`` overrides."""
    backend = force
    if backend is None:
        # works under tracing too (committed device platform is unavailable
        # on tracers; the default backend is what jit will compile for)
        platform = jax.default_backend()
        backend = "pallas" if (_PALLAS_OK and platform == "tpu") else "dense"
        if backend == "pallas" and pick_block(q.shape[2], DEFAULT_BLOCK_Q) is None:
            backend = "dense"      # no valid block for this length: XLA path
            # (auto-selected only; an explicit force='pallas' still raises so
            # parity tests can't silently compare dense against itself)
    if backend == "pallas":
        return flash_attention(q, k, v, lengths, causal, interpret=interpret)
    return _dense_attention(q, k, v, lengths, causal)
