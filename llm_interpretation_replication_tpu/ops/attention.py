"""Fused attention ops: Pallas TPU flash attention + XLA fallback.

The prompt forward pass is the sweep's FLOP hot spot (SURVEY.md §3.1); this
kernel keeps the S×S score matrix out of HBM by computing attention blockwise
in VMEM with an online softmax (flash-attention recurrence):

    grid = (batch, heads, Sq/BLOCK_Q); per program the query block lives in
    VMEM while K/V stream through ``pl.ds`` slices; m/l/acc carry the
    softmax state in fp32; matmuls run on the MXU via
    ``preferred_element_type=float32``.

``attention(...)`` dispatches: Pallas on TPU backends, a dense XLA
implementation elsewhere (tests run the kernel in interpret mode to pin the
two paths together).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e9
DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128


def _dense_attention(q, k, v, lengths, causal: bool):
    """Reference XLA path: [B, N, S, D] inputs."""
    b, n, s, d = q.shape
    scores = jnp.einsum("bnsd,bntd->bnst", q, k).astype(jnp.float32)
    scores = scores / jnp.sqrt(jnp.asarray(d, jnp.float32))
    cols = jnp.arange(s)
    valid = cols[None, :] < lengths[:, None]                   # [B, S]
    mask = valid[:, None, None, :]
    if causal:
        mask = mask & (cols[None, None, :, None] >= cols[None, None, None, :])
    scores = jnp.where(mask, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = jnp.einsum("bnst,bntd->bnsd", probs, v)
    # NEG_INF is finite, so a fully-masked row softmaxes to uniform 1/S and
    # would return the mean of V; zero it instead (length-0 padded rows),
    # matching the ring/Ulysses semantics.
    return jnp.where(lengths[:, None, None, None] > 0, out, 0)


def _flash_kernel(len_ref, q_ref, k_ref, v_ref, o_ref, *, block_q, block_k,
                  seq_len, causal):
    bi = pl.program_id(0)
    qi = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32)                        # [BQ, D]
    d = q.shape[-1]
    scale = jax.lax.rsqrt(jnp.asarray(d, jnp.float32))
    q = q * scale
    length = len_ref[bi]

    rows = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
    num_kv = seq_len // block_k

    def body(ki, carry):
        m, l, acc = carry
        k_blk = k_ref[0, 0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, 0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)  # [BQ, BK]
        cols = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        mask = cols < length
        if causal:
            mask = mask & (cols <= rows)
        s = jnp.where(mask, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        correction = jnp.exp(m - m_new)
        l_new = l * correction + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * correction + jnp.dot(p, v_blk, preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, q.shape[-1]), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, num_kv, body, (m0, l0, acc0))
    # NEG_INF is finite, so l is always > 0 (a fully-masked row sums exp(0)
    # over every column); the real fully-masked condition is a zero valid-key
    # count — causal rows always see >= 1 column when length > 0.
    out = jnp.where(length > 0, acc / jnp.maximum(l, 1e-30), 0.0)
    o_ref[0, 0] = out.astype(o_ref.dtype)


try:  # pallas imports fail gracefully on unsupported backends
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _PALLAS_OK = True
except (ImportError, AttributeError):  # pragma: no cover
    # absent/renamed experimental module only — a device error at import
    # time must propagate to faults classification, not silently route
    # every sweep onto the dense fallback (graftlint G05)
    _PALLAS_OK = False


def pick_block(s: int, default: int) -> Optional[int]:
    """Largest power-of-two block ≤ ``default`` that divides ``s`` (≥8 so the
    MXU/VPU tiles stay efficient).  None when no such block exists — non-
    power-of-two length buckets like 448/320/192 are all multiples of 64, so
    in practice this only fails on pathological sequence lengths."""
    blk = default                      # defaults are powers of two
    while blk > s:
        blk //= 2
    while blk >= 8:
        if s % blk == 0:
            return blk
        blk //= 2
    return None


def flash_attention(
    q, k, v,                       # [B, N, S, D]
    lengths,                       # [B] int32 valid key counts
    causal: bool = True,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    interpret: bool = False,
):
    """Pallas flash attention over [B, N, S, D]; blocks shrink to the largest
    power-of-two divisor of S (bucketed batching keeps S a multiple of 64)."""
    b, n, s, d = q.shape
    block_q = pick_block(s, block_q)
    block_k = pick_block(s, block_k)
    if block_q is None or block_k is None:
        raise ValueError(f"seq {s} has no power-of-two block divisor >= 8")
    grid = (b, n, s // block_q)
    kernel = functools.partial(
        _flash_kernel, block_q=block_q, block_k=block_k, seq_len=s, causal=causal
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bi, ni, qi, lens: (bi, ni, qi, 0)),
            pl.BlockSpec((1, 1, s, d), lambda bi, ni, qi, lens: (bi, ni, 0, 0)),
            pl.BlockSpec((1, 1, s, d), lambda bi, ni, qi, lens: (bi, ni, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d), lambda bi, ni, qi, lens: (bi, ni, qi, 0)),
    )
    fn = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, n, s, d), q.dtype),
        interpret=interpret,
    )
    return fn(jnp.asarray(lengths, jnp.int32), q, k, v)


# ---------------------------------------------------------------------------
# Grouped single-pass prefill kernel (MQA/GQA)
# ---------------------------------------------------------------------------
#
# For multi-query (Falcon: 71 heads share 1 K/V head) and grouped-query
# (Mistral: 32 share 8) prefill at sweep-bucket lengths (S ≤ ~2k), the whole
# [S, D] K/V block for one group fits in VMEM, so online softmax is
# unnecessary: flatten the group's (heads × S) query rows into one long row
# axis and do ONE [rows, D]·[D, S] → softmax → [rows, S]·[S, D] pass per
# program, consuming K/V *unrepeated* and never materializing the
# [B, N, S, S] score tensor in HBM.
#
# Measured reality on v5e (Falcon-7B geometry, B=192, S=432): the kernel runs
# ~45 ms/layer vs ~22 ms/layer for XLA's fused dense attention in situ — both
# are VPU-bound on the fp32 softmax/mask passes and XLA overlaps them with
# the surrounding int8 projections better than the sequential Pallas grid
# does, so ``attention_impl='xla'`` stays the sweep default (bench.py).  The
# kernel still earns its keep where dense attention can't go: it takes
# grouped K/V directly (no [B, N, S, D] repeat — 2×754 MB saved per layer at
# the sweep shape), works for any S%16==0 bucket (the per-head flash kernel
# needs a power-of-two block divisor and crashed the worker at S=432), and
# keeps peak memory flat at long S where dense's S² scores OOM.

GROUPED_BLOCK_ROWS = 512
GROUPED_MAX_SEQ = 2048           # [BLOCK_ROWS, S] fp32 scores stay < 4 MB VMEM


def _grouped_kernel(len_ref, q_ref, k_ref, v_ref, o_ref, *, block_rows,
                    seq_len, causal):
    bi = pl.program_id(0)
    ri = pl.program_id(2)
    q = q_ref[0, 0]                                            # [BR, D] input dtype
    d = q.shape[-1]
    k = k_ref[0, 0]                                            # [S, D]
    v = v_ref[0, 0]
    # matmuls stay in the input dtype (bf16 on the sweep path — the MXU's
    # native rate; fp32 operands would run at a fraction of it) with fp32
    # accumulation; masking/softmax run in fp32.
    s = jax.lax.dot_general(                                   # q @ k.T [BR, S]
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    s = s * jax.lax.rsqrt(jnp.asarray(d, jnp.float32))
    # Single-compare masking: the valid-column count per row is
    # min(length, pos+1); computing it on [BR, 1] keeps the expensive
    # broadcast work to ONE [BR, S] compare + select (the kernel is
    # VPU-bound on these elementwise passes, not on the MXU matmuls).
    bound = jnp.full((block_rows, 1), len_ref[bi], jnp.int32)
    if causal:
        rows = ri * block_rows + jax.lax.broadcasted_iota(
            jnp.int32, (block_rows, 1), 0
        )
        pos = rows - (rows // seq_len) * seq_len               # row's seq position
        bound = jnp.minimum(bound, pos + 1)
    cols = jax.lax.broadcasted_iota(jnp.int32, (block_rows, seq_len), 1)
    s = jnp.where(cols < bound, s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    out = jnp.dot(p.astype(v.dtype), v, preferred_element_type=jnp.float32)
    # NEG_INF is finite so l is always > 0; a row is truly fully masked iff
    # its valid-column bound is 0 — zero those rows (length-0 padded batch
    # rows) instead of returning a uniform average of V.
    out = jnp.where(bound > 0, out / jnp.maximum(l, 1e-30), 0.0)  # [BR, D]
    o_ref[0, 0] = out.astype(o_ref.dtype)


def grouped_attention(
    q,                             # [B, N, S, D]
    k, v,                          # [B, G, S, D], N % G == 0 (G=1 for MQA)
    lengths,                       # [B] int32 valid key counts
    causal: bool = True,
    block_rows: int = GROUPED_BLOCK_ROWS,
    interpret: bool = False,
):
    """Single-pass Pallas attention with K/V resident in VMEM per group.

    Query heads sharing a K/V group are flattened into the row axis (rows are
    padded up to a ``block_rows`` multiple; pad rows compute garbage that is
    sliced off).  Row → (head, position) is recovered inside the kernel as
    ``pos = row % S`` for the causal mask.
    """
    b, n, s, d = q.shape
    g = k.shape[1]
    hpg = n // g
    rows = hpg * s
    q = q.reshape(b, g, rows, d)
    rows_pad = -(-rows // block_rows) * block_rows
    if rows_pad != rows:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, rows_pad - rows), (0, 0)))
    grid = (b, g, rows_pad // block_rows)
    kernel = functools.partial(
        _grouped_kernel, block_rows=block_rows, seq_len=s, causal=causal
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_rows, d), lambda bi, gi, ri, lens: (bi, gi, ri, 0)),
            pl.BlockSpec((1, 1, s, d), lambda bi, gi, ri, lens: (bi, gi, 0, 0)),
            pl.BlockSpec((1, 1, s, d), lambda bi, gi, ri, lens: (bi, gi, 0, 0)),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, block_rows, d), lambda bi, gi, ri, lens: (bi, gi, ri, 0)
        ),
    )
    fn = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, g, rows_pad, d), q.dtype),
        interpret=interpret,
    )
    out = fn(jnp.asarray(lengths, jnp.int32), q, k, v)
    return out[:, :, :rows, :].reshape(b, g, hpg, s, d).reshape(b, n, s, d)


# ---------------------------------------------------------------------------
# Causal block-skipping grouped flash kernel (layout-native [B, S, N, D])
# ---------------------------------------------------------------------------
#
# Second-generation sweep kernel, attacking the two costs the first two
# kernels (and XLA dense) all pay:
#
# 1. **Upper-triangle waste.** Dense/grouped/flash all compute the FULL [S, S]
#    score matrix and then mask — for causal attention half of the matmul,
#    exp, and compare/select work lands on positions that are discarded.
#    Here the k-block loop runs only up to each query block's causal/length
#    bound, and only the *boundary* blocks pay the compare/select mask;
#    interior blocks run mask-free.  At S=432 that halves the VPU softmax
#    work (the measured 14% of step time — bench.py) and the attention MXU
#    work.
#
# 2. **Layout transposes.** The projections produce [B, S, N, D]; the
#    head-major kernels force two [B, N, S, D] transposes of the 754 MB
#    q/out tensors per layer.  This kernel consumes the projection layout
#    directly: rows of one program are (query-position block × the heads
#    sharing a KV group) — a free reshape for MQA — and K/V arrive unrepeated
#    ([B, S, G, D], only the small grouped tensors get transposed).
#
# Online softmax (m/l/acc in fp32) keeps scores out of HBM as in the flash
# kernel; matmuls stay in the input dtype (bf16) for full MXU rate.
#
# MEASURED OUTCOME (v5e, Falcon-7B geometry, S=432, bf16, B=48 standalone /
# B=192 end-to-end in the int8 scoring sweep — the VERDICT r1 #3 experiment):
#
# | attention                        | standalone ms | sweep p/s (e2e) |
# |----------------------------------|---------------|-----------------|
# | XLA dense (fused by compiler)    | 21.6          | **38.2**        |
# | r1 grouped single-pass kernel    | 20.2          | 33.3            |
# | this kernel, dynamic fori_loop   | 22.7 (130 s compile) | 16.5     |
# | this kernel, static grid+scratch | **16.2**      | 33.6            |
# | XLA dense, microbatch=2 overlap  | —             | 31.6            |
#
# The static form is the fastest attention op measured — 25% over XLA dense
# standalone, block-size-insensitive (bp 8/16/24/48 within 16.2-18.1) — yet
# still loses ~12% end-to-end: a Pallas call is an opaque boundary, so XLA
# cannot fuse/overlap it with the surrounding int8 projections the way it
# overlaps its own dense attention (projections measure ~94% of int8 MXU
# peak with dense attention in situ).  Recovering that would mean fusing the
# int8 QKV/out projections INTO the kernel — a near-full-layer program whose
# expected value is negative given XLA's existing 94%.  Closed as
# measured-infeasible for the sweep default ('xla' stays); this kernel is
# the long-S / memory-bound path: no [B,N,S,D] K/V repeat, no S² HBM
# scores, causal block-skip, and the best standalone latency.
#
# Two engineering lessons, paid for in compile hours: (a) data-dependent
# fori_loop bounds lower to a serial `while` that disables Mosaic's
# pipeliner (4x slower, 130 s compiles) — use a static grid dimension with
# @pl.when predication instead; (b) [rows, 1] per-row state wastes 127/128
# VPU lanes — keep m/l lane-broadcast at [rows, block_k] (33% faster).

CAUSAL_BLOCK_K = 128
CAUSAL_MAX_ROWS = 1024           # [rows, BLOCK_K] fp32 scores ≤ 512 KB VMEM


def pick_block_pos(s: int, heads_per_group: int,
                   max_rows: int = CAUSAL_MAX_ROWS,
                   min_blocks: int = 4) -> Optional[int]:
    """Query-position block ``bp``: divides ``s``, flattened row count
    ``bp * heads_per_group`` sublane-aligned (%8) and within VMEM budget.

    Among valid blocks, prefer the largest with at least ``min_blocks`` query
    blocks — one giant block (nq=1, the MHA temptation) would make every
    k-tile a boundary tile and skip nothing, defeating the causal
    block-skipping the kernel exists for.  Falls back to the largest valid
    block when no divisor leaves ``min_blocks`` (short sequences)."""
    valid = []
    for bp in range(1, s + 1):
        if s % bp:
            continue
        rows = bp * heads_per_group
        if rows % 8 or rows > max_rows:
            continue
        valid.append(bp)
    if not valid:
        return None
    skipping = [bp for bp in valid if s // bp >= min_blocks]
    return max(skipping) if skipping else max(valid)


def _causal_grouped_kernel(len_ref, q_ref, k_ref, v_ref, o_ref,
                           m_scr, l_scr, acc_scr, *,
                           block_pos, hpg, block_k, n_k, causal):
    # Grid: (batch, group, q-block, k-block) with the k dimension 'arbitrary'
    # (sequential) — m/l/acc live in VMEM scratch across k steps.  Static
    # trip counts keep Mosaic's pipeliner on; the causal skip is a @pl.when
    # predicate, so tiles above the diagonal cost a branch, not compute.
    # (A first version used fori_loop with data-dependent bounds: Mosaic
    # lowers that to a serial while that disables pipelining — measured 4x
    # slower than this form and 130 s to compile.)
    bi = pl.program_id(0)
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    q = q_ref[0, 0, 0]                                 # [rows, D] pre-flattened
    rows, d = q.shape                                  # row = pos_in_block*hpg + head
    length = len_ref[bi]
    pos0 = qi * block_pos
    if causal:
        clean_end = jnp.minimum(length, pos0 + 1)      # cols every row sees
        bound_max = jnp.minimum(length, pos0 + block_pos)
    else:
        clean_end = length
        bound_max = length

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    def row_bounds(lanes):
        # [rows, lanes] with every lane equal — 1-lane vectors waste 127/128
        # of the VPU, so all per-row state here stays lane-broadcast (the
        # same layout trick as the reference JAX TPU flash kernel's m/l).
        pos = pos0 + jax.lax.broadcasted_iota(jnp.int32, (rows, lanes), 0) // hpg
        if causal:
            return jnp.minimum(length, pos + 1)
        return jnp.full((rows, lanes), length, jnp.int32)

    def tile(masked):
        kb = k_ref[0, 0]                               # [BK, D]
        vb = v_ref[0, 0]
        s = lax.dot_general(                           # [rows, BK] fp32
            q, kb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * jax.lax.rsqrt(jnp.asarray(d, jnp.float32))
        if masked:
            cols = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (rows, block_k), 1
            )
            s = jnp.where(cols < row_bounds(block_k), s, NEG_INF)
        m = m_scr[...]                                 # [rows, BK] lane-bcast
        l = l_scr[...]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        # A fully-masked boundary tile can only hit a row whose m is already
        # finite (a row's first executed tile always holds >=1 valid column
        # when row_bound > 0), so exp(NEG_INF - m_new) underflows to 0.
        p = jnp.exp(s - m_new)                         # lanes of m_new equal
        corr = jnp.exp(m - m_new)
        m_scr[...] = m_new
        l_scr[...] = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[...] = acc_scr[...] * corr[:, :d] + lax.dot_general(
            p.astype(vb.dtype), vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    needed = ki * block_k < bound_max
    clean = (ki + 1) * block_k <= clean_end            # no masking required

    @pl.when(needed & clean)
    def _clean_tile():
        tile(masked=False)

    @pl.when(needed & jnp.logical_not(clean))
    def _boundary_tile():
        tile(masked=True)

    @pl.when(ki == n_k - 1)
    def _finalize():
        out = jnp.where(
            row_bounds(d) > 0,
            acc_scr[...] / jnp.maximum(l_scr[...][:, :d], 1e-30),
            0.0,
        )
        o_ref[0, 0, 0] = out.astype(o_ref.dtype)


def causal_grouped_attention(
    q,                             # [B, S, N, D] — projection-native layout
    k, v,                          # [B, S, G, D], N % G == 0 (unrepeated)
    lengths,                       # [B] int32 valid key counts
    causal: bool = True,
    block_k: int = CAUSAL_BLOCK_K,
    block_pos: Optional[int] = None,
    interpret: bool = False,
):
    """Causal block-skipping grouped flash attention; returns [B, S, N, D]."""
    b, s, n, d = q.shape
    g = k.shape[2]
    hpg = n // g
    if block_pos is None:
        block_pos = pick_block_pos(s, hpg)
        if block_pos is None:
            raise ValueError(
                f"no sublane-aligned query block for S={s}, heads/group={hpg}"
            )
    nq = s // block_pos
    block_k = max(block_k, d)      # kernel slices corr/l down to [:, :d]
    s_pad = -(-s // block_k) * block_k
    k = jnp.swapaxes(k, 1, 2)                          # [B, G, S, D] (small)
    v = jnp.swapaxes(v, 1, 2)
    if s_pad != s:
        # padded cols carry garbage scores; every block touching them is a
        # boundary block (col >= s >= length) and masks them off
        k = jnp.pad(k, ((0, 0), (0, 0), (0, s_pad - s), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, s_pad - s), (0, 0)))
    rows = block_pos * hpg
    n_k = s_pad // block_k
    # Flatten (pos-in-block, head) into the row axis OUTSIDE the kernel
    # (Mosaic cannot shape-cast merged sublane dims in VMEM).  For MQA (g=1,
    # the flagship Falcon case) moving the size-1 group axis is a bitcast —
    # no data movement; GQA/MHA pay one transpose each way, same as the
    # head-major kernels did.
    q5 = q.reshape(b, nq, block_pos, g, hpg, d)
    q5 = q5.transpose(0, 3, 1, 2, 4, 5).reshape(b, g, nq, rows, d)
    grid = (b, g, nq, n_k)
    kernel = functools.partial(
        _causal_grouped_kernel, block_pos=block_pos, hpg=hpg,
        block_k=block_k, n_k=n_k, causal=causal,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, 1, rows, d),
                         lambda bi, gi, qi, ki, lens: (bi, gi, qi, 0, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, gi, qi, ki, lens: (bi, gi, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, gi, qi, ki, lens: (bi, gi, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, 1, rows, d),
                               lambda bi, gi, qi, ki, lens: (bi, gi, qi, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((rows, block_k), jnp.float32),  # m (lane-broadcast max)
            pltpu.VMEM((rows, block_k), jnp.float32),  # l (lane-broadcast sum)
            pltpu.VMEM((rows, d), jnp.float32),        # acc
        ],
    )
    fn = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, g, nq, rows, d), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )
    out = fn(jnp.asarray(lengths, jnp.int32), q5, k, v)
    out = out.reshape(b, g, nq, block_pos, hpg, d).transpose(0, 2, 3, 1, 4, 5)
    return out.reshape(b, s, n, d)


def attention_bsnd(q, k, v, lengths, causal: bool = True,
                   force: Optional[str] = None, interpret: bool = False):
    """Layout-native dispatch: q [B,S,N,D], k/v [B,S,G,D] unrepeated.

    On TPU the causal block-skipping kernel runs directly on the projection
    layout; elsewhere (or when the shape has no valid query block) the tensors
    transpose to head-major and take the :func:`attention` dispatcher."""
    b, s, n, d = q.shape
    g = k.shape[2]
    backend = force
    bp = pick_block_pos(s, n // g)
    if backend is None:
        platform = jax.default_backend()
        if _PALLAS_OK and platform == "tpu" and bp is not None:
            backend = "causal"
    if backend == "causal":
        return causal_grouped_attention(q, k, v, lengths, causal,
                                        block_pos=bp, interpret=interpret)
    out = attention(
        jnp.swapaxes(q, 1, 2), jnp.swapaxes(k, 1, 2), jnp.swapaxes(v, 1, 2),
        lengths, causal, force=force, interpret=interpret,
    )
    return jnp.swapaxes(out, 1, 2)


def cache_extend_attention(q, kp, vp, kt, vt, bias,
                           kp_scale=None, vp_scale=None):
    """Attention for a SUFFIX-EXTENSION prefill over a prefilled prefix KV
    cache (the engine's prefix-reuse path, runtime/engine.score_prefixed):
    the suffix's queries attend jointly over the big read-only prefix block
    and the suffix's own K/V.

    q: [B, S, N, D] suffix queries (projection layout, heads unrepeated in
    K/V); kp/vp: [B, T, G, D] prefix cache block; kt/vt: [B, S, G, D] the
    suffix's own K/V; bias: fp32 additive [B, N_or_1, S, T+S] built from the
    cache's slot->position mapping (causal + padding + ALiBi — the caller
    owns position semantics, exactly like the dense trunk path).

    ``kp_scale``/``vp_scale`` ([B, T, G] fp32, or None): when the prefix
    cache is int8-quantized (models/decoder.KVCache with per-head scales,
    ops/quant.quantize_kv) the dequant happens HERE, right before the
    joint softmax, so the int8 block streams from HBM at half the bf16
    bandwidth and only the current extension's working set ever exists in
    the compute dtype.  The suffix's own kt/vt are always exact (they were
    just projected); quantization applies only to the stored prefix.

    ONE joint softmax over the concatenated key axis, NOT the two-block
    split-softmax decode trick (models/decoder.grouped_attention_two_block):
    the split perturbs the summation grouping, and this path's contract is
    that a fused prefix+suffix score is numerically indistinguishable from
    the unfused full-prompt prefill — masked prefix pad slots contribute
    exact zeros (exp(NEG_INF - max) underflows to 0.0), so the joint softmax
    reproduces the full-sequence dense attention bit for bit.  A Pallas
    two-block kernel is deliberately NOT attempted: the sweep's suffix
    blocks are <=64 tokens, so the score tensor here is [B, N, S_suf, T+S]
    — tiny next to the prompt forward's S×S — and the r2 outcome table
    (this module's flash kernel losing ~12% in situ as an opaque fusion
    boundary) says XLA dense wins at these shapes anyway."""
    from . import quant

    if kp_scale is not None:
        kp = quant.dequantize_kv(kp, kp_scale, kt.dtype)
    elif kp.dtype != kt.dtype:
        kp = kp.astype(kt.dtype)
    if vp_scale is not None:
        vp = quant.dequantize_kv(vp, vp_scale, vt.dtype)
    elif vp.dtype != vt.dtype:
        vp = vp.astype(vt.dtype)
    k = jnp.concatenate([kp, kt], axis=1)
    v = jnp.concatenate([vp, vt], axis=1)
    b, t, g, d = k.shape
    n = q.shape[2]
    if g != n:  # MQA/GQA: repeat K/V to full heads, like the dense trunk
        k = jnp.broadcast_to(k[:, :, :, None, :], (b, t, g, n // g, d)
                             ).reshape(b, t, n, d)
        v = jnp.broadcast_to(v[:, :, :, None, :], (b, t, g, n // g, d)
                             ).reshape(b, t, n, d)
    scores = jnp.einsum("bsnd,btnd->bnst", q, k) / jnp.sqrt(d).astype(q.dtype)
    scores = scores.astype(jnp.float32) + bias
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bnst,btnd->bsnd", probs, v)


def attention(q, k, v, lengths, causal: bool = True, force: Optional[str] = None,
              interpret: bool = False):
    """Dispatch: 'pallas' on TPU, dense XLA elsewhere.  ``force`` overrides.

    ``k``/``v`` may be *grouped* — ``[B, G, S, D]`` with ``G`` dividing the
    query head count (MQA/GQA, K/V not yet repeated).  The grouped Pallas
    kernel consumes them directly; the dense path repeats them to full heads.
    """
    b, n, s, d = q.shape
    backend = force
    if backend is None:
        # works under tracing too (committed device platform is unavailable
        # on tracers; the default backend is what jit will compile for)
        platform = jax.default_backend()
        backend = "pallas" if (_PALLAS_OK and platform == "tpu") else "dense"
        if backend == "pallas":
            if s <= GROUPED_MAX_SEQ and s % 16 == 0:
                backend = "grouped"    # VPU sublane tiling needs S%16 (all
                # runtime/batching buckets qualify; raw lengths may not)
            else:
                blk = pick_block(s, DEFAULT_BLOCK_Q)
                if blk is None or blk < 32:
                    # no valid block, or only a tiny one: block 16 crashed
                    # the TPU worker (observed at S=432) — fall back to XLA.
                    # Dense can be memory-hungry at long S, but a loud OOM
                    # beats a worker crash.  Auto-selected only: an explicit
                    # force='pallas' bypasses this guard (and raises only
                    # when no power-of-two block exists at all).
                    backend = "dense"
    if backend == "grouped":
        return grouped_attention(q, k, v, lengths, causal, interpret=interpret)
    if k.shape[1] != n:                    # grouped K/V on a non-grouped path
        reps = n // k.shape[1]
        k = jnp.repeat(k, reps, axis=1)
        v = jnp.repeat(v, reps, axis=1)
    if backend == "pallas":
        return flash_attention(q, k, v, lengths, causal, interpret=interpret)
    return _dense_attention(q, k, v, lengths, causal)
