#!/usr/bin/env bash
# certify_install.sh — prove the package installs and serves from a FRESH
# virtualenv with NO network access, then smoke the tier-1 gate.
#
# What this certifies (the failure modes it exists to catch):
#   * packaging drift — a module missing from the wheel/editable install
#     that the in-repo test run never notices because the repo root is on
#     sys.path anyway;
#   * hidden network dependencies — `--no-index` makes any build-time or
#     install-time fetch a hard failure (the image bakes in the runtime
#     deps; an install that needs PyPI is broken here by definition);
#   * console entry-point rot (`llm-interp-tpu` must resolve and answer
#     `--help` from the venv, not from the checkout).
#
# Usage:
#   scripts/certify_install.sh                 # fast smoke (-m faults)
#   CERTIFY_SMOKE_MARKER='not slow' \
#       scripts/certify_install.sh             # the full tier-1 gate
#   CERTIFY_VENV=/tmp/certify-venv \
#       scripts/certify_install.sh             # reuse/inspect the venv
set -euo pipefail

REPO="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
VENV="${CERTIFY_VENV:-"$(mktemp -d)/certify-venv"}"
# a fast in-gate marker by default; 'not slow' runs the whole tier-1 gate
SMOKE_MARKER="${CERTIFY_SMOKE_MARKER:-faults}"

echo "== certify_install: fresh venv at $VENV"
# --system-site-packages: the runtime deps (jax, numpy, ...) resolve from
# the image, OFFLINE — the venv only isolates the package install itself
python3 -m venv --system-site-packages "$VENV"
# shellcheck source=/dev/null
. "$VENV/bin/activate"

echo "== certify_install: offline editable install (--no-index)"
pip install --quiet --no-index --no-build-isolation --no-deps -e "$REPO"

echo "== certify_install: import + console entry point"
python - <<'PYEOF'
import llm_interpretation_replication_tpu as pkg
from llm_interpretation_replication_tpu.serve import EnginePool  # noqa: F401
print(f"import ok: {pkg.__name__}")
PYEOF
llm-interp-tpu --help >/dev/null
echo "console entry point ok"

echo "== certify_install: static-analysis gates from the venv"
# the three lint entry points a CI hook runs, executed from the fresh
# install: the repo gate (G01-G11 incl. the whole-tree thread model),
# the cross-artifact contracts layer, and the cheap changed-files mode
# (must exit 0 on a clean tree even when the diff is empty)
cd "$REPO"
python -m llm_interpretation_replication_tpu lint
python -m llm_interpretation_replication_tpu lint contracts
python -m llm_interpretation_replication_tpu lint --diff
python -m llm_interpretation_replication_tpu lint contracts --diff

echo "== certify_install: sharded sweep-shell dryrun"
# ROADMAP item 5 remainder: a tiny run_model_perturbation_sweep on a
# dp×tp virtual mesh with a resume-skip assertion — must print the
# 'dryrun sweep OK' line (fresh process: the dryrun pins the platform
# and virtual device count before any JAX backend initializes)
cd "$REPO"
python __graft_entry__.py dryrun-sweep 4 | tee /dev/stderr \
    | grep -q "dryrun sweep OK"

echo "== certify_install: tier-1 smoke (-m '$SMOKE_MARKER')"
cd "$REPO/tests"
JAX_PLATFORMS=cpu python -m pytest -q -m "$SMOKE_MARKER" \
    -p no:cacheprovider

echo "== certify_install: PASS (venv: $VENV)"
